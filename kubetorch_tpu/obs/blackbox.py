"""The read side of the flight recorder: verify a spool, reconstruct the
dead process's last interval, render the ``kt blackbox`` report.

Verification is two-layer: each segment's per-record hash chain (blake2b
over previous hash + canonical JSON, restarting at the segment boundary)
proves no record was altered or truncated, and the spool-wide ``seq``
continuity proves no retained record is missing — rotation only ever
deletes whole segments from the OLD end, so surviving records must be
strictly consecutive.

Reconstruction folds the delta-encoded metric payloads forward
(:func:`recorder.apply_delta`) into the process's final snapshot, keeps
the snapshot one record earlier for the metric diff, and pulls the final
record's in-flight spans — the work the process was doing when it died —
for the waterfall.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from .recorder import SEGMENT_GLOB, apply_delta, chain_hash

# how many completed spans reconstruction keeps (newest win): enough for
# any one trace's waterfall without holding a long run's whole history
_SPAN_KEEP = 512


def spool_dirs(root: str) -> List[Path]:
    """Per-process spool directories under a spool root, sorted by name."""
    base = Path(root)
    if not base.is_dir():
        return []
    return sorted(p for p in base.iterdir()
                  if p.is_dir() and list(p.glob(SEGMENT_GLOB)))


def spool_identity(spool_dir) -> Tuple[str, Optional[int]]:
    """``(process name, pid)`` parsed from a spool directory's
    ``<name>-<pid>`` naming; pid None when the suffix isn't numeric."""
    stem = Path(spool_dir).name
    name, _, pid = stem.rpartition("-")
    try:
        return (name or stem), int(pid)
    except ValueError:
        return stem, None


def pid_alive(pid: int) -> bool:
    """Signal-0 liveness probe (EPERM counts as alive)."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def read_spool(spool_dir) -> Dict[str, Any]:
    """Parse and verify every committed segment of one spool. Returns
    ``{"dir", "records", "errors", "segments", "torn_tail"}`` —
    ``errors`` holds one human line per broken chain link, truncated
    record, or seq gap, and is EMPTY for a hash-clean spool (what the
    soak invariant asserts). The writer appends one kernel-buffered
    line per record, so a SIGKILL can tear exactly one place: the final
    line of the final segment. That tear is the expected crash artifact
    — reported as ``torn_tail``, not an error; every earlier record was
    committed whole."""
    spool_dir = Path(spool_dir)
    records: List[Dict[str, Any]] = []
    errors: List[str] = []
    torn_tail = False
    segments = sorted(spool_dir.glob(SEGMENT_GLOB))
    if not segments:
        errors.append(f"{spool_dir}: no committed segments")
    prev_seq: Optional[int] = None
    for seg_i, seg in enumerate(segments):
        prev_hash = ""
        try:
            lines = seg.read_text("utf-8").splitlines()
        except OSError as exc:
            errors.append(f"{seg.name}: unreadable ({exc})")
            continue
        for lineno, line in enumerate(lines, start=1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if seg_i == len(segments) - 1 and lineno == len(lines):
                    torn_tail = True
                else:
                    errors.append(f"{seg.name}:{lineno}: truncated or "
                                  f"corrupt record")
                break
            if rec.get("h") != chain_hash(prev_hash, rec):
                errors.append(f"{seg.name}:{lineno}: hash chain broken")
                break
            prev_hash = rec["h"]
            seq = rec.get("seq")
            if prev_seq is not None and seq != prev_seq + 1:
                errors.append(f"{seg.name}:{lineno}: seq {seq} follows "
                              f"{prev_seq} (records missing)")
            if isinstance(seq, int):
                prev_seq = seq
            records.append(rec)
    return {"dir": str(spool_dir), "records": records, "errors": errors,
            "segments": len(segments), "torn_tail": torn_tail}


def verify_spool(spool_dir) -> List[str]:
    """Just the error lines — the soak invariant's yes/no input."""
    return read_spool(spool_dir)["errors"]


def reconstruct(spool_dir) -> Dict[str, Any]:
    """Fold a spool into the dead process's story: its final metric
    snapshot, the snapshot one record earlier (for the last-interval
    diff), its in-flight spans at the last record, and the most recent
    completed spans."""
    data = read_spool(spool_dir)
    records = data["records"]
    running: Dict[str, Dict] = {}
    previous: Dict[str, Dict] = {}
    span_by_id: Dict[Tuple[str, str], Dict] = {}
    for i, rec in enumerate(records):
        if i == len(records) - 1:
            previous = json.loads(json.dumps(running))
        running = apply_delta(running, rec.get("metrics", {}),
                              full=bool(rec.get("full")))
        for span_dict in rec.get("spans", []):
            key = (span_dict.get("trace_id", ""),
                   span_dict.get("span_id", ""))
            span_by_id[key] = span_dict
        while len(span_by_id) > _SPAN_KEEP:
            span_by_id.pop(next(iter(span_by_id)))
    last = records[-1] if records else {}
    name, pid = spool_identity(spool_dir)
    return {
        "dir": data["dir"],
        "name": name,
        "pid": pid,
        "errors": data["errors"],
        "segments": data["segments"],
        "torn_tail": data["torn_tail"],
        "records": len(records),
        "first_ts": records[0].get("ts") if records else None,
        "last_ts": last.get("ts"),
        "last_kind": last.get("kind"),
        "note": last.get("note"),
        "metrics": running,
        "metrics_prev": previous,
        "spans": list(span_by_id.values()),
        "inflight": last.get("inflight", []),
    }


def _flatten(snapshot: Dict[str, Dict]) -> Dict[str, Any]:
    """One scalar per exposed series line: counters/gauges as-is,
    histograms as their ``_count``/``_sum``."""
    flat: Dict[str, Any] = {}
    for series, entry in snapshot.items():
        labelnames = entry.get("labels", [])
        for lkey, lval in entry.get("values", {}).items():
            labelvalues = lkey.split("\x1f") if lkey else []
            suffix = ""
            if labelnames and labelvalues:
                pairs = ",".join(f'{ln}="{lv}"' for ln, lv
                                 in zip(labelnames, labelvalues))
                suffix = "{" + pairs + "}"
            if isinstance(lval, dict):
                flat[f"{series}_count{suffix}"] = lval.get("count", 0)
                flat[f"{series}_sum{suffix}"] = round(lval.get("sum", 0.0), 6)
            else:
                flat[f"{series}{suffix}"] = lval
    return flat


def metric_diff(prev: Dict[str, Dict], cur: Dict[str, Dict]) -> List[str]:
    """Human lines for every series whose value changed between two
    snapshots — the black box's 'what moved in the last interval'."""
    before, after = _flatten(prev), _flatten(cur)
    out = []
    for series_line in sorted(set(before) | set(after)):
        old = before.get(series_line, 0)
        new = after.get(series_line, 0)
        if old == new:
            continue
        try:
            step = round(new - old, 6)
            arrow = f"{old} -> {new}  ({'+' if step >= 0 else ''}{step})"
        except TypeError:
            arrow = f"{old} -> {new}"
        out.append(f"{series_line}  {arrow}")
    return out


def _death_waterfall(recon: Dict[str, Any], width: int) -> str:
    """Waterfall of the dead process's last trace: the in-flight spans
    (extended to the moment of the final record and marked) plus the
    completed spans of the same trace(s); falls back to the newest
    completed trace when nothing was in flight."""
    last_ts = recon.get("last_ts") or 0.0
    picked: List[Dict] = []
    for span_dict in recon.get("inflight", []):
        open_span = dict(span_dict)
        start = open_span.get("start", last_ts)
        if open_span.get("end") is None:
            open_span["end"] = max(last_ts, start)
        open_span["attrs"] = dict(open_span.get("attrs", {}), inflight=True)
        picked.append(open_span)
    traces = {s.get("trace_id") for s in picked}
    completed = recon.get("spans", [])
    if traces:
        picked += [s for s in completed if s.get("trace_id") in traces]
    elif completed:
        newest = max(completed, key=lambda s: s.get("start", 0.0))
        picked = [s for s in completed
                  if s.get("trace_id") == newest.get("trace_id")]
    if not picked:
        return "(no spans recorded)"
    return telemetry.format_waterfall(picked, width=width)


def format_blackbox(recon: Dict[str, Any], width: int = 40) -> str:
    """The ``kt blackbox`` report for one reconstructed spool."""
    pid = recon.get("pid")
    state = "unknown"
    if pid is not None:
        state = "STILL RUNNING" if pid_alive(pid) else "dead"
    lines = [f"black box: {recon['dir']}",
             f"process: {recon['name']} (pid {pid}, {state})"]
    for err in recon["errors"]:
        lines.append(f"  ! {err}")
    last_ts = recon.get("last_ts")
    when = (time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(last_ts))
            if last_ts else "never")
    lines.append(f"records: {recon['records']} across "
                 f"{recon['segments']} segment(s); last record "
                 f"kind={recon.get('last_kind')} at {when}")
    if recon.get("torn_tail"):
        lines.append("  (final line torn mid-append — the process died "
                     "writing it; every shown record committed whole)")
    note = recon.get("note")
    if note:
        detail = " ".join(f"{k}={v}" for k, v in sorted(note.items()))
        lines.append(f"final note: {detail}")
    first_ts = recon.get("first_ts")
    if first_ts and last_ts:
        lines.append(f"history covers {last_ts - first_ts:.1f}s")
    lines.append("")
    lines.append(f"in-flight at last record "
                 f"({len(recon.get('inflight', []))} span(s)):")
    lines.append(_death_waterfall(recon, width))
    lines.append("")
    diff = metric_diff(recon.get("metrics_prev", {}),
                       recon.get("metrics", {}))
    lines.append(f"metric movement over the final interval "
                 f"({len(diff)} series):")
    if diff:
        lines.extend(f"  {d}" for d in diff)
    else:
        lines.append("  (no movement)")
    return "\n".join(lines)
