"""The fleet aggregator: merge per-pod telemetry into ``kt_fleet_*``
rollups and compute multi-window SLO burn rates.

Transport-free on purpose: callers (the controller's scrape loop, the
``--obs`` bench, tests) fetch ``/metrics`` text however they like and
feed it to :meth:`FleetAggregator.ingest`; :meth:`FleetAggregator.tick`
closes a scrape round. That keeps the merge math — the part with real
failure modes — importable and testable without an event loop.

Failure modes handled here:

- **mismatched bucket sets** — pods on different builds expose different
  edges; :func:`merge_histograms` merges onto the UNION of edges, reading
  each pod's cumulative count at the largest of its own edges ≤ the union
  edge (cumulative histograms are step functions; flooring is the
  conservative reading) and taking ``+Inf`` as the pod's total;
- **counter resets** — a scraped cumulative value that went DOWN means
  the pod restarted: :class:`CounterEpochs` opens a new epoch and counts
  the fresh value as the delta, never producing a negative;
- **dead pods** — an unreachable pod contributes its last corrected
  totals (history survives) and is reported ``down``.

Burn rates follow the SRE multi-window recipe: over each window, the
fraction of stage observations slower than the latency SLO, divided by
the error budget ``1 - target``. 1.0 burns the budget exactly at the
sustainable rate; the classic fast-window page threshold is 14.4.
Crossing the threshold emits a typed, rehydratable
:class:`~kubetorch_tpu.exceptions.SloBurnAlert`.
"""

from __future__ import annotations

import re
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from .. import telemetry
from ..exceptions import SloBurnAlert, package_exception

_STAGE_LABEL_RE = re.compile(r'kt_stage_seconds_bucket\{[^}]*stage="([^"]+)"')
_BUILD_INFO_RE = re.compile(r'^kt_build_info\{([^}]*)\}', re.MULTILINE)
_LABEL_PAIR_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

_SERIES_SEP = "\x1f"


def _edge(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def merge_histograms(
        per_pod: Dict[str, Dict[str, float]]) -> Dict[str, float]:
    """Merge per-pod cumulative bucket maps (``le string → count``, the
    ``_parse_histogram_buckets`` shape) onto the union of bucket edges.
    See the module docstring for the mismatched-edge semantics."""
    edge_str: Dict[float, str] = {}
    for buckets in per_pod.values():
        for le in buckets:
            edge_str.setdefault(_edge(le), le)
    merged: Dict[str, float] = {}
    for union_edge in sorted(edge_str):
        total = 0.0
        for buckets in per_pod.values():
            floor: Optional[Tuple[float, float]] = None
            for le, count in buckets.items():
                fe = _edge(le)
                if fe <= union_edge and (floor is None or fe > floor[0]):
                    floor = (fe, count)
            if floor is not None:
                total += floor[1]
        merged[edge_str[union_edge]] = total
    return merged


class CounterEpochs:
    """Reset-aware accumulator for one pod's cumulative series.

    ``update(key, raw)`` folds a freshly-scraped cumulative bucket map
    into a corrected running total: normally the per-edge delta since the
    last scrape (clamped at 0 so a bucket-set change can't go negative),
    but when the series' total (``+Inf``) DECREASED the pod restarted —
    a new epoch begins and the raw values themselves are the delta.
    ``resets`` counts epochs opened."""

    def __init__(self) -> None:
        self._last: Dict[str, Dict[str, float]] = {}
        self._corrected: Dict[str, Dict[str, float]] = {}
        self.resets = 0

    @staticmethod
    def _total(buckets: Dict[str, float]) -> float:
        return buckets.get("+Inf", max(buckets.values(), default=0.0))

    def update(self, key: str, raw: Dict[str, float]) -> Dict[str, float]:
        last = self._last.get(key)
        corrected = self._corrected.setdefault(key, {})
        reset = last is not None and self._total(raw) < self._total(last)
        if reset:
            self.resets += 1
        for le, count in raw.items():
            if last is None or reset:
                delta = count
            else:
                delta = max(0.0, count - last.get(le, 0.0))
            corrected[le] = corrected.get(le, 0.0) + delta
        self._last[key] = dict(raw)
        return dict(corrected)

    def corrected(self, key: str) -> Dict[str, float]:
        return dict(self._corrected.get(key, {}))

    def keys(self) -> List[str]:
        return list(self._corrected)


class FleetAggregator:
    """Controller-side rollup of per-pod ``/metrics`` scrapes.

    One :meth:`ingest` per pod per round, one :meth:`tick` to close the
    round (returns the :class:`SloBurnAlert` records it raised). The
    merged rollups render from a PRIVATE registry (:meth:`render`) —
    re-aggregated scrapes observed into the global registry would
    double-count the moment the controller scrapes itself.
    """

    def __init__(self, slo_s: float = 1.0, target: float = 0.99,
                 burn_threshold: float = 14.4,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 max_alerts: int = 64):
        self.slo_s = float(slo_s)
        self.target = min(float(target), 1.0 - 1e-9)
        self.burn_threshold = float(burn_threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self._epochs: Dict[str, CounterEpochs] = {}
        self._pods: Dict[str, Dict[str, Any]] = {}
        self._window: Deque[
            Tuple[float, Dict[str, Tuple[float, float]]]] = deque()
        self.alerts: Deque[SloBurnAlert] = deque(maxlen=max_alerts)
        self._last_alert: Dict[Tuple[str, str], float] = {}
        self._rollup = telemetry.MetricsRegistry()

    @classmethod
    def from_config(cls) -> "FleetAggregator":
        from ..config import config
        cfg = config()
        return cls(slo_s=cfg.obs_slo_s, target=cfg.obs_slo_target,
                   burn_threshold=cfg.obs_burn_threshold,
                   fast_window_s=cfg.obs_slo_fast_s,
                   slow_window_s=cfg.obs_slo_slow_s)

    # -- scrape round --------------------------------------------------

    def ingest(self, pod: str, text: Optional[str],
               now: Optional[float] = None) -> None:
        """Fold one pod's ``/metrics`` exposition text into the fleet
        state; ``text=None`` marks the pod unreachable this round."""
        family = telemetry.fleet_metrics()
        now = time.time() if now is None else now
        state = self._pods.setdefault(
            pod, {"up": False, "last_ts": 0.0, "build": {}})
        if not text:
            state["up"] = False
            family["scrapes"].inc(outcome="error")
            return
        from ..controller.app import _parse_histogram_buckets
        epochs = self._epochs.setdefault(pod, CounterEpochs())
        resets_before = epochs.resets
        for stage in sorted(set(_STAGE_LABEL_RE.findall(text))):
            raw = _parse_histogram_buckets(
                text, "kt_stage_seconds", f'stage="{stage}"')
            if raw:
                epochs.update(f"stage{_SERIES_SEP}{stage}", raw)
        if epochs.resets > resets_before:
            family["resets"].inc(epochs.resets - resets_before)
        build = _BUILD_INFO_RE.search(text)
        if build:
            state["build"] = dict(_LABEL_PAIR_RE.findall(build.group(1)))
        state["up"] = True
        state["last_ts"] = now
        family["scrapes"].inc(outcome="ok")

    def tick(self, now: Optional[float] = None) -> List[SloBurnAlert]:
        """Close a scrape round: sample the merged good/total counts into
        the burn windows, publish gauges + rollups, and return any alerts
        this round raised."""
        now = time.time() if now is None else now
        family = telemetry.fleet_metrics()
        up = sum(1 for s in self._pods.values() if s["up"])
        family["pods"].set(up, state="up")
        family["pods"].set(len(self._pods) - up, state="down")

        merged = self.merged_stages()
        sample: Dict[str, Tuple[float, float]] = {}
        for stage, buckets in merged.items():
            total = buckets.get("+Inf", max(buckets.values(), default=0.0))
            sample[stage] = (self._good_count(buckets, total), total)
        self._window.append((now, sample))
        horizon = now - self.slow_window_s - 1.0
        while len(self._window) > 1 and self._window[0][0] < horizon:
            self._window.popleft()

        raised: List[SloBurnAlert] = []
        for stage in sorted(sample):
            for window, length in (("fast", self.fast_window_s),
                                   ("slow", self.slow_window_s)):
                burn = self._burn(stage, length, now)
                family["slo_burn"].set(burn, stage=stage, window=window)
                if burn <= self.burn_threshold:
                    continue
                last = self._last_alert.get((stage, window), float("-inf"))
                if now - last < length:
                    continue     # one page per ongoing breach per window
                alert = SloBurnAlert(
                    f"stage {stage!r} burns error budget at {burn:.1f}x "
                    f"the sustainable rate over the {window} window "
                    f"(threshold {self.burn_threshold:g}x, SLO "
                    f"{self.slo_s:g}s at {self.target:.3%})",
                    stage=stage, window=window, burn_rate=round(burn, 3),
                    threshold=self.burn_threshold, slo_s=self.slo_s,
                    target=self.target, at=now)
                self.alerts.append(alert)
                raised.append(alert)
                self._last_alert[(stage, window)] = now
                family["alerts"].inc(stage=stage, window=window)
        self._update_rollup(merged)
        return raised

    # -- merge + burn math ---------------------------------------------

    def merged_stages(self) -> Dict[str, Dict[str, float]]:
        """Fleet-merged corrected cumulative buckets per stage."""
        per_stage: Dict[str, Dict[str, Dict[str, float]]] = {}
        for pod, epochs in self._epochs.items():
            for key in epochs.keys():
                kind, _, stage = key.partition(_SERIES_SEP)
                if kind == "stage":
                    per_stage.setdefault(stage, {})[pod] = \
                        epochs.corrected(key)
        return {stage: merge_histograms(pods)
                for stage, pods in per_stage.items()}

    def _good_count(self, buckets: Dict[str, float], total: float) -> float:
        """Observations within the latency SLO: the cumulative count at
        the smallest edge ≥ ``slo_s``. With no finite edge that high the
        histogram can't distinguish — read as all-good rather than
        inventing badness the data can't support."""
        candidates = [(_edge(le), count) for le, count in buckets.items()
                      if _edge(le) != float("inf")
                      and _edge(le) >= self.slo_s]
        if not candidates:
            return total
        return min(candidates)[1]

    def _burn(self, stage: str, window_s: float, now: float) -> float:
        """Burn rate over one window: the bad fraction of observations in
        the window divided by the error budget. The baseline is the
        newest sample at or before the window start; with history shorter
        than the window the oldest sample stands in (the burn since
        scraping began)."""
        if not self._window:
            return 0.0
        current = self._window[-1][1].get(stage, (0.0, 0.0))
        baseline: Optional[Tuple[float, float]] = None
        for ts, sample in self._window:
            if ts <= now - window_s:
                baseline = sample.get(stage, (0.0, 0.0))
            else:
                break
        if baseline is None:
            baseline = self._window[0][1].get(stage, (0.0, 0.0))
        d_total = current[1] - baseline[1]
        if d_total <= 0:
            return 0.0
        d_bad = (current[1] - current[0]) - (baseline[1] - baseline[0])
        bad_frac = min(max(d_bad / d_total, 0.0), 1.0)
        return bad_frac / (1.0 - self.target)

    def quantile(self, stage: str, q: float) -> Optional[float]:
        """Merged fleet quantile for one stage (None without data)."""
        from ..controller.app import _quantile_from_buckets
        buckets = self.merged_stages().get(stage)
        if not buckets:
            return None
        return _quantile_from_buckets(buckets, q)

    # -- surfaces ------------------------------------------------------

    def _update_rollup(self, merged: Dict[str, Dict[str, float]]) -> None:
        bucket_gauge = self._rollup.gauge(
            "kt_fleet_stage_seconds_bucket",
            "Fleet-merged cumulative kt_stage_seconds buckets "
            "(counter-reset corrected; gauge because it is a "
            "re-aggregated scrape, not a process-local histogram)",
            labels=("stage", "le"))
        count_gauge = self._rollup.gauge(
            "kt_fleet_stage_seconds_count",
            "Fleet-merged kt_stage_seconds observation totals",
            labels=("stage",))
        quantile_gauge = self._rollup.gauge(
            "kt_fleet_stage_quantile_seconds",
            "Fleet-merged per-stage latency quantiles",
            labels=("stage", "q"))
        from ..controller.app import _quantile_from_buckets
        for stage, buckets in merged.items():
            for le, count in buckets.items():
                bucket_gauge.set(count, stage=stage, le=le)
            count_gauge.set(
                buckets.get("+Inf", max(buckets.values(), default=0.0)),
                stage=stage)
            for q in (0.5, 0.99):
                value = _quantile_from_buckets(buckets, q)
                if value is not None:
                    quantile_gauge.set(value, stage=stage, q=q)

    def render(self) -> str:
        """Exposition text of the merged rollups — appended to the
        controller's ``/metrics`` after the global registry."""
        return self._rollup.render()

    def status(self) -> Dict[str, Any]:
        """The ``/fleet/status`` body ``kt obs top`` renders."""
        stages: Dict[str, Any] = {}
        merged = self.merged_stages()
        latest = self._window[-1][1] if self._window else {}
        # anchor at the last sample's clock, not wall time — ticks may run
        # on an injected timeline (tests, replayed scrapes)
        burn_now = self._window[-1][0] if self._window else time.time()
        for stage, buckets in sorted(merged.items()):
            good, total = latest.get(stage, (0.0, 0.0))
            stages[stage] = {
                "count": total,
                "p50": self.quantile(stage, 0.5),
                "p99": self.quantile(stage, 0.99),
                "bad_frac": ((total - good) / total) if total else 0.0,
                "burn": {
                    "fast": self._burn(stage, self.fast_window_s, burn_now),
                    "slow": self._burn(stage, self.slow_window_s, burn_now),
                },
            }
        return {
            "slo": {"slo_s": self.slo_s, "target": self.target,
                    "burn_threshold": self.burn_threshold,
                    "fast_window_s": self.fast_window_s,
                    "slow_window_s": self.slow_window_s},
            "pods": {pod: dict(state)
                     for pod, state in sorted(self._pods.items())},
            "stages": stages,
            "alerts": [package_exception(a) for a in self.alerts],
        }
