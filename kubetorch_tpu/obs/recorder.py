"""The per-process flight recorder: always-on telemetry history with a
crash black box.

A background thread appends one record per interval to a local spool
directory (``<KT_OBS_SPOOL>/<name>-<pid>/segment-NNNNNN.jsonl``). Each
record carries a delta-encoded snapshot of the metrics registry, the
spans that completed since the previous record, and — crucially — the
spans still OPEN right now (:func:`telemetry.active_spans`): a SIGKILL
leaves the interesting span in flight, so every periodic record persists
the in-flight state, not just the final one. The loss window after a
hard kill is therefore one interval, never the whole history.

Durability and verifiability:

- every flush APPENDS one record line and pushes it to the kernel page
  cache — commit cost is O(one record), never O(segment), which is what
  keeps the perf gate's ``recorder_overhead`` ratio inside its <3%
  budget. PROCESS death (SIGKILL, OOM — the black box's threat model)
  loses nothing already appended; fsync happens at segment close and on
  event/final records, so MACHINE death costs at most the open
  segment's tail. A kill mid-append can tear only the very last line;
  the reader treats a torn final line of the final segment as the
  expected crash artifact (every earlier record was committed whole)
  and anything else as corruption;
- records are hash-chained per segment (blake2b over the previous hash +
  the record's canonical JSON), restarting at ``""`` on rotation so each
  retained segment verifies independently after older ones are deleted;
- ``seq`` increments across the whole spool, so the reader can prove no
  retained record is missing;
- spans are capped per record (``_SPAN_PER_RECORD_CAP`` newest win, the
  drop count stamped into the record) so a span storm inflates neither
  the flush nor the spool.

Boundedness: segments rotate at ``max_bytes/4`` and the spool deletes
oldest segments beyond ``max_bytes`` total or ``max_age_s`` old — the
soak's ``check_blackbox`` invariant and the perf gate's
``recorder_overhead`` stage hold this module to its budget.

Crash hooks: ``atexit`` always; SIGTERM/SIGINT only when the process had
no handler installed (the recorder never steals a server's shutdown
path); watchdog deaths arrive via :func:`note_death`.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import signal
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from ..data_store.durability import blake2b_bytes

RECORD_VERSION = 1
SEGMENT_GLOB = "segment-*.jsonl"

# finished-span dedup memory: larger than the trace ring's default
# capacity (2048), so a span evicted from this set has almost certainly
# left the ring too and cannot be re-recorded
_SPAN_DEDUP_CAP = 4096

# newest completed spans one record may carry: under a span storm the
# black box's value is the LAST interval, not a complete span archive —
# the overflow is counted into the record, never silently dropped. 128
# keeps the per-flush serialize+fsync cost well inside the <3% overhead
# budget the perf gate pins (recon keeps 512 across records anyway)
_SPAN_PER_RECORD_CAP = 128

# seconds between spool-cap sweeps (glob + stat of every segment): cap
# enforcement also runs on every rotation, so the sweep interval only
# bounds how stale the spool_bytes gauge can get
_CAPS_SWEEP_S = 2.0


def _canonical(record: Dict[str, Any]) -> bytes:
    return json.dumps(record, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def chain_hash(prev: str, record: Dict[str, Any]) -> str:
    """Hash-chain link for one spool record: blake2b over the previous
    record's hash plus this record's canonical JSON (minus its own
    ``h`` field). The chain restarts at ``""`` at every segment boundary
    so each segment stays independently verifiable after rotation has
    deleted its predecessors."""
    body = {k: v for k, v in record.items() if k != "h"}
    return blake2b_bytes(prev.encode("ascii") + _canonical(body))


def snapshot_delta(prev: Dict[str, Dict],
                   cur: Dict[str, Dict]) -> Dict[str, Dict]:
    """Changed-series-only encoding of ``cur`` relative to ``prev`` (both
    in the :meth:`MetricsRegistry.snapshot` shape). A series appears when
    any of its label combinations changed value or the series is new;
    histogram entries are replaced wholesale — their bucket lists are
    cumulative, so intra-entry diffing buys nothing."""
    delta: Dict[str, Dict] = {}
    for series, entry in cur.items():
        base = prev.get(series)
        if (base is None or base.get("kind") != entry.get("kind")
                or base.get("labels") != entry.get("labels")
                or base.get("le") != entry.get("le")):
            delta[series] = entry
            continue
        changed = {lkey: lval for lkey, lval in entry["values"].items()
                   if base["values"].get(lkey) != lval}
        if changed:
            slim = {field: fval for field, fval in entry.items()
                    if field != "values"}
            slim["values"] = changed
            delta[series] = slim
    return delta


def apply_delta(base: Dict[str, Dict], payload: Dict[str, Dict],
                full: bool = False) -> Dict[str, Dict]:
    """Fold one record's ``metrics`` payload into a running snapshot —
    the reader-side inverse of :func:`snapshot_delta`. Deep-copies via
    the JSON round trip the payload already survived, so the running
    state never aliases record internals."""
    copied = json.loads(json.dumps(payload))
    if full:
        return copied
    for series, entry in copied.items():
        have = base.get(series)
        if have is None or have.get("kind") != entry.get("kind"):
            base[series] = entry
            continue
        for field, fval in entry.items():
            if field != "values":
                have[field] = fval
        have.setdefault("values", {}).update(entry.get("values", {}))
    return base


class FlightRecorder:
    """One process's always-on telemetry history (see module docstring).

    ``start()`` writes a synchronous full snapshot before the thread even
    exists, so a process killed instants after boot still leaves a
    readable black box. ``flush()`` is safe from any thread (RLock) —
    the periodic thread, signal handlers, atexit, and watchdog hooks all
    funnel through it.
    """

    def __init__(self, spool_root: str, name: str = "proc",
                 interval_s: float = 1.0,
                 max_bytes: int = 8 * 1024 * 1024,
                 max_age_s: float = 3600.0,
                 registry: Optional[telemetry.MetricsRegistry] = None):
        safe = re.sub(r"[^A-Za-z0-9_.]+", "-", str(name)).strip("-") or "proc"
        self.dir = Path(spool_root) / f"{safe}-{os.getpid()}"
        self.name = safe
        self.interval_s = max(0.01, float(interval_s))
        self.max_bytes = max(64 * 1024, int(max_bytes))
        self.max_age_s = float(max_age_s)
        self.segment_bytes = max(16 * 1024, self.max_bytes // 4)
        self.registry = registry if registry is not None else telemetry.REGISTRY
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._seg_index = 0
        self._file: Optional[Any] = None
        self._seg_bytes = 0
        self._last_caps = 0.0
        self._prev_hash = ""
        self._prev_snapshot: Dict[str, Dict] = {}
        self._seen_spans: "OrderedDict[Tuple[str, str], None]" = OrderedDict()
        self._finalized = False
        self._prev_handlers: Dict[int, Any] = {}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "FlightRecorder":
        self.dir.mkdir(parents=True, exist_ok=True)
        self.flush()
        self._thread = threading.Thread(
            target=self._run, name="kt-flight-recorder", daemon=True)
        self._thread.start()
        atexit.register(self._atexit)
        self._install_signal_hooks()
        return self

    def stop(self, final: bool = True) -> None:
        """Orderly shutdown (tests, clean exits): stop the thread, then
        append the terminal record. Crash paths never get here — they go
        through the atexit/signal hooks or lose at most one interval."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final:
            self._finalize("stop")
        with self._lock:
            self._close_segment()
        try:
            atexit.unregister(self._atexit)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — forensics must never kill the host
                pass

    # -- record append -------------------------------------------------

    def flush(self, kind: str = "snapshot",
              note: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Append one record to the current segment and push it to the
        kernel. ``kind`` is ``snapshot`` (periodic), ``event``
        (out-of-band, e.g. a watchdog death), or ``final`` (terminal).

        Durability is tiered by what kills the process: the buffered
        write is flushed to the kernel page cache before this method
        returns, so PROCESS death (SIGKILL, OOM) loses nothing already
        appended — the black box's actual threat model. fsync (MACHINE
        death) happens at segment close and terminal records; a node
        crash costs at most the open segment's tail, and paying ~1ms of
        fsync per record bought nothing for the crash class the spool
        exists to survive."""
        with self._lock:
            now = time.time()
            cur = self.registry.snapshot()
            f = self._open_segment()
            full = self._seg_bytes == 0
            spans, dropped = self._drain_new_spans()
            record: Dict[str, Any] = {
                "v": RECORD_VERSION,
                "seq": self._seq,
                "ts": now,
                "kind": kind,
                "full": full,
                "metrics": (cur if full
                            else snapshot_delta(self._prev_snapshot, cur)),
                "spans": spans,
                "inflight": telemetry.active_spans(),
            }
            if dropped:
                record["dropped_spans"] = dropped
            if note:
                record["note"] = note
            # serialize the body ONCE: the chain hash covers these exact
            # canonical bytes, and the committed line is the same bytes
            # with the hash spliced in. The reader re-canonicalizes the
            # parsed record minus ``h`` — Python's JSON float/str round
            # trip is stable, so the bytes (and the hash) agree.
            body = _canonical(record)
            record["h"] = blake2b_bytes(
                self._prev_hash.encode("ascii") + body)
            line = body[:-1] + (',"h":"%s"}\n' % record["h"]).encode("ascii")
            f.write(line)
            f.flush()
            if kind != "snapshot":
                try:
                    os.fsync(f.fileno())
                except OSError:
                    pass
            self._seg_bytes += len(line)
            self._seq += 1
            self._prev_hash = record["h"]
            self._prev_snapshot = cur
            family = telemetry.obs_metrics()
            family["snapshots"].inc(kind=kind)
            rotated = self._seg_bytes >= self.segment_bytes
            if rotated:
                self._close_segment()
                self._seg_index += 1
                self._prev_hash = ""
                family["rotations"].inc()
            if rotated or now - self._last_caps >= _CAPS_SWEEP_S:
                self._last_caps = now
                family["spool_bytes"].set(self._enforce_caps(now))
            return record

    def _open_segment(self):
        if self._file is None:
            path = self.dir / f"segment-{self._seg_index:06d}.jsonl"
            self._file = open(path, "ab")
            self._seg_bytes = self._file.tell()
        return self._file

    def _close_segment(self) -> None:
        if self._file is not None:
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
            except OSError:
                pass
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
            self._seg_bytes = 0

    def note_event(self, event: str, **attrs: Any) -> None:
        """Append an out-of-band event record and commit immediately —
        the watchdog's death hook rides this, so a rank's demise is on
        disk even if the supervisor dies next. Never raises."""
        try:
            self.flush(kind="event", note={"event": event, **attrs})
        except Exception:  # noqa: BLE001
            pass

    def _drain_new_spans(self) -> Tuple[List[Dict], int]:
        """(newest completed spans since the last record, drop count).

        Drains from a bounded ring slice (2x the record cap): under a
        span storm the ring is already evicting silently, so scanning
        its full depth buys nothing but GIL time — the drop count is a
        floor, not an exact census."""
        fresh = []
        for span_dict in telemetry.RING.snapshot(
                limit=2 * _SPAN_PER_RECORD_CAP):
            dedup = (span_dict.get("trace_id", ""),
                     span_dict.get("span_id", ""))
            if dedup in self._seen_spans:
                continue
            self._seen_spans[dedup] = None
            fresh.append(span_dict)
        while len(self._seen_spans) > _SPAN_DEDUP_CAP:
            self._seen_spans.popitem(last=False)
        dropped = 0
        if len(fresh) > _SPAN_PER_RECORD_CAP:
            dropped = len(fresh) - _SPAN_PER_RECORD_CAP
            fresh = fresh[-_SPAN_PER_RECORD_CAP:]
        return fresh, dropped

    def _enforce_caps(self, now: float) -> int:
        """Delete oldest non-current segments beyond the size cap and any
        past the age cap; returns the spool's resulting byte size."""
        current = self.dir / f"segment-{self._seg_index:06d}.jsonl"
        sizes: "OrderedDict[Path, int]" = OrderedDict()
        for seg in sorted(self.dir.glob(SEGMENT_GLOB)):
            try:
                sizes[seg] = seg.stat().st_size
            except OSError:
                continue
        total = sum(sizes.values())
        for seg, size in sizes.items():
            if seg == current:
                continue
            try:
                expired = (now - seg.stat().st_mtime) > self.max_age_s
            except OSError:
                expired = True
            if total > self.max_bytes or expired:
                try:
                    seg.unlink()
                    total -= size
                except OSError:
                    pass
        return total

    # -- crash hooks ---------------------------------------------------

    def _finalize(self, reason: str, **attrs: Any) -> None:
        with self._lock:
            if self._finalized:
                return
            self._finalized = True
        self._stop.set()
        try:
            self.flush(kind="final", note={"reason": reason, **attrs})
        except Exception:  # noqa: BLE001 — last gasp is best-effort
            pass

    def _atexit(self) -> None:
        self._stop.set()
        self._finalize("atexit")

    def _install_signal_hooks(self) -> None:
        # Only from the main thread (signal.signal raises elsewhere), and
        # only where the process runs the DEFAULT handler — a server that
        # installed its own graceful-shutdown path keeps it; its atexit
        # still writes our final record.
        if threading.current_thread() is not threading.main_thread():
            return
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                if signal.getsignal(signum) == signal.SIG_DFL:
                    self._prev_handlers[signum] = signal.SIG_DFL
                    signal.signal(signum, self._on_signal)
            except (ValueError, OSError):
                continue

    def _on_signal(self, signum: int, frame: Any) -> None:
        self._finalize("signal", signum=int(signum))
        try:
            signal.signal(signum,
                          self._prev_handlers.get(signum, signal.SIG_DFL))
        except (ValueError, OSError):
            pass
        os.kill(os.getpid(), signum)


# -- process-wide singleton -------------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def maybe_start_recorder(name: str = "proc") -> Optional[FlightRecorder]:
    """Arm the process-wide recorder from config (``KT_OBS_SPOOL``).
    Idempotent; returns None — and costs nothing — when no spool is
    configured. Entry points (pod server, store server, rank workers)
    call this unconditionally at boot; the env decides."""
    global _RECORDER
    from ..config import config
    cfg = config()
    spool = getattr(cfg, "obs_spool", "")
    if not spool:
        return None
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder(
                spool, name=name,
                interval_s=cfg.obs_interval_s,
                max_bytes=cfg.obs_spool_max_bytes,
                max_age_s=cfg.obs_spool_max_age_s).start()
    return _RECORDER


def recorder() -> Optional[FlightRecorder]:
    """The armed process-wide recorder, or None."""
    return _RECORDER


def note_death(rank: int, cause: Optional[str],
               exitcode: Optional[int]) -> None:
    """Watchdog death hook: stamp a worker's demise into this process's
    spool with an immediate commit. No-op when the recorder is off."""
    rec = _RECORDER
    if rec is not None:
        rec.note_event("watchdog.death", rank=rank, cause=cause,
                       exitcode=exitcode)


def _reset_for_tests() -> None:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.stop(final=False)
        _RECORDER = None
