"""Trace recording for the policy lab (ROADMAP item 4).

The scheduling-policy simulator wants production-shaped workloads; this
module is the seam that captures them. Same house style as
``soak/schedule.py``: records are **op-indexed** (``op`` 0..n-1 in
recording order) with timestamps RELATIVE to the header's ``t0``, the
header carries an explicit ``seed`` plus the recording process's build
identity, and the file is canonical JSONL (sorted keys) — so a recorded
trace replays deterministically through a seeded simulator regardless of
machine speed, and two recordings of the same run diff cleanly.

File layout (``kt-trace-v1``): one header line, then one line per op::

    {"schema": "kt-trace-v1", "v": 1, "seed": 7, "t0": ..., "meta": {...},
     "build": {...}}
    {"op": 0, "t": 0.0131, "name": "stage.execute", "dur_s": 0.021, ...}

:class:`TraceRecorder` feeds from completed spans (hand it span dicts,
or let :meth:`drain_ring` pull the trace ring) and commits the whole
file durably on :meth:`close`. :class:`TraceReader` validates the schema
and op-index continuity, then hands back ops in recorded or replay
(time-sorted) order.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from .. import telemetry
from ..data_store.durability import durable_write_bytes

TRACE_SCHEMA = "kt-trace-v1"


class TraceRecorder:
    """Accumulate spans as op records; durably commit on close.

    The file appears atomically at :meth:`close` (tmp sibling + fsynced
    rename) — a reader never sees a half-written trace, and a recorder
    killed mid-run simply leaves no file (the flight-recorder spool is
    the crash-forensics surface; this one is the curated dataset)."""

    def __init__(self, path, seed: int = 0,
                 meta: Optional[Dict[str, Any]] = None,
                 t0: Optional[float] = None):
        self.path = Path(path)
        self.t0 = float(t0) if t0 is not None else time.time()
        self.header: Dict[str, Any] = {
            "schema": TRACE_SCHEMA,
            "v": 1,
            "seed": int(seed),
            "t0": self.t0,
            "meta": dict(meta or {}),
            "build": dict(telemetry.build_info()),
        }
        self._ops: List[Dict[str, Any]] = []
        self._seen: Set[Tuple[str, str]] = set()
        self._closed = False

    def __len__(self) -> int:
        return len(self._ops)

    def record_span(self, span_dict: Dict[str, Any]) -> Optional[int]:
        """Append one completed span as an op record; returns its op
        index, or None when this ``(trace_id, span_id)`` was already
        recorded (re-shipped prefixes dedup away, same as the ring)."""
        key = (str(span_dict.get("trace_id", "")),
               str(span_dict.get("span_id", "")))
        if key in self._seen or self._closed:
            return None
        self._seen.add(key)
        start = float(span_dict.get("start", self.t0))
        end = span_dict.get("end")
        op = {
            "op": len(self._ops),
            "t": round(start - self.t0, 9),
            "name": span_dict.get("name", ""),
            "dur_s": (round(float(end) - start, 9)
                      if isinstance(end, (int, float)) else None),
            "status": span_dict.get("status", "ok"),
            "trace_id": key[0],
            "span_id": key[1],
            "parent_id": span_dict.get("parent_id"),
            "attrs": dict(span_dict.get("attrs", {})),
        }
        self._ops.append(op)
        return op["op"]

    def record_spans(self, spans: Iterable[Dict[str, Any]]) -> int:
        return sum(1 for s in spans if self.record_span(s) is not None)

    def drain_ring(self, limit: Optional[int] = None) -> int:
        """Record every completed span currently in the trace ring that
        this recorder hasn't seen yet; returns how many were new."""
        return self.record_spans(telemetry.RING.snapshot(limit=limit))

    def close(self) -> Path:
        if not self._closed:
            lines = [json.dumps(self.header, sort_keys=True,
                                separators=(",", ":"))]
            lines += [json.dumps(op, sort_keys=True, separators=(",", ":"))
                      for op in self._ops]
            durable_write_bytes(
                self.path, ("\n".join(lines) + "\n").encode("utf-8"))
            self._closed = True
        return self.path

    def __enter__(self) -> "TraceRecorder":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class TraceReader:
    """Parse + validate one recorded trace file.

    Raises ``ValueError`` on a wrong/missing schema marker or an op-index
    gap — a trace with holes would silently skew any policy scored
    against it, so drift fails loudly at load, not at analysis."""

    def __init__(self, path):
        self.path = Path(path)
        lines = [ln for ln in
                 self.path.read_text("utf-8").splitlines() if ln.strip()]
        if not lines:
            raise ValueError(f"{self.path}: empty trace file")
        self.header: Dict[str, Any] = json.loads(lines[0])
        if self.header.get("schema") != TRACE_SCHEMA:
            raise ValueError(
                f"{self.path}: schema {self.header.get('schema')!r}, "
                f"expected {TRACE_SCHEMA!r}")
        self.ops: List[Dict[str, Any]] = [json.loads(ln)
                                          for ln in lines[1:]]
        for index, op in enumerate(self.ops):
            if op.get("op") != index:
                raise ValueError(
                    f"{self.path}: op index {op.get('op')!r} at "
                    f"position {index} (records missing or reordered)")

    def __len__(self) -> int:
        return len(self.ops)

    @property
    def t0(self) -> float:
        return float(self.header.get("t0", 0.0))

    @property
    def seed(self) -> int:
        return int(self.header.get("seed", 0))

    def replay(self) -> List[Dict[str, Any]]:
        """Ops in simulator feed order: by relative start time, op index
        breaking ties — deterministic for any recorded file."""
        return sorted(self.ops,
                      key=lambda op: (op.get("t", 0.0), op["op"]))
