"""TPU Pallas kernels for the hot ops.

Only ops where XLA's automatic fusion is insufficient get kernels: attention
(blockwise flash, ring) — the O(S²) memory/bandwidth monster. RMSNorm, RoPE,
SwiGLU are left to XLA, which fuses elementwise chains into the surrounding
matmuls better than a hand kernel would (verified against the fallback in
benchmarks before adding any kernel here).
"""

from .attention import flash_attention

__all__ = ["flash_attention"]
