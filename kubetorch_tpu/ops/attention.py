"""FlashAttention-2 for TPU in Pallas: blockwise causal attention with online
softmax, GQA-aware, custom VJP with a flash backward pass.

Why a kernel at all: XLA materializes the (S, S) logits tensor per head for
plain attention — at S=8k that is the HBM-bandwidth wall. The kernel streams
K/V blocks through VMEM with fp32 accumulators, never materializing logits.

Layout: heads are moved to the second dim — (B, N, S, Hd) — so each grid step
works on a (block, head_dim) tile that maps directly onto the MXU; the
(1, 1, BQ, BK) logits tile lives only in VMEM/registers. GQA is handled in
the BlockSpec index maps (q-head h reads kv-head h*NKV//N) so K/V are never
broadcast in HBM.

Causality is enforced at two levels: whole (q-block, k-block) tiles above the
diagonal are skipped via ``pl.when`` (half the FLOPs), and the diagonal tile
is masked elementwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# TPU memory tiles are (8, 128) for fp32: a per-row statistic like the LSE
# cannot be stored as a bare (..., S) array with (1, 1, block_q) blocks — the
# last two block dims must tile onto (8, 128). Per-row stats are therefore
# broadcast across a 128-lane trailing dim (same layout the stock XLA flash
# kernels use) and lane 0 is read back inside the kernels.
LANES = 128


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                scale: float, causal: bool, block_q: int, block_k: int,
                need_lse: bool):
    lse_ref, acc_ref, m_ref, l_ref = rest if need_lse else (None, *rest)
    qi = pl.program_id(2)   # q-block index
    kj = pl.program_id(3)   # k-block index (innermost, sequential)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    should_compute = True
    if causal:
        # block above the diagonal ⇒ fully masked ⇒ skip
        should_compute = qi * block_q + block_q - 1 >= kj * block_k

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # (BQ, Hd)
        k = k_ref[0, 0].astype(jnp.float32)          # (BK, Hd)
        v = v_ref[0, 0].astype(jnp.float32)          # (BK, Hd)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:]                             # (BQ, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                        # (BQ, BK)
        alpha = jnp.exp(m_prev - m_new)               # (BQ, 1)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)          # fully-masked rows → 0 out
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        if need_lse:
            lse = m_ref[:] + jnp.log(l_safe)          # (BQ, 1)
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret, need_lse=True):
    b, n, s, hd = q.shape
    nkv = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)
    grid = (b, n, s // block_q, s // block_k)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               need_lse=need_lse)
    out_specs = [pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, i, j: (b_, h, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((b, n, s, hd), q.dtype)]
    if need_lse:
        # lse only exists to seed the backward pass; the no-grad path skips
        # writing it entirely (it is 128 lanes wide — see LANES)
        out_specs.append(pl.BlockSpec((1, 1, block_q, LANES),
                                      lambda b_, h, i, j: (b_, h, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, n, s, LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, i, j: (b_, h * nkv // n, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, i, j: (b_, h * nkv // n, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),    # m
            pltpu.VMEM((block_q, 1), jnp.float32),    # l
        ],
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if need_lse else (res[0], None)


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 style, two passes)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    should = True
    if causal:
        should = qi * block_q + block_q - 1 >= kj * block_k

    @pl.when(should)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]                    # (BQ, 1), lane 0
        delta = delta_ref[0, 0][:, :1]                # (BQ, 1), lane 0
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                           # (BQ, BK)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc_ref[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, scale, causal, block_q, block_k, nq_blocks):
    kj = pl.program_id(2)
    qi = pl.program_id(3)   # innermost: folded (group-member × q-block) index
    nq = pl.num_programs(3)
    # Decode the real q-block: the folded axis runs q-blocks fastest within
    # each query head of the GQA group. Using the folded index directly for
    # causality would mis-mask every head after the first.
    qb = qi % nq_blocks

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    should = True
    if causal:
        should = qb * block_q + block_q - 1 >= kj * block_k

    @pl.when(should)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                           # (BQ, BK)
        dv_acc_ref[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                  # (BQ, BK)
        dk_acc_ref[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    b, n, s, hd = q.shape
    nkv = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, s)

    # delta = rowsum(dO * O) — the softmax-grad correction term. Both stats
    # are broadcast on the fly into the 128-lane layout (see LANES) here;
    # the residual itself is stored narrow.
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LANES))
    lse = jnp.broadcast_to(lse[..., None], (*lse.shape, LANES))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, n, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, i, j: (b_, h * nkv // n, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, i, j: (b_, h * nkv // n, j, 0)),
            pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b_, h, i, j: (b_, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, i, j: (b_, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    # dk/dv: one pass per (kv-head, k-block), iterating q blocks of every
    # query head in the group. Grid over q-heads with accumulation across the
    # group would race, so fold the group loop into the q-block axis instead:
    # treat the (group × q-blocks) product as the innermost axis.
    group = n // nkv
    nq_blocks = s // block_q

    def qhead(h, i):
        # i indexes group*nq_blocks: which q head within the group + q block
        return h * group + i // nq_blocks

    def qblock(i):
        return i % nq_blocks

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq_blocks=nq_blocks),
        grid=(b, nkv, s // block_k, group * nq_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, j, i: (b_, qhead(h, i), qblock(i), 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, j, i: (b_, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, j, i: (b_, h, j, 0)),
            pl.BlockSpec((1, 1, block_q, hd), lambda b_, h, j, i: (b_, qhead(h, i), qblock(i), 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b_, h, j, i: (b_, qhead(h, i), qblock(i), 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b_, h, j, i: (b_, qhead(h, i), qblock(i), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, j, i: (b_, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b_, h, j, i: (b_, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                  need_lse=False)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    # keep only lane 0 as the residual — holding the full 128-lane stat from
    # forward to backward would be a 128x HBM blow-up per un-remat'd layer
    return out, (q, k, v, out, lse[..., 0])


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, dout):
    return _bwd(scale, causal, block_q, block_k, interpret, res, dout)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 1024,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise causal attention. q: (B, S, N, Hd); k, v: (B, S, NKV, Hd).

    Returns (B, S, N, Hd). NKV must divide N (GQA). S must be divisible by
    the (clamped) block sizes. ``interpret=None`` auto-enables interpreter
    mode off-TPU so the same code path is unit-testable on CPU.

    Default blocks come from an on-chip sweep (v5e, B=4 S=2048 N=12 Hd=128,
    TPU_EVIDENCE.md): bk=1024 is ~14% faster fwd than 512 — fewer grid
    steps and a longer K/V stream per tile amortize the revisit of the
    q tile; bq beyond 512 bought nothing. Shorter sequences clamp down.
    """
    b, s, n, hd = q.shape
    nkv = k.shape[2]
    assert n % nkv == 0, f"GQA requires n_kv | n_heads, got {nkv}, {n}"
    if scale is None:
        scale = hd ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # choose block sizes that divide S
    bq, bk = min(block_q, s), min(block_k, s)
    while s % bq:
        bq //= 2
    while s % bk:
        bk //= 2

    # head-major layout for the kernel
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, scale, causal, bq, bk, interpret)
    return out.transpose(0, 2, 1, 3)
