"""Flash-decode: fused single-token attention over a slot-grid KV cache.

The engine's decode step attends one new token per slot against that slot's
whole cache. The XLA einsum path materializes a (B, NKV, G, S) logits
tensor per step and reads the full (B, S, NKV, Hd) cache even past each
slot's frontier; at serving lengths the logits tile plus the masked tail
are wasted HBM round-trips on the latency-critical op. This kernel streams
K/V tiles through VMEM with an online softmax (the FlashAttention recipe
with a query block of GQA group rows) and — the decode-specific part —
**skips every tile beyond the slot's position outright**: ``pos`` rides in
as a prefetched scalar and the K/V BlockSpec index maps clamp to the last
in-range tile (Pallas elides the DMA when the block index repeats), so a
slot 300 tokens into a 4096-row cache streams 8 tiles, not 32
([pos // block_k] + 1 of them); ``pl.when`` skips the matching compute.

Two cache layouts share ONE kernel body (``_make_decode_kernel``):

- full-precision (B, S, NKV, Hd) rows — probs round through the cache
  dtype before the PV dot, matching the einsum reference bitwise;
- int8 rows + per-row fp32 scales (``serve.kv_quant``) — the scales fold
  into the math (logits columns ·ks, probs ·vs; all fp32), so the HBM
  stream is int8 tiles plus one (1, block_k) scale row per tile and no fp
  rows ever materialize.

Layout mirrors ``ops.attention``: (B, NKV, G, Hd) query block per grid
step, K/V head-major, fp32 accumulators in VMEM scratch, the innermost
grid axis sequential over K tiles.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# query rows per block = GQA group size padded up to the fp32 sublane tile
_MIN_ROWS = 8


def _make_decode_kernel(quant: bool, *, scale: float, block_k: int):
    """One online-softmax body for both cache layouts. ``quant`` is a
    trace-time switch: it only changes which refs exist and where the
    row scales fold in — the frontier skip, init/finalize, and softmax
    scaffolding are shared so they can never drift apart."""

    def kernel(pos_ref, q_ref, *refs):
        if quant:
            k_ref, ks_ref, v_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
        else:
            k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        b = pl.program_id(0)
        kj = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(kj == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        pos_b = pos_ref[b]
        start = kj * block_k

        # the whole tile is past this slot's frontier ⇒ nothing to read
        @pl.when(start <= pos_b)
        def _compute():
            q = q_ref[0, 0].astype(jnp.float32)       # (Gp, Hd)
            k = k_ref[0, 0].astype(jnp.float32)       # (BK, Hd)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = s * scale
            if quant:
                s = s * ks_ref[0, 0]                  # (1, BK) logit columns
            cols = start + jax.lax.broadcasted_iota(
                jnp.int32, (q.shape[0], block_k), 1)
            s = jnp.where(cols <= pos_b, s, NEG_INF)

            m_prev = m_ref[:]                         # (Gp, 1)
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            if quant:
                # vs folds into the probs; int8 V dequantizes to fp32 —
                # the whole PV dot runs fp32 (the quant einsum reference)
                pv_lhs = p * vs_ref[0, 0]
                v = v_ref[0, 0].astype(jnp.float32)
            else:
                # p rounds through the cache dtype before the PV dot
                # (fp32 acc) — same rounding as the einsum reference and
                # the flash fwd kernel
                v = v_ref[0, 0]
                pv_lhs = p.astype(v.dtype)
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                pv_lhs, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:] = m_new

        @pl.when(kj == nk - 1)
        def _finalize():
            l = l_ref[:]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)

    return kernel


def _decode_call(quant: bool, q, values, scales, pos, *,
                 scale: Optional[float], block_k: int,
                 interpret: Optional[bool]):
    """Shared wrapper: shape derivation, GQA padding, head-major
    transposes, frontier-clamp BlockSpecs, scratch, and output slicing for
    both layouts. ``values`` = (ck, cv) rows (B, S, NKV, Hd); ``scales`` =
    (ks, vs) per-row scales (B, S, NKV) for the quant layout, else None."""
    b, nh, hd = q.shape
    s, nkv = values[0].shape[1], values[0].shape[2]
    assert nh % nkv == 0, f"GQA requires n_kv | n_heads, got {nkv}, {nh}"
    group = nh // nkv
    if scale is None:
        scale = hd ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bk = min(block_k, s)
    while s % bk:
        bk //= 2

    # group-major query rows, padded to the sublane tile
    gp = max(_MIN_ROWS, group)
    qg = q.reshape(b, nkv, group, hd)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    # the frontier skip lives in the index maps, not the kernel body:
    # Pallas elides a block DMA only when the index map returns the same
    # block as the previous step, so past-frontier steps clamp to the last
    # in-range tile (the kernel's pl.when then skips the compute too).
    # pl.when alone would save FLOPs but still stream every tile from HBM.
    def val_spec():
        return pl.BlockSpec((1, 1, bk, hd),
                            lambda b_, h, j, pos_: (
                                b_, h, jnp.minimum(j, pos_[b_] // bk), 0))

    def scale_spec():
        return pl.BlockSpec((1, 1, 1, bk),
                            lambda b_, h, j, pos_: (
                                b_, h, 0, jnp.minimum(j, pos_[b_] // bk)))

    q_spec = pl.BlockSpec((1, 1, gp, hd),
                          lambda b_, h, j, pos_: (b_, h, 0, 0))
    inputs, in_specs = [qg], [q_spec]
    for i, val in enumerate(values):
        inputs.append(val.transpose(0, 2, 1, 3))       # (B, NKV, S, Hd)
        in_specs.append(val_spec())
        if quant:
            inputs.append(scales[i].transpose(0, 2, 1)[:, :, None, :])
            in_specs.append(scale_spec())              # (B, NKV, 1, S)

    out = pl.pallas_call(
        _make_decode_kernel(quant, scale=scale, block_k=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nkv, s // bk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, gp, hd),
                                   lambda b_, h, j, pos_: (b_, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((gp, hd), jnp.float32),    # acc
                pltpu.VMEM((gp, 1), jnp.float32),     # m
                pltpu.VMEM((gp, 1), jnp.float32),     # l
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, nkv, gp, hd), q.dtype),
        interpret=interpret,
    )(pos.astype(jnp.int32), *inputs)
    return out[:, :, :group].reshape(b, nh, hd)


def decode_attention(q: jax.Array, ck: jax.Array, cv: jax.Array,
                     pos: jax.Array, *, scale: Optional[float] = None,
                     block_k: int = 512,
                     interpret: Optional[bool] = None) -> jax.Array:
    """One new token per slot against its cache rows ``<= pos``.

    q: (B, NH, Hd); ck/cv: (B, S, NKV, Hd); pos: (B,) int32 — the row each
    slot's new token occupies (already written). Returns (B, NH, Hd).
    Bit-compatible with the masked-einsum reference in
    ``serve.engine._decode_layer`` (asserted in tests/test_decode_kernel.py).

    ``block_k=512`` validated by an on-chip sweep (v5e, 16 slots, S=4096):
    1024 wins ~3% on a full cache but loses at quarter fill where the
    finer frontier skip streams fewer rows — 512 is the serving-mix
    compromise (slots are usually mid-generation, not full).
    """
    return _decode_call(False, q, (ck, cv), None, pos, scale=scale,
                        block_k=block_k, interpret=interpret)


def decode_attention_quant(q: jax.Array, kq: jax.Array, ks: jax.Array,
                           vq: jax.Array, vs: jax.Array, pos: jax.Array, *,
                           scale: Optional[float] = None, block_k: int = 512,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Flash-decode over an int8 cache (``serve.kv_quant``): same frontier
    tile-skipping as :func:`decode_attention`, HALF the HBM stream.

    q: (B, NH, Hd); kq/vq: (B, S, NKV, Hd) int8; ks/vs: (B, S, NKV) fp32
    per-row scales; pos: (B,). Bit-compatible with the fp32 fold-in einsum
    reference (``serve.engine._decode_layer_quant``), asserted in
    tests/test_kv_quant.py."""
    return _decode_call(True, q, (kq, vq), (ks, vs), pos, scale=scale,
                        block_k=block_k, interpret=interpret)
