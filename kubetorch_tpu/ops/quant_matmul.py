"""Fused int4-dequant matmul: the HBM stream is the PACKED nibbles.

XLA cannot fuse the int4 unpack (shift / sign-extend / concat) into a
dot's operand pipeline the way it fuses the int8 ``convert``: the
unpacked full-precision weight materializes in HBM every step, and the
measured decode matmul lands ~4× SLOWER than int8
(``scripts/tpu_int4_probe.py``). This kernel does the unpack in VMEM:
each grid step DMAs one packed tile — half of int8's bytes — shifts the
two nibble planes out on the VPU, and issues one MXU dot per plane
against the matching halves of ``x`` (the half-split pack format of
``models.quant._quantize_leaf_int4``: byte row r = weight rows r and
r + K/2). Group scales (one per ``block_k`` rows) multiply the partial
product, so the accumulation is exact over groups.

Decode is weight-bound at batch≈slots, so this is the difference between
int4-as-capacity (fits, but slower than int8) and int4-as-throughput
(half int8's weight stream).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(xlo_ref, xhi_ref, p_ref, slo_ref, shi_ref, o_ref):
    kj = pl.program_id(1)
    p = p_ref[:].astype(jnp.int32)
    lo = jnp.right_shift(jnp.left_shift(p, 28), 28)      # sign-extend nibble
    hi = jnp.right_shift(jnp.left_shift(p, 24), 28)
    part = jnp.dot(xlo_ref[:], lo.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) * slo_ref[:]
    part = part + jnp.dot(xhi_ref[:], hi.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32) * shi_ref[:]

    @pl.when(kj == 0)
    def _init():
        o_ref[:] = part

    @pl.when(kj > 0)
    def _acc():
        o_ref[:] += part


@functools.partial(jax.jit, static_argnames=("block_j", "interpret"))
def _q4_matmul(x, packed, scale, block_j: int, interpret: bool):
    b, din = x.shape
    half, dout = packed.shape
    groups = scale.shape[0]
    block_k = half // (groups // 2)      # = the quantization group size
    kt = half // block_k
    xlo, xhi = x[:, : din // 2], x[:, din // 2:]
    slo, shi = scale[: groups // 2], scale[groups // 2:]
    grid = (dout // block_j, kt)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, block_k), lambda j, k: (0, k)),        # x lo
            pl.BlockSpec((b, block_k), lambda j, k: (0, k)),        # x hi
            pl.BlockSpec((block_k, block_j), lambda j, k: (k, j)),  # packed
            pl.BlockSpec((1, block_j), lambda j, k: (k, j)),        # s lo
            pl.BlockSpec((1, block_j), lambda j, k: (k, j)),        # s hi
        ],
        out_specs=pl.BlockSpec((b, block_j), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b, dout), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(xlo, xhi, packed, slo, shi)
    return out


def q4_matmul(x: jax.Array, packed: jax.Array, scale: jax.Array,
              block_j: int = 512,
              interpret: Optional[bool] = None) -> jax.Array:
    """``x @ W`` where W is half-split nibble-packed int4.

    x (B, K) any float dtype; packed (K/2, N) int8; scale (K/g, N) f32
    with the group size g dividing K/2 evenly (the kernel's K tile IS the
    group). Returns (B, N) f32 — callers cast. Shapes that don't tile
    (g ∤ K/2, block_j ∤ N) must use the XLA fallback
    (``models.quant._dequant_int4``); ``supported`` checks."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _q4_matmul(x.astype(jnp.bfloat16), packed, scale,
                      block_j=min(block_j, packed.shape[1]),
                      interpret=bool(interpret))


def q4_supported(x_shape, packed_shape, scale_shape,
                 block_j: int = 512) -> bool:
    """Static tiling check — mirrors what the kernel assumes."""
    b, din = x_shape
    half, dout = packed_shape
    groups = scale_shape[0]
    if din != 2 * half or groups % 2 or scale_shape[1] != dout:
        return False
    if half % (groups // 2):
        return False
    block_k = half // (groups // 2)
    if block_k % 128 or dout % min(block_j, dout):
        return False
    return True
