"""Device-mesh parallelism: the TPU-native feature the reference lacks.

The reference is a *launcher* — TP/PP/SP/EP/CP are absent from its tree
(SURVEY §2.4) because torch leaves model parallelism to user frameworks. On
TPU, parallelism is a launcher-level concern: a device mesh + sharding rules
compiled through jit/GSPMD. This package makes ``.distribute("jax",
mesh={"data": N, "fsdp": M, "tensor": K, "context": C, "expert": E})``
first-class.
"""

from .mesh import MeshSpec, build_mesh, AXIS_DATA, AXIS_FSDP, AXIS_TENSOR, AXIS_CONTEXT, AXIS_EXPERT
from .sharding import ShardingRules, LLAMA_RULES, named_sharding, shard_pytree

__all__ = [
    "MeshSpec", "build_mesh", "ShardingRules", "LLAMA_RULES",
    "named_sharding", "shard_pytree",
    "AXIS_DATA", "AXIS_FSDP", "AXIS_TENSOR", "AXIS_CONTEXT", "AXIS_EXPERT",
]
