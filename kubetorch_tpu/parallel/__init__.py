"""Device-mesh parallelism: the TPU-native feature the reference lacks.

The reference is a *launcher* — TP/PP/SP/EP/CP are absent from its tree
(SURVEY §2.4) because torch leaves model parallelism to user frameworks. On
TPU, parallelism is a launcher-level concern: a device mesh + sharding rules
compiled through jit/GSPMD. This package makes ``.distribute("jax",
mesh={"data": N, "fsdp": M, "tensor": K, "context": C, "expert": E})``
first-class.
"""

from .mesh import (MeshSpec, build_mesh, AXIS_DATA, AXIS_FSDP, AXIS_PIPE,
                   AXIS_TENSOR, AXIS_CONTEXT, AXIS_EXPERT)
from .sharding import (ShardingRules, LLAMA_RULES, MOE_RULES, VIT_RULES,
                       named_sharding, shard_pytree)

# pipeline.py imports jax at module top; the server/controller processes
# import this package (via .mesh) pre-spawn and must stay jax-free, so the
# pipeline exports resolve lazily (PEP 562).
_PIPELINE_EXPORTS = ("gpipe", "gpipe_interleaved",
                     "llama_forward_pipelined",
                     "llama_loss_pipelined", "llama_pipeline_place",
                     "llama_pipeline_shardings",
                     "llama_pipeline_specs", "PIPE_LLAMA_RULES",
                     "moe_forward_pipelined", "moe_loss_pipelined",
                     "moe_pipeline_place",
                     "moe_pipeline_shardings", "moe_pipeline_specs",
                     "PIPE_MOE_RULES",
                     "vit_forward_pipelined", "vit_loss_pipelined",
                     "vit_pipeline_place", "vit_pipeline_shardings",
                     "vit_pipeline_specs", "PIPE_VIT_RULES")

__all__ = [
    "MeshSpec", "build_mesh", "ShardingRules", "LLAMA_RULES", "MOE_RULES",
    "VIT_RULES", "named_sharding", "shard_pytree",
    *_PIPELINE_EXPORTS,
    "AXIS_DATA", "AXIS_FSDP", "AXIS_PIPE", "AXIS_TENSOR", "AXIS_CONTEXT",
    "AXIS_EXPERT",
]


def __getattr__(name):
    if name in _PIPELINE_EXPORTS:
        from . import pipeline
        return getattr(pipeline, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
