"""Device-mesh construction from a declarative spec.

Design: the user (or ``Compute.distribute``) states logical axis sizes; we
validate them against the device count, lay the axes out so the
highest-traffic axis (tensor) maps to the innermost/fastest ICI dimension, and
return a ``jax.sharding.Mesh``. Multi-slice TPU pods add a leading ``dcn``
axis (data parallelism across slices rides DCN; everything else stays inside
a slice on ICI) — the megascale recipe from the scaling book.

Axis conventions (all optional, size-1 axes are dropped from PartitionSpecs
automatically by GSPMD):

- ``data``:    pure data parallelism (gradient psum only)
- ``fsdp``:    data parallelism with parameter/optimizer sharding (ZeRO-3);
               params all-gathered per layer, grads reduce-scattered
- ``tensor``:  Megatron-style tensor parallelism within attention/FFN
- ``context``: sequence/context parallelism (ring attention over ICI neighbors)
- ``expert``:  expert parallelism for MoE (all-to-all token routing)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_DCN = "dcn"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_TENSOR = "tensor"
AXIS_CONTEXT = "context"
AXIS_EXPERT = "expert"

# Outer-to-inner order: dcn crosses slices (slowest fabric), tensor innermost
# (most collective traffic per step → nearest-neighbor ICI links). Pipe sits
# between the data-like axes and the per-stage axes: one ppermute per
# microbatch per boundary is far less traffic than tensor's per-matmul psums.
CANONICAL_ORDER: Tuple[str, ...] = (
    AXIS_DCN, AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_EXPERT, AXIS_CONTEXT,
    AXIS_TENSOR,
)


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh: axis name → size. ``-1`` on at most one axis means
    "absorb all remaining devices" (like a reshape wildcard)."""

    data: int = 1
    fsdp: int = 1
    pipe: int = 1
    tensor: int = 1
    context: int = 1
    expert: int = 1
    dcn: int = 1  # number of slices (multi-slice pods)

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshSpec":
        unknown = set(d) - {a for a in CANONICAL_ORDER}
        if unknown:
            raise ValueError(f"Unknown mesh axes {sorted(unknown)}; valid: {CANONICAL_ORDER}")
        return cls(**{k: int(v) for k, v in d.items()})

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in CANONICAL_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill a single ``-1`` wildcard and validate the product."""
        sizes = self.axis_sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"Cannot absorb remainder: {n_devices} devices not divisible by {fixed}")
            sizes[wild[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"Mesh spec {sizes} wants {total} devices but {n_devices} are available")
        return MeshSpec(**sizes)

    @property
    def names(self) -> Tuple[str, ...]:
        return CANONICAL_ORDER

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in CANONICAL_ORDER)

    def shrink_to(self, n_devices: int) -> "MeshSpec":
        """Re-mesh for a smaller device count (elastic N-1 resume,
        ISSUE 6 / NTP arXiv:2504.06095's degraded-but-alive mode).

        Model-parallel axes (tensor/context/expert/pipe) keep their sizes —
        they define the sharded program's shape and the checkpoint's leaf
        layout — while the data-like axes (data, then fsdp, then dcn, in
        shrink-preference order) absorb the loss: pure data parallelism
        costs only throughput to shrink, fsdp additionally re-gathers
        parameters (the resharded checkpoint load handles that), and
        slice count moves last. Raises ``ValueError`` when ``n_devices``
        cannot hold the model axes at all.
        """
        sizes = self.axis_sizes()
        data_axes = (AXIS_DATA, AXIS_FSDP, AXIS_DCN)
        model = math.prod(s for a, s in sizes.items() if a not in data_axes)
        if n_devices < model or n_devices % model:
            raise ValueError(
                f"Cannot re-mesh to {n_devices} devices: model-parallel "
                f"axes need a multiple of {model}")
        for axis in data_axes:
            trial = dict(sizes)
            trial[axis] = -1
            try:
                return MeshSpec(**trial).resolve(n_devices)
            except ValueError:
                continue
        # remainder doesn't factor across the kept data axes: collapse all
        # data parallelism onto one axis (prefer fsdp if it was in use)
        trial = dict(sizes)
        trial.update({a: 1 for a in data_axes})
        trial[AXIS_FSDP if sizes[AXIS_FSDP] > 1 else AXIS_DATA] = -1
        return MeshSpec(**trial).resolve(n_devices)


@dataclass
class DistributedConfig:
    """The ``.distribute()`` payload that travels controller→pod as metadata.

    Reference analog: ``Compute.distributed_config`` (``compute.py:1570-1604``)
    which carried only {type, workers, procs}. Ours adds the mesh.
    """

    distribution_type: str = "jax"      # jax | pytorch | tensorflow | ray | spmd | local
    workers: int = 1                    # pod replicas (hosts)
    procs_per_worker: Optional[int] = None  # default: 1 per TPU host (megacore)
    mesh: Optional[Dict[str, int]] = None
    restart_procs: bool = False
    # elastic policy knobs (serving/elastic.py ElasticPolicy.from_dict):
    # present → rank loss resumes from the last committed checkpoint on a
    # re-meshed N-1 world instead of cancelling the fan-out. {} opts in
    # with every default.
    elastic: Optional[Dict] = None

    def to_dict(self) -> Dict:
        return {
            "distribution_type": self.distribution_type,
            "workers": self.workers,
            "procs_per_worker": self.procs_per_worker,
            "mesh": self.mesh,
            "restart_procs": self.restart_procs,
            "elastic": self.elastic,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "DistributedConfig":
        return cls(**{k: d.get(k) for k in (
            "distribution_type", "workers", "procs_per_worker", "mesh",
            "restart_procs", "elastic")
            if d.get(k) is not None})


def build_mesh(spec: MeshSpec | Dict[str, int] | None = None,
               devices: Optional[Sequence] = None):
    """Construct a ``jax.sharding.Mesh`` from a spec.

    Devices are reshaped in canonical order so ``tensor`` varies fastest —
    on a real slice JAX enumerates devices in torus order, putting tensor
    neighbors one ICI hop apart. Uses ``jax.experimental.mesh_utils`` when the
    topology is a real TPU slice for optimal physical layout, with a plain
    reshape fallback (CPU meshes, odd shapes).
    """
    import jax
    from jax.sharding import Mesh
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec(data=len(devices))
    if isinstance(spec, dict):
        spec = MeshSpec.from_dict(spec)
    spec = spec.resolve(len(devices))

    shape = spec.shape
    try:
        if devices[0].platform == "tpu":
            from jax.experimental import mesh_utils
            dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
        else:
            raise ValueError  # fall through to reshape
    except Exception:
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, spec.names)


def live_axes(mesh) -> Dict[str, int]:
    """Axis name → size for every mesh axis with size > 1 (the axes that
    actually shard anything; size-1 axes are pruned from PartitionSpecs)."""
    return {n: s for n, s in zip(mesh.axis_names, mesh.devices.shape) if s > 1}


def normalize_batch_axes(live: Dict[str, int],
                         batch_axes: Sequence[str] = ("dcn", "data", "fsdp")):
    """Batch-dim PartitionSpec entry from the live axes: a tuple when
    several batch axes shard it, the bare name for one, None for none —
    the one normalization every shard_map spec builder and cache-sharding
    site shares (drift here desynchronizes specs from stored layouts and
    forces reshards)."""
    ba = tuple(a for a in batch_axes if a in live)
    return ba if len(ba) > 1 else (ba[0] if ba else None)


def shard_map_fn():
    """jax.shard_map across the JAX versions this image may carry (the
    experimental path is the fallback).

    Newer JAX renamed the replication-check kwarg ``check_rep`` →
    ``check_vma``; callers here use the new name. When the installed
    shard_map predates the rename, translate ``check_vma`` to
    ``check_rep`` (same semantics: disable the static replication
    checker) so one call site works on both sides of the rename."""
    import functools
    import inspect

    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):
        return sm
    if "check_vma" in params:
        return sm

    @functools.wraps(sm)
    def _compat(*args, **kwargs):
        if "check_vma" in kwargs:
            val = kwargs.pop("check_vma")
            if "check_rep" in params:
                kwargs["check_rep"] = val
        return sm(*args, **kwargs)

    return _compat


def lax_axis_size(axis):
    """Static mesh-axis size from inside a shard_map body, across the JAX
    API gap: ``lax.axis_size`` where it exists, else the older
    ``core.axis_frame`` lookup (same static int on 0.4.x)."""
    import jax
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.core.axis_frame(axis)


def best_mesh_for(n_devices: int, prefer: str = "fsdp") -> MeshSpec:
    """A sensible default mesh when the user gives none: everything on one
    axis (fsdp by default — params shard, no user model change needed)."""
    return MeshSpec(**{prefer: n_devices})
