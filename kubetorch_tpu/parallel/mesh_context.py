"""Ambient mesh for model code.

Model forwards are pure functions; the mesh is launcher state. Rather than
threading a Mesh through every model signature, the train-step builder (and
anything else that jits over a mesh) installs it here, and mesh-aware ops
(ring attention) pick it up at *trace* time — it is static w.r.t. jit.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

_state = threading.local()


def current_mesh():
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh):
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def axis_size(mesh, name: str) -> int:
    if mesh is None or name not in mesh.axis_names:
        return 1
    return mesh.devices.shape[list(mesh.axis_names).index(name)]
