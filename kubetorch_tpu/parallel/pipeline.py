"""Pipeline parallelism: GPipe over a ``pipe`` mesh axis via shard_map.

TPU-first formulation: the model's layer-stacked params (every leaf is
``(L, ...)`` for ``lax.scan``) shard their **layer dimension** over the
``pipe`` axis — stage p holds layers ``[p·L/P, (p+1)·L/P)`` with no
re-packing. Activations flow stage→stage with ``lax.ppermute`` (one ICI hop
per microbatch per boundary); the GPipe schedule is a ``lax.scan`` over
``M + P - 1`` timesteps, so the whole pipeline is one compiled program —
no host round-trips between microbatches.

Differentiable end-to-end (scan + ppermute transpose cleanly), so the same
function trains; remat inside the stage body keeps bubble memory bounded.

Neither the reference nor torch launchers can express this: it exists here
because parallelism is a launcher-level concern on TPU (SURVEY §2.4).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import (AXIS_CONTEXT, AXIS_EXPERT, AXIS_FSDP, AXIS_PIPE,
                   AXIS_TENSOR, lax_axis_size as _lax_axis_size,
                   live_axes as _live_axes)
from .sharding import (BATCH_AXES as _BATCH_AXES, LLAMA_RULES, VIT_RULES,
                       ShardingRules)


def _shard_map():
    # version-compat (check_vma ↔ check_rep) lives in one place: mesh.py
    from .mesh import shard_map_fn
    return shard_map_fn()


def _reduce_stage_aux(aux_acc, mesh, axis):
    """Epilogue for the stage-aux channel (shared by both schedules): sum
    over stages (pipe), average over axes that see different data (batch
    shards, sequence shards); replicated axes (tensor/expert) compute
    identical aux already."""
    aux = lax.psum(aux_acc, axis)
    reduce_axes = tuple(a for a in (*_BATCH_AXES, AXIS_CONTEXT)
                        if a in _live_axes(mesh))
    if reduce_axes:
        aux = lax.pmean(aux, reduce_axes)
    return aux


def gpipe(stage_fn: Callable, mesh, *, axis: str = "pipe",
          n_microbatches: int, in_specs, params_specs, out_specs=None,
          stage_aux: bool = False):
    """Build a pipelined ``f(stage_params, x) -> y`` over ``mesh[axis]``.

    ``stage_fn(stage_params, x) -> y`` consumes one stage's params (the
    layer-dim shard) and one microbatch activation, both local. ``x`` is
    globally (M*mb, ...) — reshaped to microbatches internally. The result is
    replicated across the pipe axis.

    With ``stage_aux=True``, ``stage_fn`` returns ``(y, aux_scalar)`` and the
    pipelined function returns ``(y, aux_sum)``: the fp32 scalar summed over
    every REAL (stage, microbatch) execution — bubble ticks (a stage running
    garbage before/after its window) are masked out — then psummed over the
    pipe axis. Used for MoE router load-balancing losses.
    """
    from jax.sharding import PartitionSpec as P

    smap = _shard_map()

    def pipelined(stage_params, x):
        M = n_microbatches

        def per_device(local_params, x_local):
            p = lax.axis_index(axis)
            n_stages = _lax_axis_size(axis)
            xs = x_local.reshape(M, x_local.shape[0] // M, *x_local.shape[1:])

            def timestep(carry, t):
                recv, outputs, aux_acc = carry
                mb = t - p                       # my microbatch at this tick
                in_window = (mb >= 0) & (mb < M)
                # stage 0 pulls fresh input; later stages consume the wire
                fresh = lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
                inp = jnp.where(p == 0, fresh, recv)
                if stage_aux:
                    out, aux = stage_fn(local_params, inp)
                    # bubble ticks run garbage; only real executions count
                    aux_acc = aux_acc + jnp.where(
                        in_window, aux.astype(jnp.float32), 0.0)
                else:
                    out = stage_fn(local_params, inp)
                # rotate outputs one stage forward (ring; the wrap-around
                # value into stage 0 is ignored by the `where` above)
                send = lax.ppermute(
                    out, axis,
                    [(i, (i + 1) % n_stages) for i in range(n_stages)])
                # last stage records finished microbatch `mb` when valid
                valid = (p == n_stages - 1) & in_window
                idx = jnp.clip(mb, 0, M - 1)
                current = lax.dynamic_index_in_dim(outputs, idx, 0,
                                                   keepdims=False)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(valid, out, current), idx, 0)
                return (send, outputs, aux_acc), None

            init = (jnp.zeros_like(xs[0]),
                    jnp.zeros((M, *xs.shape[1:]), xs.dtype),
                    jnp.zeros((), jnp.float32))
            (_, outputs, aux_acc), _ = lax.scan(timestep, init,
                                                jnp.arange(M + n_stages - 1))
            # only the last stage holds real outputs; replicate via psum
            outputs = lax.psum(
                jnp.where(p == n_stages - 1, outputs,
                          jnp.zeros_like(outputs)), axis)
            outputs = outputs.reshape(x_local.shape)
            if stage_aux:
                return outputs, _reduce_stage_aux(aux_acc, mesh, axis)
            return outputs

        specs_out = out_specs if out_specs is not None else in_specs
        if stage_aux:
            specs_out = (specs_out, P())
        return smap(per_device, mesh=mesh,
                    in_specs=(params_specs, in_specs),
                    # NOT `or`: an empty PartitionSpec (replicated) is falsy
                    out_specs=specs_out,
                    check_vma=False)(stage_params, x)

    return pipelined


def gpipe_interleaved(chunk_fn: Callable, mesh, *, axis: str = "pipe",
                      n_microbatches: int, n_virtual: int, in_specs,
                      params_specs, out_specs=None, stage_aux: bool = False):
    """Interleaved (virtual-stage) pipeline schedule over ``mesh[axis]``.

    Each device holds ``n_virtual`` layer CHUNKS instead of one contiguous
    stage — global chunk ``c`` lives on device ``c mod P`` (local param
    leaves carry a leading ``(V, 1, ...)`` chunk dim; the size-1 dim is the
    sharded pipe dim of the host-side ``(V, P, ...)`` layout) — and every
    activation loops the ring ``V`` times. Microbatches advance in blocks
    of ``P``: at shifted time ``s = t - p`` device ``p`` runs virtual chunk
    ``v = (s // P) mod V`` on microbatch ``(s // (P·V))·P + s % P``; the
    ring wrap-around from the last device back to device 0 legitimately
    carries loop ``v``'s output into loop ``v+1``. Total ticks =
    ``M·V + P - 1``, so the bubble is ``P - 1`` ticks of 1/V-sized chunks —
    V× smaller than GPipe at the same per-device layer count (Megatron's
    interleaved schedule, expressed as one ``lax.scan``).

    ``chunk_fn(chunk_params, x) -> y`` consumes ONE chunk's params (the V
    dim already indexed out) and one microbatch activation. Requires
    ``M % P == 0`` (microbatches advance in blocks of P). ``stage_aux``
    behaves as in :func:`gpipe` (per-chunk aux scalar, bubble-masked).
    """
    from jax.sharding import PartitionSpec as P

    smap = _shard_map()
    P_size = _live_axes(mesh).get(axis, 1)
    if n_microbatches % P_size:
        raise ValueError(f"interleaved schedule needs microbatches="
                         f"{n_microbatches} divisible by pipe={P_size}")

    def pipelined(stage_params, x):
        M, V = n_microbatches, n_virtual
        ticks = M * V + P_size - 1

        def per_device(local_params, x_local):
            p = lax.axis_index(axis)
            n_stages = _lax_axis_size(axis)
            xs = x_local.reshape(M, x_local.shape[0] // M, *x_local.shape[1:])
            # (V, 1, ...) local leaves → (V, ...): drop the sharded pipe dim
            chunks = jax.tree_util.tree_map(
                lambda a: a.reshape(a.shape[0], *a.shape[2:]), local_params)

            def timestep(carry, t):
                recv, outputs, aux_acc = carry
                s = t - p
                k = s // n_stages                  # = block·V + v
                v = k % V
                mb = (k // V) * n_stages + s % n_stages
                in_window = (s >= 0) & (s < M * V)
                fresh = lax.dynamic_index_in_dim(
                    xs, jnp.clip(mb, 0, M - 1), axis=0, keepdims=False)
                # device 0 at v==0 starts a fresh microbatch; everything
                # else (incl. device 0 at v>0) consumes the wire
                inp = jnp.where((p == 0) & (v == 0), fresh, recv)
                chunk_params = jax.tree_util.tree_map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, jnp.clip(v, 0, V - 1), axis=0, keepdims=False),
                    chunks)
                if stage_aux:
                    out, aux = chunk_fn(chunk_params, inp)
                    aux_acc = aux_acc + jnp.where(
                        in_window, aux.astype(jnp.float32), 0.0)
                else:
                    out = chunk_fn(chunk_params, inp)
                send = lax.ppermute(
                    out, axis,
                    [(i, (i + 1) % n_stages) for i in range(n_stages)])
                valid = (p == n_stages - 1) & (v == V - 1) & in_window
                idx = jnp.clip(mb, 0, M - 1)
                current = lax.dynamic_index_in_dim(outputs, idx, 0,
                                                   keepdims=False)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(valid, out, current), idx, 0)
                return (send, outputs, aux_acc), None

            init = (jnp.zeros_like(xs[0]),
                    jnp.zeros((M, *xs.shape[1:]), xs.dtype),
                    jnp.zeros((), jnp.float32))
            (_, outputs, aux_acc), _ = lax.scan(timestep, init,
                                                jnp.arange(ticks))
            outputs = lax.psum(
                jnp.where(p == n_stages - 1, outputs,
                          jnp.zeros_like(outputs)), axis)
            outputs = outputs.reshape(x_local.shape)
            if stage_aux:
                return outputs, _reduce_stage_aux(aux_acc, mesh, axis)
            return outputs

        specs_out = out_specs if out_specs is not None else in_specs
        if stage_aux:
            specs_out = (specs_out, P())
        return smap(per_device, mesh=mesh,
                    in_specs=(params_specs, in_specs),
                    out_specs=specs_out,
                    check_vma=False)(stage_params, x)

    return pipelined


# ---------------------------------------------------------------------------
# Llama integration
# ---------------------------------------------------------------------------

# Llama layout on a pipe(+data/fsdp/tensor) mesh: layer stack sharded on the
# layer dim over pipe, the Megatron dim over tensor, and the d_model dim over
# fsdp (ZeRO-3: the stage body all-gathers one layer's weights at a time and
# the gather's transpose reduce-scatters the grads — scaling-book FSDP+PP).
# embed/lm_head shard like LLAMA_RULES and run under GSPMD outside the
# shard_map. Axis pruning for size-1/absent axes lives in
# ShardingRules.spec_for.
# Layer-stack rules take precedence (matched first, `layers/` prefix);
# embed/lm_head/final-norm fall through to the non-pipelined LLAMA_RULES so
# the two paths can never place them differently.
PIPE_LLAMA_RULES = ShardingRules(rules=[
    (r"layers/(wq|wk|wv|w_gate|w_up)$", (AXIS_PIPE, AXIS_FSDP, AXIS_TENSOR)),
    (r"layers/(wo|w_down)$",            (AXIS_PIPE, AXIS_TENSOR, AXIS_FSDP)),
    (r"layers/.*norm$",                 (AXIS_PIPE,)),
] + LLAMA_RULES.rules)

# The pipelined activation: batch dim over the data-like axes, sequence dim
# over the context axis (ring attention runs inside the stage body).
_PIPE_ACT_RULES = ShardingRules(rules=[(r"^x$", (_BATCH_AXES, AXIS_CONTEXT))])


def _build_pipeline_runner(stage_fn, mesh, M: int, n_virtual: int,
                           act_spec, layer_specs, stage_aux: bool):
    """Pick the schedule and wire the specs — shared by every model family."""
    if n_virtual > 1:
        return gpipe_interleaved(
            stage_fn, mesh, axis="pipe", n_microbatches=M,
            n_virtual=n_virtual, in_specs=act_spec,
            params_specs=_virtual_layer_specs(layer_specs, n_virtual),
            out_specs=act_spec, stage_aux=stage_aux)
    return gpipe(stage_fn, mesh, axis="pipe", n_microbatches=M,
                 in_specs=act_spec, params_specs=layer_specs,
                 out_specs=act_spec, stage_aux=stage_aux)


def _resolve_stage_attn(cfg, live, tp: int, seq_len: int):
    """Resolve ``cfg.attn_impl`` for use INSIDE a pipeline stage's shard_map.

    With a live context axis, attention MUST be context-parallel (a local-
    chunk flash/xla would silently attend over 1/cp of the sequence): ulysses
    when requested, the ring otherwise — via the ``*_local`` already-inside-
    shard_map dispatches. Without one, ring/ulysses are rejected and "auto"
    resolves to flash (TPU) / xla, since "auto" consults the ambient mesh
    context which must not route to a nested shard_map. Works for any config
    dataclass carrying attn_impl/n_heads/n_kv_heads (Llama, MoE, ...).
    """
    import dataclasses as _dc

    if cfg.attn_impl not in ("auto", "xla", "flash", "ring", "ulysses"):
        raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}; expected "
                         "auto|xla|flash|ring|ulysses")
    cp = live.get("context", 1)
    if cp > 1:
        if seq_len % cp:
            raise ValueError(f"seq_len={seq_len} not divisible by "
                             f"context={cp}")
        if cfg.attn_impl == "ulysses":
            # ulysses scatters the LOCAL (post-tp) heads over the context axis
            if (cfg.n_heads // tp) % cp or (cfg.n_kv_heads // tp) % cp:
                raise ValueError(
                    f"ulysses needs context={cp} to divide the per-tensor-"
                    f"shard head counts {cfg.n_heads}/{tp} and "
                    f"{cfg.n_kv_heads}/{tp}; use ring attention instead")
            return _dc.replace(cfg, attn_impl="ulysses_local")
        return _dc.replace(cfg, attn_impl="ring_local")
    if cfg.attn_impl in ("ring", "ulysses"):
        raise ValueError(
            f"attn_impl={cfg.attn_impl!r} in a pipeline needs a live "
            "context axis (mesh context size > 1); use xla/flash otherwise")
    if cfg.attn_impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "xla"
        return _dc.replace(cfg, attn_impl=impl)
    return cfg


def _validate_stage_divisibility(cfg, n_stages: int, tp: int, fsdp: int,
                                 n_virtual: int = 1) -> None:
    """Shared pipe/tensor/fsdp divisibility checks for pipelined models."""
    if cfg.n_layers % (n_stages * n_virtual):
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pipe={n_stages}"
            + (f" × virtual={n_virtual}" if n_virtual > 1 else ""))
    if tp > 1 and (cfg.n_kv_heads % tp or cfg.ffn_dim % tp):
        raise ValueError(f"tensor={tp} must divide n_kv_heads="
                         f"{cfg.n_kv_heads} and ffn_dim={cfg.ffn_dim}")
    if fsdp > 1 and cfg.dim % fsdp:
        raise ValueError(f"fsdp={fsdp} must divide dim={cfg.dim}")


def _validate_pipe_batch(batch: int, live, n_microbatches: int) -> None:
    dp = 1
    for a in _BATCH_AXES:
        dp *= live.get(a, 1)
    local_batch = batch // dp
    if batch % dp or local_batch % n_microbatches:
        raise ValueError(
            f"batch={batch} must divide over dp={dp} into local "
            f"batches divisible by microbatches={n_microbatches}")


def _make_zero3_gather(layer_specs, fsdp: int):
    """Build the in-stage ZeRO-3 gather for one layer's (scan-stripped) param
    tree: each fsdp-sharded leaf is all-gathered on the dim the rule table
    puts "fsdp" at (minus the stripped pipe dim). Under the remat wrapper the
    gathered copies are recomputed in backward, where the gather's transpose
    reduce-scatters the weight grads back over fsdp. One implementation for
    every pipelined model family."""

    def path_key(path):
        return tuple(str(getattr(p, "key", p)) for p in path)

    gather_dims = {path_key(path): list(spec).index("fsdp") - 1
                   for path, spec in
                   jax.tree_util.tree_leaves_with_path(layer_specs)
                   if fsdp > 1 and "fsdp" in spec}

    def gather_layer(lw):
        if not gather_dims:
            return lw

        def gather(path, leaf):
            dim = gather_dims.get(path_key(path))
            if dim is None:
                return leaf
            return lax.all_gather(leaf, "fsdp", axis=dim, tiled=True)

        return jax.tree_util.tree_map_with_path(gather, lw)

    return gather_layer


def _local_freqs(freqs, h, cp: int):
    """RoPE positions are global; slice this context-rank's window of the
    (S, Hd/2) table for its local sequence chunk."""
    if cp <= 1:
        return freqs
    s_local = h.shape[1]
    return lax.dynamic_slice_in_dim(
        freqs, lax.axis_index("context") * s_local, s_local, axis=0)


def llama_pipeline_specs(params, mesh):
    """PartitionSpec pytree placing a llama param tree per ``PIPE_LLAMA_RULES``."""
    return PIPE_LLAMA_RULES.tree_specs(params, mesh)


def llama_pipeline_shardings(params, mesh):
    """``NamedSharding`` pytree for ``llama_pipeline_specs`` (device_put-able)."""
    return PIPE_LLAMA_RULES.tree_shardings(params, mesh)


def _virtual_layer_specs(layer_specs, n_virtual: int):
    """Spec for the interleaved ``(V, P, lpc, …)`` layer layout: the layer
    dim's pipe sharding moves to dim 1 (chunk c on device c mod P), V and
    lpc replicated, trailing dims keep their rule-table placement."""
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(
        lambda spec: P(None, list(spec)[0], None, *list(spec)[1:]),
        layer_specs)


def _pipeline_place(params, mesh, specs, n_virtual: int):
    """Place a param tree for the (optionally interleaved) pipeline.

    ``n_virtual == 1``: device_put per ``specs``. ``n_virtual > 1``: each
    layer-stacked leaf under ``params["layers"]`` is reshaped ``(L, …) →
    (V, P, L/(P·V), …)`` so global chunk ``c`` lands on device ``c mod P``
    (the strided layout the interleaved schedule needs), then device_put;
    everything outside ``layers`` keeps its rule-table placement.
    """
    from jax.sharding import NamedSharding

    if n_virtual == 1:
        return jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(leaf,
                                              NamedSharding(mesh, spec)),
            params, specs)
    p_size = _live_axes(mesh).get("pipe", 1)

    def reshape(leaf):
        if leaf.shape[0] % (p_size * n_virtual):
            raise ValueError(
                f"n_layers={leaf.shape[0]} not divisible by pipe={p_size} "
                f"× virtual={n_virtual}")
        lpc = leaf.shape[0] // (p_size * n_virtual)
        return leaf.reshape(n_virtual, p_size, lpc, *leaf.shape[1:])

    placed = dict(params)
    vspecs = _virtual_layer_specs(specs["layers"], n_virtual)
    placed["layers"] = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(reshape(leaf),
                                          NamedSharding(mesh, spec)),
        params["layers"], vspecs)
    for key in params:
        if key != "layers":
            placed[key] = jax.tree_util.tree_map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)),
                params[key], specs[key])
    return placed


def llama_pipeline_place(params, mesh, n_virtual: int = 1):
    """Place a llama param tree for the (optionally interleaved) pipeline."""
    return _pipeline_place(params, mesh, llama_pipeline_specs(params, mesh),
                           n_virtual)


def llama_hidden_pipelined(params, tokens, cfg, mesh, *,
                           n_microbatches: Optional[int] = None,
                           n_virtual: int = 1):
    """Llama forward with layers pipelined over the mesh's ``pipe`` axis,
    composing with data parallelism (batch dim over ``data``/``fsdp``/``dcn``),
    ZeRO-3 parameter sharding (``fsdp`` axis: stage weights stored sharded,
    one layer all-gathered at a time, grads reduce-scattered), and Megatron
    tensor parallelism (``tensor`` axis) inside each stage.

    ``n_virtual > 1`` switches to the interleaved (virtual-stage) schedule:
    each device holds V strided layer chunks and the bubble shrinks V×
    (:func:`gpipe_interleaved`). Params must then be placed with
    ``llama_pipeline_place(params, mesh, n_virtual)`` — layer leaves carry
    the ``(V, P, lpc, …)`` layout.

    Embedding / final norm / LM head stay under GSPMD outside the shard_map
    (they are a tiny fraction of FLOPs); only the layer stack is staged.
    """
    from ..models.llama import _layer, rmsnorm, rope_freqs

    live = _live_axes(mesh)
    n_stages = live.get("pipe", 1)
    tp = live.get("tensor", 1)
    fsdp = live.get("fsdp", 1)
    _validate_stage_divisibility(cfg, n_stages, tp, fsdp, n_virtual)
    cfg = _resolve_stage_attn(cfg, live, tp, tokens.shape[1])
    cp = live.get("context", 1)
    M = n_microbatches or n_stages
    _validate_pipe_batch(tokens.shape[0], live, M)

    x = params["embed"][tokens].astype(cfg.dtype)
    freqs = rope_freqs(cfg, tokens.shape[1])

    tp_axis = "tensor" if tp > 1 else None
    layer_specs = llama_pipeline_specs(params, mesh)["layers"]
    gather_layer = _make_zero3_gather(layer_specs, fsdp)

    def stage_fn(local_layers, h):
        fr = _local_freqs(freqs, h, cp)

        def body(carry, lw):
            return _layer(cfg, carry, gather_layer(lw), fr,
                          tp_axis=tp_axis), None
        body = jax.checkpoint(body)
        out, _ = lax.scan(body, h, local_layers)
        return out
    act_spec = _PIPE_ACT_RULES.spec_for("x", mesh)
    run = _build_pipeline_runner(stage_fn, mesh, M, n_virtual, act_spec,
                                 layer_specs, stage_aux=False)
    x = run(params["layers"], x)
    return rmsnorm(x, params["final_norm"], cfg.norm_eps)


def llama_forward_pipelined(params, tokens, cfg, mesh, **kw):
    """Pipelined forward to logits (see :func:`llama_hidden_pipelined`)."""
    x = llama_hidden_pipelined(params, tokens, cfg, mesh, **kw)
    return (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)


def llama_loss_pipelined(params, tokens, targets, cfg, mesh, *,
                         chunk: int = 256, **kw):
    """Pipelined next-token CE WITHOUT materializing the (B, S, V) fp32
    logits: the pipelined hidden states feed the shared per-chunk LM-head
    loss (``models.llama.chunked_ce``) — same HBM win as the non-pipelined
    ``llama_loss_chunked``."""
    from ..models.llama import chunked_ce

    x = llama_hidden_pipelined(params, tokens, cfg, mesh, **kw)
    return chunked_ce(x, targets, params["lm_head"].astype(cfg.dtype), chunk)


# ---------------------------------------------------------------------------
# MoE integration: expert parallelism inside pipeline stages
# ---------------------------------------------------------------------------

# MoE layer stack on a pipe(+data/fsdp/expert/tensor) mesh: attention weights
# as in the llama table; expert-stacked FFN weights additionally shard their
# expert dim over "expert" (the stage body slices dispatch/combine to local
# experts and psums the output — activations are replicated over the expert
# axis in this layout, so no all-to-all is needed); router replicated (fp32,
# tiny, and every rank routes identically).
PIPE_MOE_RULES = ShardingRules(rules=[
    (r"layers/(wq|wk|wv)$",            (AXIS_PIPE, AXIS_FSDP, AXIS_TENSOR)),
    (r"layers/wo$",                    (AXIS_PIPE, AXIS_TENSOR, AXIS_FSDP)),
    (r"layers/experts/w_(gate|up)$",
     (AXIS_PIPE, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR)),
    (r"layers/experts/w_down$",
     (AXIS_PIPE, AXIS_EXPERT, AXIS_TENSOR, AXIS_FSDP)),
    (r"layers/router$",                (AXIS_PIPE,)),
    (r"layers/.*norm$",                (AXIS_PIPE,)),
] + LLAMA_RULES.rules)


def moe_pipeline_specs(params, mesh):
    return PIPE_MOE_RULES.tree_specs(params, mesh)


def moe_pipeline_shardings(params, mesh):
    """``NamedSharding`` pytree for an MoE param tree on a pipe mesh."""
    return PIPE_MOE_RULES.tree_shardings(params, mesh)


def moe_pipeline_place(params, mesh, n_virtual: int = 1):
    """Place an MoE param tree for the (optionally interleaved) pipeline."""
    return _pipeline_place(params, mesh, moe_pipeline_specs(params, mesh),
                           n_virtual)


def moe_hidden_pipelined(params, tokens, cfg, mesh, *,
                         n_microbatches: Optional[int] = None,
                         n_virtual: int = 1):
    """MoE headless forward (final-normed hidden states + aux) with layers
    pipelined over ``pipe``, experts sharded over
    ``expert`` INSIDE each stage, composing with data/fsdp/tensor exactly as
    :func:`llama_hidden_pipelined`. Returns ``(hidden, aux)``: the
    final-normed (B, S, D) hidden states in ``cfg.dtype`` (the LM head is
    applied by the forward/loss wrappers) and the router load-balancing
    loss averaged over microbatches and layers (bubble ticks masked by
    :func:`gpipe`'s ``stage_aux`` channel).

    Note: ``aux`` is a product of batch means, so the microbatch average
    differs from the sequential full-batch value at O(1/M) — the hidden
    states are bit-comparable, the aux regularizer is statistically
    equivalent.
    """
    from ..models.llama import rmsnorm, rope_freqs
    from ..models.moe import _moe_layer

    live = _live_axes(mesh)
    n_stages = live.get("pipe", 1)
    tp = live.get("tensor", 1)
    fsdp = live.get("fsdp", 1)
    ep = live.get("expert", 1)
    _validate_stage_divisibility(cfg, n_stages, tp, fsdp, n_virtual)
    if ep > 1 and cfg.n_experts % ep:
        raise ValueError(f"expert={ep} must divide n_experts="
                         f"{cfg.n_experts}")
    cp = live.get("context", 1)
    if cp > 1 and not cfg.context_chunked_routing:
        # in-stage MoE routing assigns expert capacity per local sequence
        # chunk, which diverges from full-sequence routing whenever an
        # expert overflows — require the explicit opt-in
        raise ValueError(
            "MoE inside pipeline stages with a context axis routes per "
            "sequence chunk; opt in with "
            "MoeConfig(context_chunked_routing=True) or use a context-free "
            "mesh")
    cfg = _resolve_stage_attn(cfg, live, tp, tokens.shape[1])
    M = n_microbatches or n_stages
    _validate_pipe_batch(tokens.shape[0], live, M)

    x = params["embed"][tokens].astype(cfg.dtype)
    freqs = rope_freqs(cfg._llama_view(), tokens.shape[1])

    tp_axis = "tensor" if tp > 1 else None
    ep_axis = "expert" if ep > 1 else None
    layer_specs = moe_pipeline_specs(params, mesh)["layers"]
    gather_layer = _make_zero3_gather(layer_specs, fsdp)

    def stage_fn(local_layers, h):
        fr = _local_freqs(freqs, h, cp)

        def body(carry, lw):
            return _moe_layer(cfg, carry, gather_layer(lw), fr,
                              tp_axis=tp_axis, ep_axis=ep_axis), None
        body = jax.checkpoint(body)
        (out, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                                 local_layers)
        return out, aux

    act_spec = _PIPE_ACT_RULES.spec_for("x", mesh)
    run = _build_pipeline_runner(stage_fn, mesh, M, n_virtual, act_spec,
                                 layer_specs, stage_aux=True)
    x, aux = run(params["layers"], x)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return x, aux / (M * cfg.n_layers)


def moe_forward_pipelined(params, tokens, cfg, mesh, **kw):
    """Pipelined MoE forward to ``(logits, aux)``."""
    x, aux = moe_hidden_pipelined(params, tokens, cfg, mesh, **kw)
    return (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32), aux


def moe_loss_pipelined(params, tokens, targets, cfg, mesh, *,
                       chunk: int = 256, **kw):
    """Pipelined MoE next-token CE + router aux, with the per-chunk LM-head
    loss (never materializes (B, S, V) fp32 logits)."""
    from ..models.llama import chunked_ce

    x, aux = moe_hidden_pipelined(params, tokens, cfg, mesh, **kw)
    ce = chunked_ce(x, targets, params["lm_head"].astype(cfg.dtype), chunk)
    return ce + cfg.router_aux_weight * aux


# ---------------------------------------------------------------------------
# ViT integration: the encoder family pipelines with the same machinery
# ---------------------------------------------------------------------------

# ViT encoder stack on a pipe(+data/fsdp/tensor) mesh: qkv/mlp matrices take
# the Megatron layout, LayerNorm scale/bias replicated per stage;
# patch_embed/pos_embed/head fall through to VIT_RULES so pipelined and
# plain paths can't diverge.
PIPE_VIT_RULES = ShardingRules(rules=[
    (r"layers/(wqkv|w_up)$", (AXIS_PIPE, AXIS_FSDP, AXIS_TENSOR)),
    (r"layers/(wo|w_down)$", (AXIS_PIPE, AXIS_TENSOR, AXIS_FSDP)),
    (r"layers/ln",           (AXIS_PIPE,)),
] + VIT_RULES.rules)


def vit_pipeline_specs(params, mesh):
    return PIPE_VIT_RULES.tree_specs(params, mesh)


def vit_pipeline_shardings(params, mesh):
    """``NamedSharding`` pytree for a ViT param tree on a pipe mesh."""
    return PIPE_VIT_RULES.tree_shardings(params, mesh)


def vit_pipeline_place(params, mesh, n_virtual: int = 1):
    """Place a ViT param tree for the (optionally interleaved) pipeline."""
    return _pipeline_place(params, mesh, vit_pipeline_specs(params, mesh),
                           n_virtual)


def vit_forward_pipelined(params, images, cfg, mesh, *,
                          n_microbatches: Optional[int] = None,
                          n_virtual: int = 1):
    """ViT forward with encoder layers pipelined over ``pipe``, composing
    with data/fsdp(ZeRO-3)/tensor exactly as the decoder families. No RoPE,
    no causal mask, no context axis (images are short sequences); the wqkv
    fused projection column-shards over tensor in blocks of 3·D/tp —
    tensor-parallel ViT stages are not wired yet, so tp must be 1.
    """
    from ..models.vit import _encoder_layer, layernorm, patchify

    live = _live_axes(mesh)
    n_stages = live.get("pipe", 1)
    if live.get("tensor", 1) > 1:
        # the fused (D, 3D) wqkv would need an interleaved q/k/v column
        # split per tensor shard; un-fused projections are round-2 work
        raise ValueError("tensor parallelism inside ViT pipeline stages is "
                         "not supported yet; use a tensor-free mesh")
    if live.get("context", 1) > 1:
        raise ValueError("a context axis does not apply to ViT (short "
                         "sequences); use a context-free mesh")
    fsdp = live.get("fsdp", 1)
    # tp forced to 1 above, so the helper's n_kv_heads/ffn_dim checks (which
    # VitConfig lacks) are short-circuited
    _validate_stage_divisibility(cfg, n_stages, 1, fsdp, n_virtual)
    M = n_microbatches or n_stages
    _validate_pipe_batch(images.shape[0], live, M)

    x = patchify(images.astype(cfg.dtype), cfg) @ params["patch_embed"]
    x = x + params["pos_embed"].astype(cfg.dtype)[None]

    layer_specs = vit_pipeline_specs(params, mesh)["layers"]
    gather_layer = _make_zero3_gather(layer_specs, fsdp)

    def stage_fn(local_layers, h):
        def body(carry, lw):
            return _encoder_layer(cfg, carry, gather_layer(lw)), None
        body = jax.checkpoint(body)
        out, _ = lax.scan(body, h, local_layers)
        return out

    act_spec = _PIPE_ACT_RULES.spec_for("x", mesh)
    run = _build_pipeline_runner(stage_fn, mesh, M, n_virtual, act_spec,
                                 layer_specs, stage_aux=False)
    x = run(params["layers"], x)
    x = layernorm(x, params["final_ln_scale"], params["final_ln_bias"],
                  cfg.norm_eps)
    pooled = jnp.mean(x, axis=1)
    return (pooled @ params["head"].astype(cfg.dtype)).astype(jnp.float32)


def vit_loss_pipelined(params, images, labels, cfg, mesh, **kw):
    from ..models.vit import classification_ce

    return classification_ce(
        vit_forward_pipelined(params, images, cfg, mesh, **kw), labels)


