"""Elastic pipeline parallelism: stage membership that survives stage loss.

The ONLY stage-membership / re-grouping site in the tree (the 15th
``scripts/check_resilience.py`` lint keeps it that way): everything that
maps pipeline stages to pod gangs, re-derives the schedule after a fault,
or fences a zombie stage goes through :class:`ElasticPipeline`.

``parallel/pipeline.py`` is the in-XLA half — one compiled GPipe program
over a ``pipe`` mesh axis, which by construction cannot lose a stage
mid-program. This module is the between-programs half, the robustness
layer ROADMAP item 1 asks for:

- **Membership** — :class:`PipelineMembership` is an immutable snapshot:
  an epoch, one :class:`StageAssignment` (contiguous layer shard + slot
  width) per stage, and the microbatch count. The GPipe tick schedule is
  *derived* from it (:meth:`PipelineMembership.schedule`), so re-deriving
  the schedule after a re-group is free and provably consistent with the
  membership that produced it.
- **Re-grouping** (Ada-Grouper, arXiv:2303.01675) — when a stage dies or
  straggles (cause classified by ``serving/watchdog.py``), the pipe is
  NOT stalled at the bubble waiting for a replacement: the dead stage's
  layer shard is absorbed by its neighbors (``regroup()`` with no
  replacement slot) and the microbatch count is re-derived so the bubble
  fraction of the new, shorter pipe stays at or below the pre-fault
  value. Surviving stages restore the absorbed layers from the last
  committed checkpoint (``llama_pipeline_place`` and friends re-place
  the param tree on the shrunk mesh).
- **Nonuniform degraded mode** (NTP, arXiv:2504.06095, generalizing
  ``MeshSpec.shrink_to``) — when a *smaller* slot is available for the
  lost stage, ``regroup(slot_width=...)`` keeps the stage count and runs
  the re-placed stage narrower than its peers; the microbatch count is
  re-derived against the slowdown factor so the straggling stage's
  service time is amortized instead of pacing the whole pipe.
- **Epoch fence** — every re-group bumps the membership epoch. A zombie
  stage from before the re-group that wakes up and calls ``confirm()``
  (or publishes a boundary activation under its old epoch's keys) is
  refused with a typed :class:`~..exceptions.StaleStageEpochError`; the
  activation keys themselves carry the epoch, so a stale publish can
  never be consumed by the current membership.
- **Data plane** — boundary activations move over the PR 10 shm/store
  data plane under :meth:`activation_key` — content keys scoped by
  ``(job, epoch, step, boundary, microbatch)``.

Scheduler integration (the PR 8 scheduler's first multi-pod-gang tenant)
lives in ``controller/scheduler.py``: :meth:`gang_request` emits the
per-stage demand rows ``Scheduler.admit_gang`` admits atomically (all
stages or queued), and a partial-gang preemption calls back into
``regroup(cause="Preempted")`` instead of killing the job.

Everything here is host-side bookkeeping — no jax imports — so the soak
trainer asset and the scheduler can use it without paying an XLA
interpreter start.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import telemetry
from ..exceptions import StaleStageEpochError

# causes a re-group may carry: the watchdog's death taxonomy
# (serving/watchdog.py classify_death) plus the straggler verdict "Slow",
# which only the pipeline supervisor's heartbeat check produces
REGROUP_CAUSES = ("Crashed", "Killed", "OOMKilled", "Preempted", "Evicted",
                  "Exited", "Slow")

# cap on microbatch re-derivation: re-grouping may grow M to amortize a
# bubble or a slow stage, but never beyond 4x the original draw — past
# that the per-microbatch batch slice is too small to be worth the
# schedule length (Ada-Grouper's diminishing-returns knee)
_MAX_MICROBATCH_GROWTH = 4


@dataclass(frozen=True)
class StageAssignment:
    """One stage's slice of the pipe: which contiguous layers it owns and
    how wide its pod slot is. ``width`` is in chips/slots — nonuniform
    widths are legal (NTP degraded mode) and feed the slowdown-adjusted
    bubble fraction."""

    stage: int
    layers: Tuple[int, ...]
    width: int = 1

    def __post_init__(self):
        if not self.layers:
            raise ValueError(f"stage {self.stage} owns no layers")
        if list(self.layers) != list(range(self.layers[0],
                                           self.layers[-1] + 1)):
            raise ValueError(
                f"stage {self.stage} layers {self.layers} not contiguous")
        if self.width < 1:
            raise ValueError(f"stage {self.stage} width {self.width} < 1")

    def to_dict(self) -> Dict[str, Any]:
        return {"stage": self.stage, "layers": list(self.layers),
                "width": self.width}


@dataclass(frozen=True)
class PipelineMembership:
    """An immutable stage-membership snapshot at one epoch. The schedule,
    the bubble fraction, and the activation-key namespace are all derived
    from it — there is no second copy of "who owns which layers" to
    drift."""

    epoch: int
    assignments: Tuple[StageAssignment, ...]
    n_microbatches: int

    def __post_init__(self):
        if not self.assignments:
            raise ValueError("membership needs at least one stage")
        if self.n_microbatches < 1:
            raise ValueError(f"n_microbatches={self.n_microbatches} < 1")
        covered: List[int] = []
        for i, a in enumerate(self.assignments):
            if a.stage != i:
                raise ValueError(f"assignment {i} carries stage {a.stage}")
            covered.extend(a.layers)
        if covered != list(range(covered[0], covered[0] + len(covered))):
            raise ValueError(f"stages do not tile the layer range: {covered}")

    @property
    def n_stages(self) -> int:
        return len(self.assignments)

    @property
    def n_layers(self) -> int:
        return sum(len(a.layers) for a in self.assignments)

    @property
    def slowdown(self) -> float:
        """Pace factor of the slowest stage vs. a full-width peer: GPipe
        ticks are lockstep, so one narrow stage paces every tick. 1.0 for
        a uniform membership."""
        full = max(a.width for a in self.assignments)
        return max(full / a.width for a in self.assignments)

    @property
    def bubble_fraction(self) -> float:
        """Fraction of the schedule's wall-clock lost to non-useful work:
        the classic GPipe ``(P-1)/(M+P-1)`` bubble, slowdown-adjusted for
        nonuniform widths (a narrow stage stretches every tick, so useful
        throughput shrinks by the pace factor too)."""
        P, M = self.n_stages, self.n_microbatches
        return 1.0 - M / ((M + P - 1) * self.slowdown)

    def layer_owner(self, layer: int) -> int:
        for a in self.assignments:
            if a.layers[0] <= layer <= a.layers[-1]:
                return a.stage
        raise ValueError(f"layer {layer} not in any stage")

    def schedule(self) -> List[List[Tuple[int, int]]]:
        """The GPipe tick schedule derived from this membership: for each
        of the ``M + P - 1`` ticks, the list of ``(stage, microbatch)``
        pairs doing useful work. Bubble ticks are the gaps. Re-deriving
        this after a re-group IS the schedule re-computation — there is
        nothing else to update."""
        P, M = self.n_stages, self.n_microbatches
        return [[(p, t - p) for p in range(P) if 0 <= t - p < M]
                for t in range(M + P - 1)]

    def to_dict(self) -> Dict[str, Any]:
        return {"epoch": self.epoch,
                "n_microbatches": self.n_microbatches,
                "bubble_fraction": round(self.bubble_fraction, 6),
                "assignments": [a.to_dict() for a in self.assignments]}


def _derive_microbatches(m_original: int, n_stages: int,
                         slowdown: float, bubble_budget: float) -> int:
    """Ada-Grouper's microbatch re-grouping, closed-form: the smallest
    ``M >= m_original`` whose slowdown-adjusted bubble fraction fits the
    budget, capped at ``_MAX_MICROBATCH_GROWTH x`` (past which the bubble
    asymptote ``1 - 1/slowdown`` is as close as M can buy)."""
    cap = m_original * _MAX_MICROBATCH_GROWTH
    m = m_original
    while m < cap:
        bubble = 1.0 - m / ((m + n_stages - 1) * slowdown)
        if bubble <= bubble_budget + 1e-9:
            break
        m += 1
    return m


class ElasticPipeline:
    """The stage-membership brain for one pipelined job: owns the current
    :class:`PipelineMembership`, performs every re-group, and enforces the
    epoch fence. Thread-safe — the supervisor's poll thread re-groups
    while stage RPCs confirm.

    ``on_regroup`` (optional) is called with the NEW membership and the
    regroup event dict after every successful re-group — the supervisor
    hook that re-places params (``llama_pipeline_place`` from the last
    committed checkpoint) and re-tasks the surviving stages.
    """

    def __init__(self, n_layers: int, n_stages: int, *,
                 n_microbatches: Optional[int] = None, stage_width: int = 1,
                 job: str = "pipeline", device_class: str = "cpu",
                 policy=None,
                 on_regroup: Optional[Callable[..., None]] = None):
        if n_layers < n_stages:
            raise ValueError(f"n_layers={n_layers} < n_stages={n_stages}")
        if policy is None:
            from ..serving.elastic import ElasticPolicy
            policy = ElasticPolicy()
        from ..resilience import RestartBudget
        self.job = job
        self.device_class = device_class
        self.policy = policy
        # the SPLIT budget, same shape as the SPMD elastic coordinator's:
        # re-groups draw from the elastic resume budget/window, so "how
        # often may this job degrade per hour" is one knob for both the
        # rank-loss and the stage-loss paths
        self.budget = RestartBudget(policy.max_resumes,
                                    policy.resume_window_s)
        self.on_regroup = on_regroup
        self._lock = threading.Lock()
        self._m_original = n_microbatches or n_stages
        base = n_layers // n_stages
        extra = n_layers % n_stages
        start = 0
        assignments = []
        for s in range(n_stages):
            size = base + (1 if s < extra else 0)
            assignments.append(StageAssignment(
                s, tuple(range(start, start + size)), stage_width))
            start += size
        self._membership = PipelineMembership(
            0, tuple(assignments), self._m_original)
        self.regroups: List[Dict[str, Any]] = []
        self.stale_refusals = 0
        self._publish_gauges()

    # -- membership ----------------------------------------------------------

    @property
    def membership(self) -> PipelineMembership:
        with self._lock:
            return self._membership

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._membership.epoch

    def confirm(self, stage: int, epoch: int) -> StageAssignment:
        """A stage confirms it is acting under ``epoch``. Returns its
        current assignment; raises the typed fence error when the epoch
        is stale — the zombie's signal to tear itself down."""
        with self._lock:
            current = self._membership
            if epoch != current.epoch:
                self.stale_refusals += 1
                telemetry.pipeline_metrics()["stale"].inc()
                raise StaleStageEpochError(
                    f"stage {stage} of {self.job!r} confirmed at epoch "
                    f"{epoch} but membership moved to {current.epoch}",
                    job=self.job, stage=stage, epoch=epoch,
                    current_epoch=current.epoch)
            if not 0 <= stage < current.n_stages:
                raise StaleStageEpochError(
                    f"stage {stage} is not in the epoch-{current.epoch} "
                    f"membership of {self.job!r} (stages "
                    f"0..{current.n_stages - 1})",
                    job=self.job, stage=stage, epoch=epoch,
                    current_epoch=current.epoch)
            return current.assignments[stage]

    # -- re-grouping (the ONLY membership mutation in the tree) --------------

    def regroup(self, lost_stage: int, cause: str,
                slot_width: Optional[int] = None) -> PipelineMembership:
        """React to the loss/slowdown of ``lost_stage``:

        - ``slot_width=None`` — no replacement slot: the lost stage's
          layer shard is absorbed by its neighbors (front half to the
          previous stage, back half to the next), the pipe shortens to
          P-1, and M is re-derived against the old bubble budget.
        - ``slot_width=w`` — a narrower slot is available (NTP degraded
          mode): the stage keeps its layers but runs at width ``w``; M is
          re-derived against the resulting pace factor.

        Bumps the epoch, records the event, updates ``kt_pipeline_*``,
        and invokes ``on_regroup``. Raises ``RuntimeError`` when the
        re-group budget is spent or the pipe cannot shrink further.
        """
        if cause not in REGROUP_CAUSES:
            raise ValueError(f"unknown regroup cause {cause!r} "
                             f"(one of {', '.join(REGROUP_CAUSES)})")
        with self._lock:
            old = self._membership
            if not 0 <= lost_stage < old.n_stages:
                raise ValueError(f"lost_stage={lost_stage} not in "
                                 f"0..{old.n_stages - 1}")
            if slot_width is None and old.n_stages == 1:
                raise RuntimeError(
                    f"{self.job!r} lost its only stage; nothing to absorb "
                    "into")
            if not self.budget.try_acquire():
                raise RuntimeError(
                    f"{self.job!r} re-group budget exhausted "
                    f"({self.policy.max_resumes} per "
                    f"{self.policy.resume_window_s:g}s)")
            bubble_budget = max(old.bubble_fraction,
                                (old.n_stages - 1)
                                / (old.n_microbatches + old.n_stages - 1))
            if slot_width is not None:
                mode = "narrow"
                assignments = tuple(
                    a if a.stage != lost_stage
                    else StageAssignment(a.stage, a.layers,
                                         max(1, slot_width))
                    for a in old.assignments)
            else:
                mode = "absorb"
                lost = old.assignments[lost_stage]
                front = len(lost.layers) // 2 if lost_stage > 0 else 0
                if lost_stage == old.n_stages - 1:
                    front = len(lost.layers)
                assignments_l: List[StageAssignment] = []
                for a in old.assignments:
                    if a.stage == lost_stage:
                        continue
                    layers = a.layers
                    if a.stage == lost_stage - 1 and front:
                        layers = layers + lost.layers[:front]
                    elif a.stage == lost_stage + 1 and front < len(lost.layers):
                        layers = lost.layers[front:] + layers
                    stage = a.stage if a.stage < lost_stage else a.stage - 1
                    assignments_l.append(
                        StageAssignment(stage, layers, a.width))
                assignments = tuple(assignments_l)
            slowdown = (max(a.width for a in assignments)
                        / min(a.width for a in assignments))
            m = _derive_microbatches(self._m_original, len(assignments),
                                     slowdown, bubble_budget)
            new = PipelineMembership(old.epoch + 1, assignments, m)
            event = {"epoch": new.epoch, "cause": cause, "mode": mode,
                     "lost_stage": lost_stage, "n_stages": new.n_stages,
                     "n_microbatches": m,
                     "bubble_fraction": round(new.bubble_fraction, 6),
                     "at": time.time()}
            self._membership = new
            self.regroups.append(event)
            del self.regroups[:-16]
            telemetry.pipeline_metrics()["regroups"].inc(cause=cause)
            self._publish_gauges()
            telemetry.add_event("pipeline.regroup", job=self.job,
                                cause=cause, mode=mode, epoch=new.epoch,
                                lost_stage=lost_stage)
        if self.on_regroup is not None:
            self.on_regroup(new, event)
        return new

    def _publish_gauges(self) -> None:
        m = telemetry.pipeline_metrics()
        m["epoch"].set(self._membership.epoch)
        m["stages"].set(self._membership.n_stages)
        m["bubble"].set(self._membership.bubble_fraction)

    # -- data plane ----------------------------------------------------------

    def activation_key(self, step: int, boundary: int, microbatch: int,
                       epoch: Optional[int] = None) -> str:
        """Store/shm data-plane key for the boundary activation leaving
        stage ``boundary`` into stage ``boundary + 1`` (boundary 0 =
        the pipe input, boundary P = the pipe output). Epoch-scoped, so a
        zombie stage's stale publish lands in a namespace nobody reads."""
        e = self._membership.epoch if epoch is None else epoch
        return (f"pipeline/{self.job}/e{e}/step{step}"
                f"/b{boundary}/mb{microbatch}")

    # -- scheduler integration ----------------------------------------------

    def gang_request(self) -> List[Dict[str, Any]]:
        """Per-stage demand rows for ``Scheduler.admit_gang`` — the gang
        is admitted atomically (every stage or none)."""
        with self._lock:
            return [{"stage": a.stage, "device_class": self.device_class,
                     "width": a.width}
                    for a in self._membership.assignments]

    # -- surfacing -----------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Surfaced under ``/health``'s ``pipeline`` key."""
        with self._lock:
            return {"job": self.job,
                    "membership": self._membership.to_dict(),
                    "regroups": list(self.regroups[-4:]),
                    "stale_refusals": self.stale_refusals,
                    **{f"budget_{k}": v
                       for k, v in self.budget.state().items()}}
