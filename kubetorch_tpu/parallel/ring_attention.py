"""Ring attention: context-parallel causal attention over the ICI torus.

The sequence axis is sharded over the ``context`` mesh axis. Each device holds
a local q/k/v chunk; K/V chunks rotate around the ring via
``jax.lax.ppermute`` (XLA lowers this to nearest-neighbor ICI transfers that
overlap with the chunk attention compute), and each device merges incoming
chunks into its local output with the online-softmax recurrence — attention
over the full sequence without any device ever holding more than 1/C of it.

The reference has no long-context support at all (SURVEY §5.7: no ring/
Ulysses/context-parallel code in its tree) — sequence scaling was delegated
to user frameworks. Here it is a mesh axis: ``.distribute("jax",
mesh={"context": C})``.

Two entry points:
- :func:`ring_attention` — the per-shard function, for use inside an existing
  ``shard_map`` (axis_name must be bound).
- :func:`ring_attention_sharded` — GSPMD-compatible wrapper: takes globally
  sharded arrays, applies ``shard_map`` over the context axis internally, so
  model code under plain ``jit`` can call it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_attention(q, k, v, scale, q_offset, kv_offset, causal):
    """fp32 blockwise attention of a local q chunk vs one roving kv chunk.

    Returns (m, l, unnormalized_acc) for online-softmax merging.
    q: (B, Sq, N, Hd); k, v: (B, Sk, NKV, Hd); offsets are global positions.
    """
    b, sq, nh, hd = q.shape
    sk, nkv = k.shape[1], k.shape[2]
    group = nh // nkv
    qg = q.reshape(b, sq, nkv, group, hd)
    s = jnp.einsum("bskgh,btkh->bkgst", qg, k).astype(jnp.float32) * scale
    if causal:
        rows = q_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = kv_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((rows >= cols)[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                    # (b,k,g,s,1)
    # guard fully-masked rows (future-only chunks): exp(NEG_INF - NEG_INF)=1
    # would pollute l; clamp m so p underflows to 0 instead.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    p = jnp.where(s <= NEG_INF, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)                    # (b,k,g,s,1)
    acc = jnp.einsum("bkgst,btkh->bkgsh", p.astype(v.dtype), v).astype(jnp.float32)
    return m_safe, l, acc


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "context", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention. Shapes are LOCAL: (B, S/C, N, Hd).

    Must run inside ``shard_map`` (or pmap) with ``axis_name`` bound.
    """
    b, sq, nh, hd = q.shape
    nkv = k.shape[2]
    group = nh // nkv
    if scale is None:
        scale = hd ** -0.5

    from .mesh import lax_axis_size
    ring = lax_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    q_offset = my * sq

    # perm: device d sends its current kv chunk to d+1 (ring shift).
    perm = [(i, (i + 1) % ring) for i in range(ring)]

    m0 = jnp.full((b, nkv, group, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nkv, group, sq, 1), jnp.float32)
    acc0 = jnp.zeros((b, nkv, group, sq, hd), jnp.float32)

    def body(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src = (my - step) % ring                 # origin device of k_cur
        kv_offset = src * k_cur.shape[1]
        m_c, l_c, acc_c = _chunk_attention(q, k_cur, v_cur, scale, q_offset,
                                           kv_offset, causal)
        m_new = jnp.maximum(m, m_c)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_c - m_new)
        l_new = l * alpha + l_c * beta
        acc_new = acc * alpha + acc_c * beta
        # rotate kv for the next step (skipped result on the last step is
        # harmless: scan's carry is simply unused afterwards)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = lax.scan(body, (m0, l0, acc0, k, v),
                                    jnp.arange(ring))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l).astype(q.dtype)              # (b, nkv, group, sq, hd)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, nh, hd)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, mesh, *,
                           causal: bool = True, scale: Optional[float] = None,
                           batch_axes=("dcn", "data", "fsdp"),
                           context_axis: str = "context",
                           head_axis: str = "tensor") -> jax.Array:
    """GSPMD wrapper: q/k/v are (B, S, N, Hd) jit-level arrays sharded
    batch×context×heads; runs the ring per context-shard via shard_map."""
    from jax.sharding import PartitionSpec as P

    from .mesh import live_axes, normalize_batch_axes
    live = live_axes(mesh)
    ba = normalize_batch_axes(live, batch_axes)
    ha = head_axis if head_axis in live else None
    spec = P(ba, context_axis if context_axis in live else None, ha, None)

    if context_axis not in live:
        # no context sharding: plain attention, let GSPMD handle the rest
        from ..ops.attention import flash_attention
        try:
            return flash_attention(q, k, v, causal=causal, scale=scale)
        except Exception:
            from ..models.llama import _xla_attention
            return _xla_attention(q, k, v, scale or q.shape[-1] ** -0.5)

    fn = functools.partial(ring_attention, axis_name=context_axis,
                           causal=causal, scale=scale)
    return _shard_map()(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(q, k, v)


# ---------------------------------------------------------------------------
# context-parallel DECODE: one new token per slot against a cache whose
# sequence axis is sharded over the context mesh axis (long-context serving)
# ---------------------------------------------------------------------------


def sp_decode_attention(q, ck, cv, pos, *, axis_name: str,
                        scale: Optional[float] = None) -> jax.Array:
    """Per-shard body: decode attention over THIS shard's cache rows, then
    one online-softmax combine across the context axis — the full-sequence
    result without any device ever holding more than 1/C of the cache (and
    without the all-gather GSPMD would insert around a dense einsum).

    q (B, NH, Hd) replicated over ``axis_name``; ck/cv (B, S_local, NKV,
    Hd) this shard's rows; pos (B,) GLOBAL frontier per slot. Rounding
    matches the engine's einsum reference (probs cast to the cache dtype
    before the PV dot); the split softmax itself combines in fp32."""
    b, nh, hd = q.shape
    s_local, nkv = ck.shape[1], ck.shape[2]
    group = nh // nkv
    if scale is None:
        scale = hd ** -0.5
    offset = lax.axis_index(axis_name) * s_local
    qg = q.reshape(b, nkv, group, hd)
    s = jnp.einsum("bkgh,bskh->bkgs", qg, ck).astype(jnp.float32) * scale
    cols = offset + jnp.arange(s_local)
    mask = cols[None, :] <= pos[:, None]                     # (B, S_local)
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), -1e29)
    p = jnp.exp(s - m)
    p = jnp.where(s <= NEG_INF, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)                   # (b,k,g,1)
    acc = jnp.einsum("bkgs,bskh->bkgh", p.astype(cv.dtype),
                     cv).astype(jnp.float32)
    m_g = lax.pmax(m, axis_name)
    corr = jnp.exp(m - m_g)                                  # (b,k,g,1)
    l_g = lax.psum(l * corr, axis_name)
    acc_g = lax.psum(acc * corr, axis_name)
    out = acc_g / jnp.where(l_g == 0.0, 1.0, l_g)
    return out.reshape(b, nh, hd).astype(q.dtype)


def sp_decode_attention_quant(q, kq, ks, vq, vs, pos, *, axis_name: str,
                              scale: Optional[float] = None) -> jax.Array:
    """Per-shard body over an int8 cache shard (``serve.kv_quant``): the
    same split-softmax combine as :func:`sp_decode_attention` with the row
    scales folded in (logits columns ·ks, probs ·vs; all fp32) — so the
    int8 KV cache and context sharding COMPOSE: 1/(2C) of the fp cache
    bytes per chip."""
    b, nh, hd = q.shape
    s_local, nkv = kq.shape[1], kq.shape[2]
    group = nh // nkv
    if scale is None:
        scale = hd ** -0.5
    offset = lax.axis_index(axis_name) * s_local
    qg = q.reshape(b, nkv, group, hd).astype(jnp.float32)
    s = jnp.einsum("bkgh,bskh->bkgs", qg,
                   kq.astype(jnp.float32)) * scale
    s = s * ks.transpose(0, 2, 1)[:, :, None, :]             # (B,NKV,1,S)
    cols = offset + jnp.arange(s_local)
    mask = cols[None, :] <= pos[:, None]
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), -1e29)
    p = jnp.exp(s - m)
    p = jnp.where(s <= NEG_INF, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p * vs.transpose(0, 2, 1)[:, :, None, :]
    acc = jnp.einsum("bkgs,bskh->bkgh", p, vq.astype(jnp.float32))
    m_g = lax.pmax(m, axis_name)
    corr = jnp.exp(m - m_g)
    l_g = lax.psum(l * corr, axis_name)
    acc_g = lax.psum(acc * corr, axis_name)
    out = acc_g / jnp.where(l_g == 0.0, 1.0, l_g)
    return out.reshape(b, nh, hd).astype(q.dtype)


def _sp_decode_specs(mesh, batch_axes, context_axis, head_axis):
    """(q_spec, kv_spec, scale_spec, pos_spec) for the decode shard_maps —
    one builder so the fp and quant wrappers can't drift."""
    from jax.sharding import PartitionSpec as P

    from .mesh import live_axes, normalize_batch_axes
    live = live_axes(mesh)
    if context_axis not in live:
        raise ValueError("sp decode requires a live "
                         f"{context_axis!r} mesh axis (callers gate on it "
                         "via sp_decode_supported)")
    ba = normalize_batch_axes(live, batch_axes)
    ha = head_axis if head_axis in live else None
    return (P(ba, ha, None), P(ba, context_axis, ha, None),
            P(ba, context_axis, ha), P(ba))


def sp_decode_supported(mesh, b: int, s: int, nkv: int, nh: int, *,
                        batch_axes=("dcn", "data", "fsdp"),
                        context_axis: str = "context",
                        head_axis: str = "tensor") -> bool:
    """Can the sp decode path partition these shapes evenly? shard_map has
    no GSPMD-style padding: every named dim must divide by its axis. When
    this says no, callers fall back to the dense path and let GSPMD handle
    layout (correct, just without the memory split)."""
    import math

    from .mesh import live_axes
    live = live_axes(mesh)
    if live.get(context_axis, 1) <= 1:
        return False
    if s % live[context_axis]:
        return False
    bprod = math.prod(live.get(a, 1) for a in batch_axes)
    if b % bprod:
        return False
    hsz = live.get(head_axis, 1)
    return nkv % hsz == 0 and nh % hsz == 0


def _shard_map():
    from .mesh import shard_map_fn
    return shard_map_fn()


def sp_decode_attention_sharded(q, ck, cv, pos, mesh, *,
                                scale: Optional[float] = None,
                                batch_axes=("dcn", "data", "fsdp"),
                                context_axis: str = "context",
                                head_axis: str = "tensor") -> jax.Array:
    """GSPMD wrapper for the engine's decode step: cache (B, S, NKV, Hd)
    sharded batch×context×heads, q (B, NH, Hd) batch×heads, pos (B,)
    batch. shard_map pins those layouts, so jit KEEPS the cache
    context-sharded across steps instead of gathering it. Callers gate on
    :func:`sp_decode_supported`."""
    q_spec, kv_spec, _, pos_spec = _sp_decode_specs(
        mesh, batch_axes, context_axis, head_axis)
    fn = functools.partial(sp_decode_attention, axis_name=context_axis,
                           scale=scale)
    return _shard_map()(fn, mesh=mesh,
                        in_specs=(q_spec, kv_spec, kv_spec, pos_spec),
                        out_specs=q_spec, check_vma=False)(q, ck, cv, pos)


def sp_decode_attention_quant_sharded(q, kq, ks, vq, vs, pos, mesh, *,
                                      scale: Optional[float] = None,
                                      batch_axes=("dcn", "data", "fsdp"),
                                      context_axis: str = "context",
                                      head_axis: str = "tensor") -> jax.Array:
    """int8-cache variant of :func:`sp_decode_attention_sharded`: values
    int8 (B, S, NKV, Hd) + per-row scales (B, S, NKV), both sharded over
    batch×context×heads."""
    q_spec, kv_spec, sc_spec, pos_spec = _sp_decode_specs(
        mesh, batch_axes, context_axis, head_axis)
    fn = functools.partial(sp_decode_attention_quant,
                           axis_name=context_axis, scale=scale)
    return _shard_map()(
        fn, mesh=mesh,
        in_specs=(q_spec, kv_spec, sc_spec, kv_spec, sc_spec, pos_spec),
        out_specs=q_spec, check_vma=False)(q, kq, ks, vq, vs, pos)
