"""Sharding rules: logical param/activation names → PartitionSpecs.

The GSPMD recipe (scaling book): annotate inputs/params with NamedSharding,
let XLA insert the collectives. Rules are (regex, PartitionSpec-template)
pairs matched against pytree paths, so one rule table covers a whole model
family. Size-1 mesh axes are pruned automatically — the same table works for
any mesh the user picks.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .mesh import AXIS_CONTEXT, AXIS_DATA, AXIS_DCN, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR


@dataclass
class ShardingRules:
    """Ordered (path-regex → axis-name-tuple template) table."""

    rules: List[Tuple[str, Tuple[Any, ...]]]

    def spec_for(self, path: str, mesh) -> "Any":
        """Resolve a pytree path to a PartitionSpec valid on ``mesh``.

        Axes absent from the mesh or with size 1 are replaced by None; tuple
        entries (multi-axis sharding like ``("data","fsdp")``) keep only live
        axes.
        """
        from jax.sharding import PartitionSpec as P

        from .mesh import live_axes
        live = live_axes(mesh)

        def prune(entry):
            if entry is None:
                return None
            if isinstance(entry, str):
                return entry if entry in live else None
            kept = tuple(a for a in entry if a in live)
            return kept if len(kept) > 1 else (kept[0] if kept else None)

        for pattern, template in self.rules:
            if re.search(pattern, path):
                return P(*(prune(e) for e in template))
        return P()  # replicated

    def tree_specs(self, tree: Any, mesh) -> Any:
        """PartitionSpec pytree matching ``tree``'s structure."""
        import jax

        def path_str(path) -> str:
            parts = []
            for p in path:
                if hasattr(p, "key"):
                    parts.append(str(p.key))
                elif hasattr(p, "idx"):
                    parts.append(str(p.idx))
                elif hasattr(p, "name"):
                    parts.append(str(p.name))
            return "/".join(parts)

        return jax.tree_util.tree_map_with_path(
            lambda path, _: self.spec_for(path_str(path), mesh), tree)

    def tree_shardings(self, tree: Any, mesh) -> Any:
        import jax
        from jax.sharding import NamedSharding

        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), self.tree_specs(tree, mesh))

    def constrain_tree(self, tree: Any, mesh) -> Any:
        """``with_sharding_constraint`` every leaf per the rules — the
        trace-time twin of :func:`shard_pytree`, usable INSIDE a jitted
        function to steer GSPMD at a specific program point.

        This is the lever behind overlapped gradient reduction
        (``make_train_step(overlap_grads=True)``): constraining each
        microbatch's gradients to the parameter layout forces the
        reduce-scatter to be emitted *there*, inside the accumulation
        scan, where XLA's latency-hiding scheduler can overlap it with
        the next microbatch's compute — instead of one bulk reduction
        after the scan. It also pins the fp32 accumulator itself to one
        fsdp shard per device rather than a full replicated copy.
        """
        import jax

        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, tree,
            self.tree_shardings(tree, mesh))


def named_sharding(mesh, *axes):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(*axes))


def shard_pytree(tree: Any, rules: ShardingRules, mesh) -> Any:
    """Place a host pytree onto the mesh per the rules (initial sharding)."""
    import jax

    shardings = rules.tree_shardings(tree, mesh)
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)


def reshard_pytree(tree: Any, rules: ShardingRules, mesh) -> Any:
    """Re-place an already-device-resident pytree onto a *different* mesh
    (the elastic N-1 re-mesh, ISSUE 6): leaves are staged through host and
    ``device_put`` with the new mesh's rule-derived shardings, so the same
    rule table that laid the N-rank world out lays the (N-1)-rank world out
    — nothing in the layout is pinned to the original device count. The
    store-backed twin of this path is ``kt.get(key, mesh=..., rules=...)``
    (resharded checkpoint load); use this one when the state is already in
    memory on a surviving host."""
    import jax
    import numpy as np

    host = jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)),
                                  tree)
    return shard_pytree(host, rules, mesh)


# ---------------------------------------------------------------------------
# Rule tables
# ---------------------------------------------------------------------------

# Llama-family params (see models/llama.py param tree). Layer-stacked leaves
# have a leading L (scan) dim that is never sharded. Layout follows the
# scaling-book recipe: FSDP shards the d_model (reduction) dim, tensor shards
# heads / ffn-hidden, so matmuls keep an unsharded contracting dim per device
# and grads reduce-scatter over fsdp.
BATCH_AXES = (AXIS_DCN, AXIS_DATA, AXIS_FSDP)

LLAMA_RULES = ShardingRules(rules=[
    (r"embed$",        (AXIS_TENSOR, AXIS_FSDP)),            # (V, D)
    (r"lm_head$",      (AXIS_FSDP, AXIS_TENSOR)),            # (D, V)
    (r"w[qkv]$",       (None, AXIS_FSDP, AXIS_TENSOR)),      # (L, D, N*Hd)
    (r"wo$",           (None, AXIS_TENSOR, AXIS_FSDP)),      # (L, N*Hd, D)
    (r"w_(gate|up)$",  (None, AXIS_FSDP, AXIS_TENSOR)),      # (L, D, F)
    (r"w_down$",       (None, AXIS_TENSOR, AXIS_FSDP)),      # (L, F, D)
    (r"norm",          (None,)),                             # replicated norms
])

# MoE adds expert-stacked FFN weights: (L, E, D, F) — experts over the expert
# axis, FFN dims as dense llama.
MOE_RULES = ShardingRules(rules=[
    (r"experts/w_(gate|up)$", (None, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR)),
    (r"experts/w_down$",      (None, AXIS_EXPERT, AXIS_TENSOR, AXIS_FSDP)),
    (r"router",               (None,)),
] + LLAMA_RULES.rules)

# ViT encoder params (see models/vit.py): same Megatron layout as llama —
# fsdp shards d_model (reduction) dims, tensor shards heads / mlp-hidden;
# position embeddings and norms replicated.
VIT_RULES = ShardingRules(rules=[
    (r"patch_embed$",  (None, AXIS_FSDP)),            # (P²C, D)
    (r"pos_embed$",    (None,)),                      # (N, D) replicated
    (r"wqkv$",         (None, AXIS_FSDP, AXIS_TENSOR)),  # (L, D, 3D)
    (r"wo$",           (None, AXIS_TENSOR, AXIS_FSDP)),  # (L, D, D)
    (r"w_up$",         (None, AXIS_FSDP, AXIS_TENSOR)),  # (L, D, M)
    (r"w_down$",       (None, AXIS_TENSOR, AXIS_FSDP)),  # (L, M, D)
    (r"head$",         (AXIS_FSDP, AXIS_TENSOR)),     # (D, n_classes)
    (r"ln|norm",       (None,)),
])

# Activations: batch over (dcn, data, fsdp), sequence over context, vocab-dim
# logits over tensor.
ACT_RULES = ShardingRules(rules=[
    (r"tokens|targets|mask", (BATCH_AXES, AXIS_CONTEXT)),
    (r"logits",              (BATCH_AXES, AXIS_CONTEXT, AXIS_TENSOR)),
])


def batch_sharding(mesh):
    """Sharding for a (B, S) token batch: batch over data-like axes, sequence
    over the context axis. Delegates to ACT_RULES so the pruning logic lives
    in exactly one place."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, ACT_RULES.spec_for("tokens", mesh))
