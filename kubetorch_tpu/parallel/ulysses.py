"""Ulysses-style sequence parallelism: all-to-all head scatter.

The second context-parallel strategy (SURVEY §5.7) besides ring attention:
instead of rotating K/V chunks around a ring, one ``all_to_all`` re-shards
the activations from sequence-sharded to **head-sharded**, every device runs
full-sequence attention for its head subset, and a second ``all_to_all``
restores sequence sharding. Two collectives per attention — better than the
ring when heads ≥ devices and sequence chunks are small enough that ring
latency dominates; worse at very long sequences (full-S attention memory per
device). Selectable per-config: ``attn_impl="ulysses"``.

Shapes inside shard_map over axis C (= ulysses degree, mesh axis "context"):
  local q: (B, S/C, N, Hd) ── all_to_all ──> (B, S, N/C, Hd)
  full-seq attention on N/C heads (flash kernel when on TPU)
  out: (B, S, N/C, Hd) ── all_to_all ──> (B, S/C, N, Hd)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _heads_to_seq(x: jax.Array, axis: str) -> jax.Array:
    """(B, S, N/C, Hd) → (B, S/C, N, Hd)."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _seq_to_heads(x: jax.Array, axis: str) -> jax.Array:
    """(B, S/C, N, Hd) → (B, S, N/C, Hd)."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "context", causal: bool = True,
                      scale: Optional[float] = None) -> jax.Array:
    """Per-shard Ulysses attention. Local shapes: (B, S/C, N, Hd); requires
    C | N and C | NKV. Must run inside shard_map with ``axis_name`` bound."""
    n, nkv = q.shape[2], k.shape[2]
    from .mesh import lax_axis_size
    c = lax_axis_size(axis_name)
    if n % c or nkv % c:
        raise ValueError(
            f"ulysses degree {c} must divide n_heads={n} and n_kv_heads={nkv}")

    qh = _seq_to_heads(q, axis_name)      # (B, S, N/C, Hd)
    kh = _seq_to_heads(k, axis_name)
    vh = _seq_to_heads(v, axis_name)

    from ..models.llama import _xla_attention

    scale = scale or q.shape[-1] ** -0.5
    if jax.default_backend() == "tpu":
        try:
            from ..ops.attention import flash_attention
            out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
        except Exception:
            out = _xla_attention(qh, kh, vh, scale, causal=causal)
    else:
        out = _xla_attention(qh, kh, vh, scale, causal=causal)

    return _heads_to_seq(out, axis_name)  # (B, S/C, N, Hd)


def ulysses_attention_sharded(q, k, v, mesh, *, causal: bool = True,
                              scale: Optional[float] = None,
                              batch_axes=("dcn", "data", "fsdp"),
                              context_axis: str = "context",
                              head_axis: str = "tensor"):
    """GSPMD wrapper mirroring ``ring_attention_sharded``: q/k/v are global
    (B, S, N, Hd) arrays sequence-sharded over the context axis; head
    sharding over the tensor axis is preserved (no silent all-gather)."""
    from jax.sharding import PartitionSpec as P

    from .mesh import live_axes
    live = live_axes(mesh)
    if context_axis not in live:
        # no context sharding: same fallback ladder as the ring wrapper —
        # flash only on TPU (off-TPU the kernel would silently run in the
        # slow Pallas interpreter), XLA reference otherwise
        if jax.default_backend() == "tpu":
            try:
                from ..ops.attention import flash_attention
                return flash_attention(q, k, v, causal=causal, scale=scale)
            except Exception:
                pass
        from ..models.llama import _xla_attention
        return _xla_attention(q, k, v, scale or q.shape[-1] ** -0.5,
                              causal=causal)
    from .mesh import normalize_batch_axes
    ba = normalize_batch_axes(live, batch_axes)
    # preserve head sharding over tensor only when the ulysses degree still
    # divides the LOCAL head counts; otherwise replicate heads (the pre-TP
    # behavior) instead of crashing GQA configs
    c = live[context_axis]
    t = live.get(head_axis, 1)
    ha = head_axis if (head_axis in live and
                       (q.shape[2] // t) % c == 0 and
                       (k.shape[2] // t) % c == 0 and
                       q.shape[2] % t == 0 and k.shape[2] % t == 0) else None
    spec = P(ba, context_axis, ha, None)

    fn = functools.partial(ulysses_attention, axis_name=context_axis,
                           causal=causal, scale=scale)
    from .mesh import shard_map_fn
    return shard_map_fn()(fn, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check_vma=False)(q, k, v)
