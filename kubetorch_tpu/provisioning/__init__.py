"""Provisioning: TPU slice topology, manifest builders, autoscaling, queues."""

from .tpu_topology import TpuSlice, parse_tpu_spec
from .manifests import build_deployment_manifest, build_service_manifest

__all__ = ["TpuSlice", "parse_tpu_spec", "build_deployment_manifest",
           "build_service_manifest"]
