"""Pod bootstrap: make ANY python image serve as a kubetorch pod.

Reference analog: ``provisioning/templates/kt_setup_template.sh.j2`` —
raise rlimits, detect python, install the framework into the image at pod
start, exec the server. TPU-first difference: instead of ``uv pip install
kubetorch[server]`` from an index (cluster egress), the framework tree is
pulled from the in-cluster data store over plain HTTP with nothing but the
python stdlib (GET /tree/{key}/manifest, then GET /blob/{hash} per file) —
the same CAS the 1-2s code-sync loop uses, so the wheel-less dev build that
deployed the workload is byte-identical to what pods run.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

FRAMEWORK_TREE_KEY = "__kt_framework__"

# sh, not bash: slim/alpine images may lack bash. `exec` replaces the shell
# so SIGTERM from the kubelet reaches the server directly.
BOOTSTRAP_SCRIPT = r'''set -e
ulimit -n 65535 2>/dev/null || true
PY="$(command -v python3 || command -v python || true)"
if [ -z "$PY" ]; then echo "kt-bootstrap: no python in image" >&2; exit 1; fi
if ! "$PY" -c "import kubetorch_tpu" 2>/dev/null; then
  if [ -z "$KT_DATA_STORE_URL" ]; then
    echo "kt-bootstrap: kubetorch_tpu not in image and no KT_DATA_STORE_URL to fetch it from" >&2
    exit 1
  fi
  echo "kt-bootstrap: fetching framework from $KT_DATA_STORE_URL"
  "$PY" - <<'PYEOF'
import json, os, urllib.request
store = os.environ["KT_DATA_STORE_URL"].rstrip("/")
key = os.environ.get("KT_FRAMEWORK_TREE_KEY", "__kt_framework__")
dest = os.environ.get("KT_BOOTSTRAP_DIR", "/kt/framework")
pkg_root = os.path.join(dest, "kubetorch_tpu")
with urllib.request.urlopen(f"{store}/tree/{key}/manifest", timeout=60) as r:
    files = json.load(r)["files"]
for rel, info in sorted(files.items()):
    target = os.path.join(pkg_root, rel)
    os.makedirs(os.path.dirname(target), exist_ok=True)
    with urllib.request.urlopen(f"{store}/blob/{info['hash']}", timeout=600) as r:
        data = r.read()
    with open(target, "wb") as f:
        f.write(data)
    os.chmod(target, info.get("mode", 0o644))
print(f"kt-bootstrap: fetched {len(files)} files -> {pkg_root}", flush=True)
PYEOF
  export PYTHONPATH="${KT_BOOTSTRAP_DIR:-/kt/framework}${PYTHONPATH:+:$PYTHONPATH}"
fi
if ! "$PY" -c "import aiohttp, requests" 2>/dev/null; then
  # bare image without the server deps: install them from the index
  # (reference `uv pip install kubetorch[server]` does the same at pod
  # start; clusters without egress should bake deps into the image)
  echo "kt-bootstrap: installing server dependencies"
  if command -v uv >/dev/null 2>&1; then
    uv pip install --system aiohttp requests click pyyaml msgpack || \
      "$PY" -m pip install --no-input aiohttp requests click pyyaml msgpack
  else
    "$PY" -m pip install --no-input aiohttp requests click pyyaml msgpack
  fi
fi
exec "$PY" -m kubetorch_tpu.serving.http_server --port "${KT_SERVER_PORT:-32300}"
'''


def bootstrap_command() -> List[str]:
    """The pod container command: a self-contained /bin/sh bootstrap."""
    return ["/bin/sh", "-c", BOOTSTRAP_SCRIPT]


def package_root() -> str:
    """The kubetorch_tpu package directory (what pods need on PYTHONPATH's
    first entry, under a dir literally named ``kubetorch_tpu``)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def push_framework(store_url: str,
                   key: str = FRAMEWORK_TREE_KEY) -> Optional[Dict]:
    """Delta-push the framework package tree to the data store so bootstrap
    pods can pull it. Content-hashed: a warm push with no code changes is a
    single round trip (the same property the code-sync loop relies on)."""
    from ..data_store.sync import push_tree
    return push_tree(store_url, key, package_root())
