"""Control-plane installer: apply the ``deploy/`` stack to a cluster.

Reference analog: the kubetorch helm chart (``charts/kubetorch``) — CRDs,
controller, data-store, Kueue wiring, the Prometheus metrics stack and Loki.
Here the same stack is plain YAML under ``deploy/``, applied doc-by-doc
through kubectl so it works with any kubectl-compatible endpoint (including
the recording fake in tests).
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

# apply order matters: CRDs and namespace before the things that use them,
# observability last (it scrapes whatever exists)
DEPLOY_ORDER = [
    "kubetorchworkload-crd.yaml",
    "knative-serving.yaml",   # CRDs + control plane autoscaled services need
    "controller.yaml",
    "data-store.yaml",
    "kueue-resources.yaml",
    "metrics.yaml",
    "loki.yaml",
]

NAMESPACE_DOC = {"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": "kubetorch"}}


def deploy_dir() -> str:
    override = os.environ.get("KT_DEPLOY_DIR")
    if override:
        return override
    # repo checkout layout: deploy/ beside the package
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "deploy")


def _kubectl(kubectl: Optional[str]) -> str:
    from ..utils.kubectl import resolve_kubectl
    resolved = resolve_kubectl(kubectl)
    if resolved is None:
        raise RuntimeError("kubectl not found; cannot install the stack")
    return resolved


def _apply_doc(kubectl: str, doc: Dict) -> None:
    ns = doc.get("metadata", {}).get("namespace", "default")
    res = subprocess.run([kubectl, "apply", "-n", ns, "-f", "-"],
                         input=json.dumps(doc), text=True,
                         capture_output=True, timeout=120)
    if res.returncode != 0:
        name = doc.get("metadata", {}).get("name", "?")
        raise RuntimeError(f"apply {doc.get('kind')}/{name} failed: "
                           f"{res.stderr.strip()}")


def install_stack(kubectl: Optional[str] = None,
                  skip: Sequence[str] = (),
                  directory: Optional[str] = None) -> List[Tuple[str, str, str]]:
    """Apply every manifest doc in ``deploy/`` in dependency order.

    ``skip`` filters by filename substring (e.g. ``["loki"]``). Returns
    ``(filename, kind, name)`` per applied doc.
    """
    import yaml

    import warnings

    kc = _kubectl(kubectl)
    root = directory or deploy_dir()
    applied: List[Tuple[str, str, str]] = []
    _apply_doc(kc, NAMESPACE_DOC)
    applied.append(("<namespace>", "Namespace", "kubetorch"))
    for fname in DEPLOY_ORDER:
        if any(s in fname for s in skip):
            continue
        path = os.path.join(root, fname)
        if not os.path.exists(path):
            warnings.warn(f"deploy manifest missing on disk: {path}",
                          stacklevel=2)
            continue
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if not doc:
                    continue
                _apply_doc(kc, doc)
                applied.append((fname, doc.get("kind", "?"),
                                doc.get("metadata", {}).get("name", "?")))
    # a deploy/*.yaml not in DEPLOY_ORDER would otherwise no-op silently
    unlisted = sorted(f for f in os.listdir(root)
                      if f.endswith((".yaml", ".yml"))
                      and f not in DEPLOY_ORDER)
    if unlisted:
        warnings.warn(f"deploy manifests not in DEPLOY_ORDER (NOT applied): "
                      f"{unlisted}", stacklevel=2)
    return applied
