"""Kubernetes manifest builders.

Reference analog: ``provisioning/utils.py`` build_deployment_manifest (:418) /
build_knative_manifest (:476) / build_raycluster_manifest (:542) plus the
Jinja pod template. TPU-first differences:

- TPU workloads build a **JobSet-style sticky Deployment** with
  ``google.com/tpu`` container resources, ``gke-tpu-accelerator/topology``
  node selectors, and a headless service for rank discovery — slice hosts
  must co-schedule, so the pod template pins one pod per TPU host with a
  hostname-ordered index (the JobSet pattern).
- No SYS_PTRACE by default (pdb runs in-process over WS); enabled only when
  debugging is requested.
"""

from __future__ import annotations

import copy
import posixpath
from typing import Any, Dict, List, Optional

from .tpu_topology import TpuSlice

KT_LABEL_PREFIX = "kubetorch.com"
SERVER_PORT = 32300


def _labels(name: str, username: Optional[str] = None,
            extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    labels = {f"{KT_LABEL_PREFIX}/service": name,
              f"{KT_LABEL_PREFIX}/managed": "true"}
    if username:
        labels[f"{KT_LABEL_PREFIX}/username"] = username
    if extra:
        labels.update(extra)
    return labels


def build_pod_template(name: str, image: str, env: Dict[str, str],
                       cpus: Optional[str] = None, memory: Optional[str] = None,
                       tpu: Optional[TpuSlice] = None,
                       gpus: Optional[int] = None,
                       gpu_type: Optional[str] = None,
                       node_selector: Optional[Dict[str, str]] = None,
                       tolerations: Optional[List[Dict]] = None,
                       volumes: Optional[List[Dict]] = None,
                       shm_size: Optional[str] = "8Gi",
                       launch_timeout: int = 900,
                       debug: bool = False,
                       command: Optional[List[str]] = None,
                       secrets: Optional[List[Dict]] = None,
                       bootstrap: bool = True) -> Dict[str, Any]:
    resources: Dict[str, Dict[str, str]] = {"requests": {}, "limits": {}}
    if cpus:
        resources["requests"]["cpu"] = str(cpus)
    if memory:
        resources["requests"]["memory"] = memory
    if tpu is not None:
        resources["limits"].update(tpu.container_resources())
        resources["requests"].update(tpu.container_resources())
    if gpus:
        resources["limits"]["nvidia.com/gpu"] = str(gpus)

    selectors = dict(node_selector or {})
    if tpu is not None:
        selectors.update(tpu.node_selectors())
    if gpu_type:
        # reference _get_node_selector (compute.py:2217): "key: value"
        # targets a custom label, bare values the GFD product label
        if ":" in gpu_type:
            key, value = gpu_type.split(":", 1)
            selectors[key.strip()] = value.strip()
        else:
            selectors["nvidia.com/gpu.product"] = gpu_type

    if command is None:
        if bootstrap:
            # self-contained bootstrap (reference kt_setup_template.sh.j2):
            # an image that bundles the framework execs the server
            # immediately; a bare python image pulls the framework tree
            # from the data store first. One command for both, so ANY image
            # with a shell works unmodified.
            from .bootstrap import bootstrap_command
            command = bootstrap_command()
        else:
            # shell-less images (distroless) that bundle the framework
            command = ["python", "-m", "kubetorch_tpu.serving.http_server",
                       "--port", str(SERVER_PORT)]
    container: Dict[str, Any] = {
        "name": "kt-server",
        "image": image,
        "command": command,
        "ports": [{"containerPort": SERVER_PORT}],
        "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
        "resources": {k: v for k, v in resources.items() if v},
        "volumeMounts": [{"name": "shm", "mountPath": "/dev/shm"}],
        "startupProbe": {
            "httpGet": {"path": "/health", "port": SERVER_PORT},
            "periodSeconds": 5,
            # reference derives failureThreshold from launch_timeout
            "failureThreshold": max(1, launch_timeout // 5),
        },
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": SERVER_PORT},
            "periodSeconds": 10,
        },
    }
    if debug:
        container["securityContext"] = {"capabilities": {"add": ["SYS_PTRACE"]}}

    pod_volumes: List[Dict[str, Any]] = [
        {"name": "shm", "emptyDir": {"medium": "Memory",
                                     **({"sizeLimit": shm_size} if shm_size else {})}},
    ]
    for vol in volumes or []:
        pod_volumes.append({"name": vol["name"],
                            "persistentVolumeClaim": {"claimName": vol["claim"]}})
        container["volumeMounts"].append({"name": vol["name"],
                                          "mountPath": vol["mount_path"]})

    # secrets ride as REFERENCES — per-key valueFrom + Secret volume mounts;
    # values stay in the Secret object (reference
    # kubernetes_secrets_client.py: inlining them in the manifest would leak
    # plaintext into workload records and persisted controller state).
    # Per-key, not blanket envFrom: envFrom would also inject the __file__
    # credential payload as an env var on Kubernetes.
    for sec in secrets or []:
        sname = sec["name"] if isinstance(sec, dict) else sec
        keys = sec.get("keys") if isinstance(sec, dict) else None
        if keys:
            container["env"].extend(
                {"name": k, "valueFrom": {"secretKeyRef":
                                          {"name": sname, "key": k}}}
                for k in keys)
        elif not (isinstance(sec, dict) and sec.get("mount_path")):
            # name-only ref (e.g. a plain string): keys unknown, fall back
            # to envFrom — safe because refs without a mount carry no
            # __file__ payload
            container.setdefault("envFrom", []).append(
                {"secretRef": {"name": sname}})
        mount = sec.get("mount_path") if isinstance(sec, dict) else None
        if mount:
            mount = ("/root" + mount[1:]) if mount.startswith("~") else mount
            vol_name = f"secret-{sname}"[:63]
            fname = posixpath.basename(mount)
            pod_volumes.append({
                "name": vol_name,
                # the file payload lives in a SEPARATE <name>-file Secret
                # (Secret.save): the base object must stay safe to expand
                # via blanket envFrom
                "secret": {"secretName": f"{sname}-file",
                           "defaultMode": 0o600,
                           "items": [{"key": "__file__", "path": fname}]}})
            # subPath overlays ONLY the credential file — mounting the
            # volume at dirname would mask the whole directory read-only
            # (e.g. ~/.cache/huggingface would lose its hub/ cache)
            container["volumeMounts"].append(
                {"name": vol_name, "mountPath": mount, "subPath": fname,
                 "readOnly": True})

    spec: Dict[str, Any] = {
        "containers": [container],
        "volumes": pod_volumes,
        "terminationGracePeriodSeconds": 30,
    }
    if selectors:
        spec["nodeSelector"] = selectors
    if tolerations:
        spec["tolerations"] = tolerations
    elif tpu is not None:
        spec["tolerations"] = [{"key": "google.com/tpu", "operator": "Exists",
                                "effect": "NoSchedule"}]
    return spec


def build_deployment_manifest(name: str, namespace: str, replicas: int,
                              pod_spec: Dict[str, Any],
                              username: Optional[str] = None,
                              annotations: Optional[Dict[str, str]] = None,
                              queue_name: Optional[str] = None) -> Dict[str, Any]:
    labels = _labels(name, username)
    if queue_name:
        labels["kueue.x-k8s.io/queue-name"] = queue_name
    manifest = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "labels": labels,
                     "annotations": annotations or {}},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {f"{KT_LABEL_PREFIX}/service": name}},
            "template": {
                "metadata": {"labels": labels},
                "spec": pod_spec,
            },
        },
    }
    if queue_name:
        # Kueue admission: created suspended (reference compute.py:1710-1758)
        manifest["spec"]["paused"] = True
    return manifest


def build_service_manifest(name: str, namespace: str,
                           headless: bool = False) -> Dict[str, Any]:
    svc_name = f"{name}-headless" if headless else name
    spec: Dict[str, Any] = {
        "selector": {f"{KT_LABEL_PREFIX}/service": name},
        "ports": [{"port": SERVER_PORT, "targetPort": SERVER_PORT,
                   "name": "http"}],
    }
    if headless:
        spec["clusterIP"] = "None"
        spec["publishNotReadyAddresses"] = True
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": svc_name, "namespace": namespace,
                         "labels": _labels(name)},
            "spec": spec}


def build_knative_manifest(name: str, namespace: str, pod_spec: Dict[str, Any],
                           autoscaling_annotations: Dict[str, str],
                           username: Optional[str] = None) -> Dict[str, Any]:
    """Knative Service for autoscaled (scale-to-zero) workloads."""
    return {
        "apiVersion": "serving.knative.dev/v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": _labels(name, username)},
        "spec": {"template": {
            "metadata": {"annotations": autoscaling_annotations,
                         "labels": _labels(name, username)},
            "spec": pod_spec,
        }},
    }


def build_jobset_manifest(name: str, namespace: str, tpu: TpuSlice,
                          pod_spec: Dict[str, Any],
                          username: Optional[str] = None) -> Dict[str, Any]:
    """JobSet for multi-host TPU slices: all hosts of a slice co-schedule
    atomically with exclusive topology placement (SURVEY §7 hard-part 2)."""
    return {
        "apiVersion": "jobset.x-k8s.io/v1alpha2",
        "kind": "JobSet",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": _labels(name, username),
                     "annotations": {
                         "alpha.jobset.sigs.k8s.io/exclusive-topology":
                             "cloud.google.com/gke-nodepool"}},
        "spec": {"replicatedJobs": [{
            "name": "workers",
            "replicas": 1,
            "template": {"spec": {
                "parallelism": tpu.num_hosts,
                "completions": tpu.num_hosts,
                "backoffLimit": 0,
                "template": {"metadata": {"labels": _labels(name, username)},
                             "spec": {**copy.deepcopy(pod_spec),
                                      "restartPolicy": "Never",
                                      "subdomain": f"{name}-headless"}},
            }},
        }]},
    }


def build_raycluster_manifest(name: str, namespace: str, replicas: int,
                              pod_spec: Dict[str, Any],
                              username: Optional[str] = None,
                              annotations: Optional[Dict[str, str]] = None
                              ) -> Dict[str, Any]:
    """KubeRay RayCluster (reference ``build_raycluster_manifest``,
    provisioning/utils.py:542): one head group + ``replicas - 1`` workers,
    all running the kt pod server so the deploy/reload/log plane works
    identically — the Ray supervisor inside the pods forms the Ray cluster
    (``serving/ray_supervisor.py``), with head discovery via the headless
    service like the SPMD path."""
    labels = _labels(name, username)
    head_spec = copy.deepcopy(pod_spec)
    worker_spec = copy.deepcopy(pod_spec)
    for spec, role in ((head_spec, "head"), (worker_spec, "worker")):
        for container in spec.get("containers", []):
            container.setdefault("env", []).append(
                {"name": "KT_RAY_ROLE", "value": role})
    return {
        "apiVersion": "ray.io/v1",
        "kind": "RayCluster",
        "metadata": {"name": name, "namespace": namespace, "labels": labels,
                     "annotations": annotations or {}},
        "spec": {
            "headGroupSpec": {
                "rayStartParams": {"dashboard-host": "0.0.0.0"},
                "template": {"metadata": {"labels": labels},
                             "spec": head_spec},
            },
            "workerGroupSpecs": [{
                "groupName": "workers",
                "replicas": max(0, replicas - 1),
                "minReplicas": max(0, replicas - 1),
                "maxReplicas": max(0, replicas - 1),
                "rayStartParams": {},
                "template": {"metadata": {"labels": labels},
                             "spec": worker_spec},
            }],
        },
    }


def nested_merge(base: Dict, override: Dict) -> Dict:
    """Deep-merge override into base (reference provisioning/utils.py:200)."""
    out = copy.deepcopy(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = nested_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out
