"""kubectl port-forward manager for laptop → cluster access.

Reference (``globals.py:123-366``): a cached ``kubectl port-forward`` to the
controller's nginx, with ``service_url()`` returning in-cluster DNS when
running inside the cluster and ``http://localhost:<pf>`` outside; atexit
cleanup. Same shape here, targeting the controller service (which proxies
``/{ns}/{service}:{port}/{path}`` onward).
"""

from __future__ import annotations

import atexit
import os
import subprocess
import threading
from typing import Dict, Optional

from ..utils.procs import free_port, kill_process_tree, wait_for_port

_lock = threading.Lock()
_handles: Dict[str, "PFHandle"] = {}


class PFHandle:
    def __init__(self, target: str, local_port: int, proc: subprocess.Popen):
        self.target = target
        self.local_port = local_port
        self.proc = proc

    @property
    def url(self) -> str:
        return f"http://localhost:{self.local_port}"

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def close(self) -> None:
        if self.alive:
            kill_process_tree(self.proc.pid)


def in_cluster() -> bool:
    return os.path.exists("/var/run/secrets/kubernetes.io/serviceaccount/token")


def ensure_port_forward(service: str = "kubetorch-controller",
                        namespace: str = "kubetorch",
                        remote_port: int = 8080) -> PFHandle:
    """Cached kubectl port-forward to a cluster service."""
    key = f"{namespace}/{service}:{remote_port}"
    with _lock:
        handle = _handles.get(key)
        if handle is not None and handle.alive:
            return handle
        from ..utils.kubectl import resolve_kubectl
        kubectl = resolve_kubectl()
        if kubectl is None:
            raise RuntimeError("kubectl not found; cannot port-forward")
        local = free_port()
        proc = subprocess.Popen(
            [kubectl, "port-forward", f"svc/{service}",
             f"{local}:{remote_port}", "-n", namespace],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        if not wait_for_port("127.0.0.1", local, timeout=15):
            kill_process_tree(proc.pid)
            raise RuntimeError(f"port-forward to {key} failed")
        handle = PFHandle(key, local, proc)
        _handles[key] = handle
        atexit.register(close_all)
        return handle


def service_url(service: str, namespace: str = "default",
                port: int = 32300) -> str:
    """In-cluster DNS inside the cluster, controller-proxied URL outside
    (reference ``service_url`` :302)."""
    if in_cluster():
        return f"http://{service}.{namespace}.svc.cluster.local:{port}"
    pf = ensure_port_forward()
    return f"{pf.url}/{namespace}/{service}:{port}"


def close_all() -> None:
    with _lock:
        for handle in _handles.values():
            handle.close()
        _handles.clear()
