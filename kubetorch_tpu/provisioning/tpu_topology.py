"""TPU slice topology resolution: ``tpu="v5p-64"`` → schedulable GKE shape.

The TPU-native analog of the reference's GPU spec handling
(``resources/compute/compute.py`` gpus/gpu_type/gpu_memory): a TPU request is
not "N devices" but an *atomic slice* — a v5p-64 is 8 hosts × 4 chips wired
in a 3D ICI torus that must co-schedule (SURVEY §7 hard-part 2). This module
owns the accelerator table: chips/host, cores/chip, valid topologies, GKE
machine types and the ``cloud.google.com/gke-tpu-*`` node selectors.

Naming conventions follow Cloud TPU: v4/v5p sizes count *TensorCores*
(2/chip); v5e/v6e sizes count chips.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class TpuGeneration:
    name: str                    # v4 | v5e | v5p | v6e
    gke_accelerator: str         # node selector value
    machine_type: str            # GKE TPU VM machine type prefix
    chips_per_host: int
    cores_per_chip: int
    sizes_in_cores: bool         # True: vXp-N counts cores; False: chips
    topology_3d: bool            # 3D ICI torus (v4/v5p) vs 2D (v5e/v6e)
    hbm_gb_per_chip: int
    peak_bf16_tflops: float


GENERATIONS: Dict[str, TpuGeneration] = {
    "v4": TpuGeneration("v4", "tpu-v4-podslice", "ct4p-hightpu-4t",
                        4, 2, True, True, 32, 275),
    "v5e": TpuGeneration("v5e", "tpu-v5-lite-podslice", "ct5lp-hightpu-4t",
                         4, 1, False, False, 16, 197),
    "v5p": TpuGeneration("v5p", "tpu-v5p-slice", "ct5p-hightpu-4t",
                         4, 2, True, True, 95, 459),
    "v6e": TpuGeneration("v6e", "tpu-v6e-slice", "ct6e-standard-4t",
                         4, 1, False, False, 32, 918),
}

# Valid 2D topologies for v5e/v6e (chips): x*y grids
_2D_TOPOLOGIES = {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8",
                  64: "8x8", 128: "8x16", 256: "16x16"}


@dataclass(frozen=True)
class TpuSlice:
    generation: TpuGeneration
    chips: int
    topology: str            # e.g. "2x4" or "2x2x4"
    num_hosts: int

    @property
    def chips_per_host(self) -> int:
        return min(self.generation.chips_per_host, self.chips)

    @property
    def total_hbm_gb(self) -> int:
        return self.chips * self.generation.hbm_gb_per_chip

    @property
    def peak_bf16_tflops(self) -> float:
        return self.chips * self.generation.peak_bf16_tflops

    def node_selectors(self) -> Dict[str, str]:
        return {
            "cloud.google.com/gke-tpu-accelerator": self.generation.gke_accelerator,
            "cloud.google.com/gke-tpu-topology": self.topology,
        }

    def container_resources(self) -> Dict[str, str]:
        return {"google.com/tpu": str(self.chips_per_host)}


def _3d_topology(chips: int) -> str:
    """Smallest-surface 3D torus factorization of ``chips`` (each dim ≥ 1,
    dims multiples of the 4-chip host tray: prefer balanced cubes)."""
    best: Optional[Tuple[int, int, int]] = None
    for x in range(1, int(round(chips ** (1 / 3))) + 2):
        if chips % x:
            continue
        rest = chips // x
        for y in range(x, int(math.isqrt(rest)) + 1):
            if rest % y:
                continue
            z = rest // y
            cand = (x, y, z)
            if best is None or _surface(cand) < _surface(best):
                best = cand
    if best is None:
        best = (1, 1, chips)
    return "x".join(str(d) for d in best)


def _surface(dims: Tuple[int, int, int]) -> int:
    x, y, z = dims
    return x * y + y * z + x * z


def parse_tpu_spec(spec: str) -> TpuSlice:
    """``"v5p-64"`` / ``"v5e-8"`` / ``"v5litepod-16"`` / ``"v6e-256"`` →
    :class:`TpuSlice`. Also accepts explicit topology: ``"v5e:4x4"``."""
    spec = spec.strip().lower().replace("v5litepod", "v5e").replace("v5lite", "v5e")

    topo_match = re.fullmatch(r"(v\d+[ep]?):(\d+x\d+(?:x\d+)?)", spec)
    if topo_match:
        gen_name, topology = topo_match.groups()
        gen = _generation(gen_name)
        chips = math.prod(int(d) for d in topology.split("x"))
        return _slice_for(gen, chips, topology)

    m = re.fullmatch(r"(v\d+[ep]?)-(\d+)", spec)
    if not m:
        raise ValueError(
            f"Unrecognized TPU spec {spec!r}; expected e.g. 'v5p-64', "
            f"'v5e-8', or 'v5e:4x4'")
    gen = _generation(m.group(1))
    size = int(m.group(2))
    chips = size // gen.cores_per_chip if gen.sizes_in_cores else size
    if chips < 1:
        raise ValueError(f"TPU spec {spec!r} resolves to zero chips")
    return _slice_for(gen, chips, None)


def _generation(name: str) -> TpuGeneration:
    if name not in GENERATIONS:
        raise ValueError(f"Unknown TPU generation {name!r}; "
                         f"known: {sorted(GENERATIONS)}")
    return GENERATIONS[name]


def _slice_for(gen: TpuGeneration, chips: int, topology: Optional[str]) -> TpuSlice:
    if topology is None:
        if gen.topology_3d:
            topology = _3d_topology(chips)
        else:
            if chips not in _2D_TOPOLOGIES:
                raise ValueError(
                    f"{gen.name} slice of {chips} chips is not a valid shape; "
                    f"valid: {sorted(_2D_TOPOLOGIES)}")
            topology = _2D_TOPOLOGIES[chips]
    num_hosts = max(1, chips // gen.chips_per_host)
    return TpuSlice(generation=gen, chips=chips, topology=topology,
                    num_hosts=num_hosts)
