"""Unified resilience layer: retry/backoff policies, circuit breaking, and
deadline propagation.

The paper's promise is that infrastructure faults surface as *typed,
catchable, recoverable* exceptions — but a taxonomy is only recoverable if
the call layers actually recover. This module is the one place retry
semantics live for all three of them:

- ``serving/http_client.py`` — user calls. Safe retries only: a connection
  that was never established is always retryable; an established POST is
  retried *only* when the caller passed an ``idempotency_key`` (the server
  dedupes it, see :class:`IdempotencyCache`).
- ``data_store/netpool.py`` — store ops. Content-addressed and therefore
  idempotent: retried by default, honoring ``Retry-After`` on 503.
- ``client.py`` (controller) — idempotent verbs retried; POSTs only when the
  connection was never established.

Deadline propagation rides the ``X-KT-Deadline`` header (absolute unix
seconds): the server rejects requests whose deadline already passed *before*
dispatch and cancels dispatch when it passes *during* — a request the client
abandoned must not burn a TPU slot. The server-side checks live in
``serving/http_server.py``; the header/clock helpers live here.

Determinism: backoff jitter is drawn from a policy-owned ``random.Random``
seeded via ``seed=`` (or ``KT_RETRY_SEED``), so a test — or the chaos
harness in :mod:`kubetorch_tpu.chaos` — can assert the exact backoff
sequence with :meth:`RetryPolicy.preview_delays`.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import requests as _requests

from . import telemetry
from .exceptions import CircuitOpenError, DeadlineExceededError

# Flight-recorder hooks (ISSUE 5): every retry attempt, backoff sleep,
# breaker transition, and deadline rejection is a span event on whatever
# request is active plus a registry counter — so a chaos test (or an
# operator) can assert retries *through traces* instead of sleep-counting.
_RETRIES = telemetry.counter(
    "kt_retry_attempts_total",
    "Retries performed by RetryPolicy.run/arun, by trigger",
    labels=("reason",))
_DEADLINE_REJECTED = telemetry.counter(
    "kt_deadline_rejections_total",
    "Calls abandoned because the propagated deadline expired",
    labels=("where",))
_BREAKER_TRANSITIONS = telemetry.counter(
    "kt_breaker_transitions_total",
    "Circuit-breaker state transitions",
    labels=("to",))


def _record_retry(attempt: int, delay: float, reason: str, **attrs) -> None:
    _RETRIES.inc(reason=reason)
    telemetry.add_event("retry", attempt=attempt,
                        delay_s=round(delay, 6), reason=reason, **attrs)
    telemetry.observe_stage("retry_sleep", delay)


def _record_deadline(where: str, deadline_at: float) -> None:
    _DEADLINE_REJECTED.inc(where=where)
    telemetry.add_event("deadline_rejected", where=where,
                        deadline=deadline_at)

# HTTP statuses that mean "the server (or something in front of it) is
# transiently unhappy" — safe to retry when the request itself is idempotent.
RETRYABLE_STATUSES = frozenset({502, 503, 504})

# requests exceptions that can occur AFTER the connection was established
# (the request may have executed server-side — only idempotent retries).
ESTABLISHED_TRANSIENT_EXCS = (
    _requests.exceptions.ConnectionError,
    _requests.exceptions.Timeout,
    _requests.exceptions.ChunkedEncodingError,   # truncated body mid-stream
    _requests.exceptions.ContentDecodingError,
)

# Substrings that prove the TCP connection was never established, so the
# request cannot have executed server-side and is ALWAYS safe to retry
# (same markers the scaled-to-zero proxy fallback keys on).
_NEVER_ESTABLISHED_MARKERS = (
    "NewConnectionError",
    "Connection refused",
    "Name or service not known",
    "No route to host",
    "Temporary failure in name resolution",
)


def connection_never_established(exc: BaseException) -> bool:
    """True when a ``requests`` connection error happened before any byte hit
    the wire — the server cannot have executed the request."""
    return isinstance(exc, _requests.exceptions.ConnectionError) and any(
        marker in str(exc) for marker in _NEVER_ESTABLISHED_MARKERS)


def retry_after_seconds(resp: Any) -> Optional[float]:
    """Parse a ``Retry-After`` header (seconds form) off a response-like
    object; None when absent/unparseable. HTTP-date form is not worth
    supporting on an internal data plane."""
    raw = getattr(resp, "headers", {}).get("Retry-After")
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Deadline propagation
# ---------------------------------------------------------------------------

DEADLINE_HEADER = "X-KT-Deadline"


@dataclass(frozen=True)
class Deadline:
    """An absolute wall-clock deadline (unix seconds) that crosses process
    and host boundaries via :data:`DEADLINE_HEADER`. Wall clock, not
    monotonic, because the pod enforcing it is a different machine than the
    client that set it; NTP-level skew is noise next to the multi-second
    budgets this guards."""

    at: float

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(at=time.time() + seconds)

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["Deadline"]:
        if not value:
            return None
        try:
            return cls(at=float(value))
        except (TypeError, ValueError):
            return None

    def header_value(self) -> str:
        return f"{self.at:.6f}"

    def remaining(self) -> float:
        return self.at - time.time()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


# ---------------------------------------------------------------------------
# Retry policy
# ---------------------------------------------------------------------------


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ[name])
    except (KeyError, TypeError, ValueError):
        return default


def _env_seed() -> Optional[int]:
    raw = os.environ.get("KT_RETRY_SEED")
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


@dataclass
class AttemptInfo:
    """Passed to the attempt callable so it can bound its own I/O."""

    index: int                      # 0-based attempt number
    timeout: Optional[float]        # per-attempt timeout, deadline-clamped
    deadline: Optional[Deadline]    # overall deadline, for header propagation


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter, per-attempt timeout, and an
    overall deadline.

    ``run`` drives an attempt callable; classification of *what* is
    retryable belongs to the call site (each call layer has different
    idempotency rules), so it arrives as predicates. Delay for attempt *i*
    is ``uniform(0, min(max_delay, base_delay * multiplier**i))`` — AWS-style
    full jitter, deterministic under ``seed``.
    """

    max_attempts: int = 3
    base_delay: float = 0.2
    max_delay: float = 10.0
    multiplier: float = 2.0
    attempt_timeout: Optional[float] = None   # per-attempt I/O timeout
    deadline: Optional[float] = None          # overall budget, seconds
    jitter: bool = True
    seed: Optional[int] = field(default_factory=_env_seed)

    def _delay(self, rng: random.Random, attempt: int) -> float:
        cap = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        return rng.uniform(0.0, cap) if self.jitter else cap

    def preview_delays(self, n: int) -> List[float]:
        """The first ``n`` backoff delays this policy will sleep, computed
        from a fresh RNG — with ``seed`` set this is exactly the sequence a
        ``run`` records, which is what the deterministic chaos tests
        assert against."""
        rng = random.Random(self.seed)
        return [self._delay(rng, i) for i in range(n)]

    def run(
        self,
        fn: Callable[[AttemptInfo], Any],
        *,
        retryable_exc: Callable[[BaseException], bool],
        response_retry_delay: Optional[Callable[[Any], Any]] = None,
        breaker: Optional["CircuitBreaker"] = None,
        deadline: Optional[Deadline] = None,
        record: Optional[List[float]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> Any:
        """Call ``fn`` until it succeeds, exhausts ``max_attempts``, or the
        deadline expires.

        - ``retryable_exc(exc)`` — True to retry after an exception.
        - ``response_retry_delay(resp)`` — ``None``: accept the response;
          ``True``: retry on the policy's backoff; a float: retry after at
          least that many seconds (``Retry-After``). The final attempt's
          response is returned as-is so the caller surfaces the real error.
        - ``record`` — appended with each slept delay (test introspection).
        """
        if deadline is None and self.deadline is not None:
            deadline = Deadline.after(self.deadline)
        rng = random.Random(self.seed)
        attempt = 0
        while True:
            if deadline is not None and deadline.expired():
                _record_deadline("before_attempt", deadline.at)
                raise DeadlineExceededError(
                    f"deadline expired before attempt {attempt + 1}",
                    deadline=deadline.at)
            if breaker is not None:
                breaker.allow()
            timeout = self.attempt_timeout
            if deadline is not None:
                rem = max(0.001, deadline.remaining())
                timeout = rem if timeout is None else min(timeout, rem)
            last = attempt >= self.max_attempts - 1
            try:
                resp = fn(AttemptInfo(index=attempt, timeout=timeout,
                                      deadline=deadline))
            except BaseException as e:  # noqa: BLE001 — classify, then re-raise
                if breaker is not None and isinstance(e, Exception):
                    breaker.record_failure()
                if last or not retryable_exc(e):
                    raise
                delay = self._delay(rng, attempt)
                retry_info = {"reason": "exception",
                              "error": type(e).__name__}
            else:
                verdict = (response_retry_delay(resp)
                           if response_retry_delay is not None else None)
                if verdict is None:
                    if breaker is not None:
                        breaker.record_success()
                    return resp
                if breaker is not None:
                    breaker.record_failure()
                if last:
                    return resp
                delay = self._delay(rng, attempt)
                if verdict is not True:
                    delay = max(delay, float(verdict))
                retry_info = {"reason": "status",
                              "status": getattr(resp, "status_code", None)
                              or getattr(resp, "status", None)}
            if deadline is not None and deadline.remaining() <= delay:
                _record_deadline("backoff", deadline.at)
                raise DeadlineExceededError(
                    f"deadline would expire during backoff after attempt "
                    f"{attempt + 1}", deadline=deadline.at)
            if record is not None:
                record.append(delay)
            _record_retry(attempt, delay, **retry_info)
            sleep(delay)
            attempt += 1

    async def arun(
        self,
        fn: Callable[[AttemptInfo], Any],
        *,
        retryable_exc: Callable[[BaseException], bool],
        response_retry_delay: Optional[Callable[[Any], Any]] = None,
        breaker: Optional["CircuitBreaker"] = None,
        deadline: Optional[Deadline] = None,
        record: Optional[List[float]] = None,
    ) -> Any:
        """Async twin of :meth:`run` (``fn`` is awaited; backoff is
        ``asyncio.sleep``). Kept as a parallel body rather than a shared
        generator so both read as straight-line control flow."""
        import asyncio

        if deadline is None and self.deadline is not None:
            deadline = Deadline.after(self.deadline)
        rng = random.Random(self.seed)
        attempt = 0
        while True:
            if deadline is not None and deadline.expired():
                _record_deadline("before_attempt", deadline.at)
                raise DeadlineExceededError(
                    f"deadline expired before attempt {attempt + 1}",
                    deadline=deadline.at)
            if breaker is not None:
                breaker.allow()
            timeout = self.attempt_timeout
            if deadline is not None:
                rem = max(0.001, deadline.remaining())
                timeout = rem if timeout is None else min(timeout, rem)
            last = attempt >= self.max_attempts - 1
            try:
                resp = await fn(AttemptInfo(index=attempt, timeout=timeout,
                                            deadline=deadline))
            except BaseException as e:  # noqa: BLE001
                if breaker is not None and isinstance(e, Exception):
                    breaker.record_failure()
                if last or not retryable_exc(e):
                    raise
                delay = self._delay(rng, attempt)
                retry_info = {"reason": "exception",
                              "error": type(e).__name__}
            else:
                verdict = (response_retry_delay(resp)
                           if response_retry_delay is not None else None)
                if verdict is None:
                    if breaker is not None:
                        breaker.record_success()
                    return resp
                if breaker is not None:
                    breaker.record_failure()
                if last:
                    return resp
                delay = self._delay(rng, attempt)
                if verdict is not True:
                    delay = max(delay, float(verdict))
                retry_info = {"reason": "status",
                              "status": getattr(resp, "status_code", None)
                              or getattr(resp, "status", None)}
            if deadline is not None and deadline.remaining() <= delay:
                _record_deadline("backoff", deadline.at)
                raise DeadlineExceededError(
                    f"deadline would expire during backoff after attempt "
                    f"{attempt + 1}", deadline=deadline.at)
            if record is not None:
                record.append(delay)
            _record_retry(attempt, delay, **retry_info)
            await asyncio.sleep(delay)
            attempt += 1


def _cfg_attempts(field: str, default: int) -> int:
    """Attempt count from the layered config (``~/.kt/config`` file under
    ``KT_*`` env, see config.py). The env var also reaches here when the
    config singleton was built before the var was set — tests and pods
    mutate env at runtime."""
    try:
        from .config import config
        return max(1, int(config().get(field, default)))
    except Exception:
        return default


def store_policy() -> RetryPolicy:
    """Data-plane default: every store op is content-addressed (idempotent),
    so retries are on by default. ``KT_STORE_RETRIES=1`` restores the old
    single-shot behavior."""
    return RetryPolicy(
        max_attempts=max(1, _env_int("KT_STORE_RETRIES",
                                  _cfg_attempts("store_retries", 3))),
        base_delay=_env_float("KT_STORE_RETRY_BASE_S", 0.2),
        max_delay=_env_float("KT_STORE_RETRY_MAX_S", 5.0),
    )


def http_policy() -> RetryPolicy:
    """Serving-path default (``HTTPClient``). The attempt count only matters
    for the *safe* retry classes; a non-idempotent established POST is never
    re-sent regardless."""
    return RetryPolicy(
        max_attempts=max(1, _env_int("KT_HTTP_RETRIES",
                                  _cfg_attempts("http_retries", 3))),
        base_delay=_env_float("KT_HTTP_RETRY_BASE_S", 0.2),
        max_delay=_env_float("KT_HTTP_RETRY_MAX_S", 5.0),
    )


def controller_policy() -> RetryPolicy:
    """Control-plane default: small and snappy — controller calls sit on the
    interactive path."""
    return RetryPolicy(
        max_attempts=max(1, _env_int("KT_CONTROLLER_RETRIES",
                                  _cfg_attempts("controller_retries", 3))),
        base_delay=_env_float("KT_CONTROLLER_RETRY_BASE_S", 0.1),
        max_delay=_env_float("KT_CONTROLLER_RETRY_MAX_S", 2.0),
    )


def restart_policy(max_restarts: Optional[int] = None) -> RetryPolicy:
    """Worker-watchdog default (``serving/watchdog.py``): backoff slept
    before each rank-pool respawn, so a crash-looping worker doesn't burn
    the whole restart budget in one watchdog tick. Deterministic under
    ``KT_RETRY_SEED`` like every other policy — the chaos suite asserts the
    respawn cadence with :meth:`RetryPolicy.preview_delays`."""
    return RetryPolicy(
        max_attempts=max(1, max_restarts if max_restarts is not None
                         else _env_int("KT_RESTART_BUDGET",
                                       _cfg_attempts("restart_budget", 3))),
        base_delay=_env_float("KT_RESTART_BACKOFF_BASE_S", 0.2),
        max_delay=_env_float("KT_RESTART_BACKOFF_MAX_S", 5.0),
    )


# ---------------------------------------------------------------------------
# Restart budget (sliding window)
# ---------------------------------------------------------------------------


class RestartBudget:
    """Sliding-window counter bounding self-healing: at most ``budget``
    acquisitions per ``window_s`` seconds, thread-safe.

    The shape retry counters can't express: a rank pool that dies once an
    hour should self-heal forever, while one that dies five times in a
    minute is crash-looping (bad weights, poisoned TPU runtime, host OOM
    pressure) and must fail *permanently and typed* rather than flap
    ``/ready`` for eternity. Old acquisitions age out of the window, so the
    budget regenerates on its own.
    """

    def __init__(self, budget: int, window_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget = max(0, int(budget))
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: deque = deque()

    def _evict(self, now: float) -> None:
        while self._events and now - self._events[0] > self.window_s:
            self._events.popleft()

    def try_acquire(self) -> bool:
        """Consume one restart if the window has room; False = exhausted."""
        with self._lock:
            now = self._clock()
            self._evict(now)
            if len(self._events) >= self.budget:
                return False
            self._events.append(now)
            return True

    @property
    def used(self) -> int:
        with self._lock:
            self._evict(self._clock())
            return len(self._events)

    @property
    def remaining(self) -> int:
        return max(0, self.budget - self.used)

    def state(self) -> Dict[str, Any]:
        return {"budget": self.budget, "window_s": self.window_s,
                "used": self.used, "remaining": self.remaining}


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Classic three-state breaker, thread-safe.

    - *closed*: calls flow; ``failure_threshold`` consecutive failures open it.
    - *open*: :meth:`allow` raises :class:`CircuitOpenError` (with
      ``retry_after``) until ``cooldown_s`` elapses.
    - *half-open*: one probe call is admitted; success closes the circuit,
      failure re-opens it for a fresh cool-down.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probe_out = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @staticmethod
    def _transition(to: str) -> None:
        """Counter + span event per state change — breaker trips become
        queryable (and assertable) instead of vanishing into fast-fails."""
        _BREAKER_TRANSITIONS.inc(to=to)
        telemetry.add_event("breaker_transition", to=to)

    def allow(self) -> None:
        with self._lock:
            if self._state == "closed":
                return
            if self._state == "open":
                elapsed = self._clock() - self._opened_at
                if elapsed < self.cooldown_s:
                    telemetry.add_event("breaker_rejected",
                                        failures=self._failures)
                    raise CircuitOpenError(
                        f"circuit open ({self._failures} consecutive "
                        f"failures); retry in "
                        f"{self.cooldown_s - elapsed:.2f}s",
                        retry_after=self.cooldown_s - elapsed)
                self._state = "half-open"
                self._probe_out = False
                self._transition("half-open")
            # half-open: admit exactly one probe at a time
            if self._probe_out:
                telemetry.add_event("breaker_rejected", probe_in_flight=True)
                raise CircuitOpenError(
                    "circuit half-open; probe already in flight",
                    retry_after=self.cooldown_s)
            self._probe_out = True

    def record_success(self) -> None:
        with self._lock:
            was = self._state
            self._state = "closed"
            self._failures = 0
            self._probe_out = False
            if was != "closed":
                self._transition("closed")

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half-open":
                self._state = "open"
                self._opened_at = self._clock()
                self._probe_out = False
                self._transition("open")
                return
            self._failures += 1
            if self._state == "closed" and \
                    self._failures >= self.failure_threshold:
                self._state = "open"
                self._opened_at = self._clock()
                self._transition("open")

    def call(self, fn: Callable[[], Any]) -> Any:
        """Convenience wrapper for a single guarded call."""
        self.allow()
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


# ---------------------------------------------------------------------------
# Server-side idempotency dedupe
# ---------------------------------------------------------------------------


class IdempotencyCache:
    """TTL cache of completed responses keyed by ``X-KT-Idempotency-Key``.

    The contract that makes POST retries safe: the client only re-sends a
    non-idempotent call when it attached a key, and the server replays the
    recorded response for a key it has already *completed* — the user
    function never executes twice. Single-event-loop use (aiohttp), so no
    lock; entries are (status, body, headers) tuples.
    """

    def __init__(self, ttl_s: float = 600.0, max_entries: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._clock = clock
        self._done: "OrderedDict[str, Tuple[float, Any]]" = OrderedDict()
        self.inflight: Dict[str, Any] = {}   # key → asyncio.Future

    def __len__(self) -> int:
        self._purge()
        return len(self._done)

    def _purge(self) -> None:
        now = self._clock()
        while self._done:
            key, (ts, _) = next(iter(self._done.items()))
            if now - ts <= self.ttl_s:
                break
            self._done.popitem(last=False)

    def lookup(self, key: str) -> Optional[Any]:
        self._purge()
        entry = self._done.get(key)
        return entry[1] if entry is not None else None

    def store(self, key: str, value: Any) -> None:
        self._purge()
        self._done[key] = (self._clock(), value)
        self._done.move_to_end(key)
        while len(self._done) > self.max_entries:
            self._done.popitem(last=False)
