"""User-facing resource API: Compute, Fn/Cls/App, Image, Volume, Secret."""
