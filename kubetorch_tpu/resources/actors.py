"""ActorMesh: single-controller actor programming over the pod fabric.

Reference analog: the Monarch mode (``serving/monarch_supervisor.py``) — a
Rust ``process_allocator`` daemon on every pod plus a hyperactor mesh. The
TPU-native rebuild needs neither: the pod runtime already hosts a live class
instance per pod (SPMD supervisor + ``Cls``), so an actor mesh is a *client
view* — selective dispatch (one actor), multicast (a subset), broadcast
(all), and async futures — over exactly the same pods. State lives per pod
and survives across calls; on TPU each actor owns its host's chips.

    mesh = kt.actors(MyActor, init_kwargs={...}).to(
        kt.Compute(tpu="v5e-8").distribute("actor", workers=2))
    mesh.act(0).step(x)                 # one actor
    mesh.all().sync_weights(ckpt)       # broadcast
    fut = mesh.act(1).rollout.remote()  # async future
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Sequence, Type, Union

from .cls import Cls
from .module import module_factory


class _ActorMethod:
    def __init__(self, mesh: "ActorMesh", selector, name: str):
        self.mesh = mesh
        self.selector = selector
        self.name = name

    def __call__(self, *args, timeout: Optional[float] = None, **kwargs):
        from .module import extract_call_config
        call_cfg = extract_call_config(kwargs)
        result = self.mesh._module._http_client().call_method(
            self.mesh._module.pointers.cls_or_fn_name, method=self.name,
            args=args, kwargs=kwargs, workers=self.selector, timeout=timeout,
            **call_cfg)
        if isinstance(self.selector, list) and len(self.selector) == 1 and \
                isinstance(result, list) and len(result) == 1:
            return result[0]
        return result

    def remote(self, *args, **kwargs) -> Future:
        """Fire-and-collect future (the actor-model async call)."""
        return self.mesh._executor.submit(self.__call__, *args, **kwargs)


class _ActorHandle:
    def __init__(self, mesh: "ActorMesh", selector):
        self._mesh = mesh
        self._selector = selector

    def __getattr__(self, name: str) -> _ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _ActorMethod(self._mesh, self._selector, name)


class ActorMesh:
    def __init__(self, module: Cls):
        self._module = module
        self._executor = ThreadPoolExecutor(max_workers=64)

    def to(self, compute) -> "ActorMesh":
        if compute.distributed is None:
            compute = compute.distribute("spmd", workers=1)
        elif compute.distributed.distribution_type == "actor":
            # actors ride the SPMD fabric; never mutate the caller's Compute
            # (the fluent convention is clone-on-change)
            compute = compute.clone()
            compute.distributed.distribution_type = "spmd"
        self._module.to(compute)
        return self

    @property
    def world_size(self) -> int:
        c = self._module.compute
        return c.replicas if c else 1

    def act(self, index: int) -> _ActorHandle:
        """Handle to one actor (pod ``index`` in sorted-IP order)."""
        return _ActorHandle(self, [index])

    def actors(self, indices: Sequence[int]) -> _ActorHandle:
        return _ActorHandle(self, list(indices))

    def all(self) -> _ActorHandle:
        return _ActorHandle(self, "all")

    def ready(self) -> _ActorHandle:
        """Only actors whose pods pass health checks (elastic dispatch)."""
        return _ActorHandle(self, "ready")

    def teardown(self) -> None:
        self._module.teardown()
        self._executor.shutdown(wait=False)


def actors(klass: Type, name: Optional[str] = None,
           init_args: Optional[list] = None,
           init_kwargs: Optional[dict] = None) -> ActorMesh:
    """``kt.actors(Learner)`` — deployable actor mesh."""
    ia = None
    if init_args or init_kwargs:
        ia = {"args": list(init_args or []), "kwargs": init_kwargs or {}}
    module = module_factory(klass, name=name, init_args=ia, cls_type=Cls)
    return ActorMesh(module)
