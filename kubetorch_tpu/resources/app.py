"""App: an arbitrary server process managed as a kubetorch service.

Reference (``resources/compute/app.py``): ``kt run python serve.py`` — the
user's command is appended to the image instructions as CMD; the pod runtime
starts it as a child process and proxies health through ``/app/status``.
"""

from __future__ import annotations

import shlex
from typing import Dict, Optional

from ..config import config
from ..utils.naming import service_name_for
from .compute import Compute
from .module import Module
from .pointers import Pointers


class App(Module):
    callable_type = "app"

    def __init__(self, command: str, name: Optional[str] = None,
                 port: Optional[int] = None, health_path: str = "/"):
        # Apps have no importable callable; pointers carry only the name.
        pointers = Pointers(project_root=".", module_name="", file_path="",
                            cls_or_fn_name=name or "app")
        base = name or shlex.split(command)[-1].split("/")[-1].split(".")[0]
        super().__init__(pointers, name=base)
        self.command = command
        self.port = port
        self.health_path = health_path

    def _metadata(self) -> Dict:
        meta = {
            "KT_CALLABLE_TYPE": "app",
            "KT_SERVICE_NAME": self.name,
            "KT_APP_CMD": self.command,
        }
        if self.port:
            meta["KT_APP_PORT"] = str(self.port)
        if self.compute:
            meta["KT_DOCKERFILE"] = self.compute.image.cmd(self.command).dockerfile()
        return meta

    def status(self) -> Dict:
        import requests
        r = requests.get(f"{self.service_url}/app/status", timeout=10)
        return r.json()


def app(command: str, name: Optional[str] = None, port: Optional[int] = None) -> App:
    """``kt.app("python serve.py", port=8000)`` — deploy a server process."""
    return App(command, name=name, port=port)
