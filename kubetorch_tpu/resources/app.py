"""App: an arbitrary server process managed as a kubetorch service.

Reference (``resources/compute/app.py``): ``kt run python serve.py`` — the
user's command is appended to the image instructions as CMD; the pod runtime
starts it as a child process and proxies health through ``/app/status``.
"""

from __future__ import annotations

import shlex
from typing import Dict, Optional

from ..config import config
from ..utils.naming import service_name_for
from .compute import Compute
from .module import Module
from .pointers import Pointers


class App(Module):
    callable_type = "app"

    def __init__(self, command: str, name: Optional[str] = None,
                 port: Optional[int] = None, health_path: str = "/"):
        # Apps have no importable callable; pointers carry only the name.
        pointers = Pointers(project_root=".", module_name="", file_path="",
                            cls_or_fn_name=name or "app")
        super().__init__(pointers, name=name or _name_from_command(command))
        self.command = command
        self.port = port
        self.health_path = health_path

    def _metadata(self) -> Dict:
        meta = {
            "KT_CALLABLE_TYPE": "app",
            "KT_SERVICE_NAME": self.name,
            "KT_APP_CMD": self.command,
        }
        if self.port:
            meta["KT_APP_PORT"] = str(self.port)
        if self.compute:
            # never mutate the user's Image: redeploys would stack CMDs and
            # replay/restart the app on every no-op .to()
            import copy
            image = copy.deepcopy(self.compute.image)
            meta["KT_DOCKERFILE"] = image.cmd(self.command).dockerfile()
        return meta

    def status(self) -> Dict:
        import requests
        r = requests.get(f"{self.service_url}/app/status", timeout=10)
        return r.json()


def _name_from_command(command: str) -> str:
    """Service name from the most script-like token: first *.py/*.sh/*.js
    basename, else the first non-flag token's basename, else 'app'.

    "python serve.py --verbose" → serve; "python -m http.server 8000" →
    http-server (never '--verbose' or '8000')."""
    tokens = shlex.split(command)
    for tok in tokens:
        base = tok.rsplit("/", 1)[-1]
        if base.endswith((".py", ".sh", ".js")):
            return base.rsplit(".", 1)[0]
    for i, tok in enumerate(tokens):
        if tok == "-m" and i + 1 < len(tokens):
            return tokens[i + 1]
        if not tok.startswith("-") and tok not in ("python", "python3", "node",
                                                   "bash", "sh", "uv", "uvx"):
            return tok.rsplit("/", 1)[-1]
    return "app"


def app(command: str, name: Optional[str] = None, port: Optional[int] = None) -> App:
    """``kt.app("python serve.py", port=8000)`` — deploy a server process."""
    return App(command, name=name, port=port)
