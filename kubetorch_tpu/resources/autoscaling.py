"""Autoscaling configuration → Knative annotations.

Reference (``provisioning/autoscaling.py``): a validated bag of Knative KPA/
HPA knobs emitted as ``autoscaling.knative.dev/*`` annotations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

VALID_METRICS = ("concurrency", "rps", "cpu", "memory")


@dataclass
class AutoscalingConfig:
    target: Optional[int] = None
    metric: str = "concurrency"
    window: Optional[str] = None            # e.g. "60s"
    min_scale: int = 0
    max_scale: Optional[int] = None
    initial_scale: Optional[int] = None
    scale_down_delay: Optional[str] = None
    scale_to_zero_retention: Optional[str] = None
    container_concurrency: Optional[int] = None

    def __post_init__(self):
        if self.metric not in VALID_METRICS:
            raise ValueError(f"metric must be one of {VALID_METRICS}")
        if self.min_scale < 0:
            raise ValueError("min_scale must be >= 0")
        if self.max_scale is not None and self.max_scale < max(self.min_scale, 1):
            raise ValueError("max_scale must be >= max(min_scale, 1)")
        for name in ("window", "scale_down_delay", "scale_to_zero_retention"):
            v = getattr(self, name)
            if v is not None and not str(v).endswith(("s", "m", "h")):
                raise ValueError(f"{name} must be a duration like '60s'")

    @property
    def autoscaler_class(self) -> str:
        # cpu/memory need the HPA class; concurrency/rps use KPA
        return "hpa.autoscaling.knative.dev" if self.metric in ("cpu", "memory") \
            else "kpa.autoscaling.knative.dev"

    def annotations(self) -> Dict[str, str]:
        pre = "autoscaling.knative.dev"
        out = {f"{pre}/class": self.autoscaler_class,
               f"{pre}/metric": self.metric,
               f"{pre}/min-scale": str(self.min_scale)}
        if self.target is not None:
            out[f"{pre}/target"] = str(self.target)
        if self.window:
            out[f"{pre}/window"] = self.window
        if self.max_scale is not None:
            out[f"{pre}/max-scale"] = str(self.max_scale)
        if self.initial_scale is not None:
            out[f"{pre}/initial-scale"] = str(self.initial_scale)
        if self.scale_down_delay:
            out[f"{pre}/scale-down-delay"] = self.scale_down_delay
        if self.scale_to_zero_retention:
            out[f"{pre}/scale-to-zero-pod-retention-period"] = \
                self.scale_to_zero_retention
        return out
