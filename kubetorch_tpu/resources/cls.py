"""Cls: remote class proxy — every public method becomes a remote call
(reference ``resources/callables/cls/cls.py``: __getattr__ :54-68, server-side
instantiation with init args)."""

from __future__ import annotations

from typing import Any, Optional, Type

from .module import Module, module_factory


class Cls(Module):
    callable_type = "cls"

    def __getattr__(self, attr: str) -> Any:
        # only called when normal lookup fails → remote method proxy
        if attr.startswith("_"):
            raise AttributeError(attr)

        def remote_method(*args, workers=None, timeout=None, **kwargs):
            if not self.is_deployed:
                raise RuntimeError(
                    f"{self.pointers.cls_or_fn_name} is not deployed; call "
                    f".to(kt.Compute(...)) first")
            from .module import extract_call_config
            call_cfg = extract_call_config(kwargs)
            return self._http_client().call_method(
                self.pointers.cls_or_fn_name, method=attr, args=args,
                kwargs=kwargs, workers=workers, timeout=timeout, **call_cfg)

        remote_method.__name__ = attr
        return remote_method


def cls(klass: Type, name: Optional[str] = None, init_args: Optional[list] = None,
        init_kwargs: Optional[dict] = None) -> Cls:
    """``kt.cls(Model, init_kwargs={...})`` → remote stateful service; the
    instance is constructed server-side in the rank subprocess."""
    ia = None
    if init_args or init_kwargs:
        ia = {"args": list(init_args or []), "kwargs": init_kwargs or {}}
    return module_factory(klass, name=name, init_args=ia, cls_type=Cls)
