"""Compute: declarative resource spec → running service.

Reference (``resources/compute/compute.py``, 2798 LoC) with the accelerator
model inverted: ``tpu="v5p-64"`` is the first-class spec (an atomic slice —
replicas = slice hosts, co-scheduled), ``gpus=`` is accepted for API
compatibility but routes to a plain device-count request.

``.distribute()`` gains the ``mesh`` argument — on TPU, parallelism is a
launcher concern (SURVEY §2.4: the reference has no TP/PP/SP/EP because torch
delegates them to user code; JAX does not).
"""

from __future__ import annotations

import copy
import dataclasses
import uuid
from typing import Any, Dict, List, Optional, Union

from ..client import controller_client
from ..config import config
from ..exceptions import ServiceTimeoutError
from ..parallel.mesh import DistributedConfig
from ..provisioning.manifests import (build_deployment_manifest,
                                      build_pod_template)
from ..provisioning.tpu_topology import TpuSlice, parse_tpu_spec
from .autoscaling import AutoscalingConfig
from .image import Image


class Compute:
    def __init__(self,
                 cpus: Optional[Union[int, str]] = None,
                 memory: Optional[str] = None,
                 tpu: Optional[str] = None,
                 gpus: Optional[int] = None,
                 gpu_type: Optional[str] = None,
                 gpu_memory: Optional[str] = None,
                 image: Optional[Image] = None,
                 env: Optional[Dict[str, str]] = None,
                 volumes: Optional[List] = None,
                 secrets: Optional[List] = None,
                 node_selector: Optional[Dict[str, str]] = None,
                 tolerations: Optional[List[Dict]] = None,
                 inactivity_ttl: Optional[int] = None,
                 queue_name: Optional[str] = None,
                 namespace: Optional[str] = None,
                 selector: Optional[Dict[str, str]] = None,
                 launch_timeout: Optional[int] = None,
                 shm_size: Optional[str] = "8Gi",
                 priority: Optional[Union[int, str]] = None,
                 drain_grace_s: Optional[float] = None):
        self.cpus = cpus
        self.memory = memory
        self.tpu_spec = tpu
        self.tpu: Optional[TpuSlice] = parse_tpu_spec(tpu) if tpu else None
        self.gpus = gpus
        # GPU routing (reference compute.py:40-80): gpu_type → node selector
        # ("nvidia.com/gpu.product" or an explicit "key: value"); gpu_memory
        # → "gpu-memory" pod annotation (a whole GPU is still requested, the
        # device plugin enforces the memory limit). On this framework the
        # first-class accelerator is tpu=; these exist for API parity.
        self.gpu_type = gpu_type
        self.gpu_memory = gpu_memory
        if (gpu_type or gpu_memory) and not gpus:
            self.gpus = 1
        self.image = image or Image()
        self.env = dict(env or {})
        self.volumes = list(volumes or [])
        self.secrets = list(secrets or [])
        self.node_selector = dict(node_selector or {})
        self.tolerations = tolerations
        self.inactivity_ttl = inactivity_ttl
        self.queue_name = queue_name
        self.namespace = namespace or config().namespace
        self.selector = selector            # BYO mode: no manifest, just route
        self.launch_timeout = launch_timeout or config().launch_timeout
        self.shm_size = shm_size
        # Scheduling tier (ISSUE 8): an int 0-100 or a tier name
        # ("high"/"normal"/"batch"). Higher tiers may preempt strictly
        # lower ones when the capacity book is full; preempted workloads
        # drain (checkpoint) and resume automatically. None → the
        # controller's default tier; drain_grace_s bounds the SIGTERM→
        # eviction window a preemption grants this workload's pods.
        self.priority = priority
        self.drain_grace_s = drain_grace_s
        self.autoscaling: Optional[AutoscalingConfig] = None
        self.distributed: Optional[DistributedConfig] = None
        self.endpoint = None                # custom routing (from_manifest)
        self._user_manifest: Optional[Dict] = None
        self._pod_template_path: Optional[List[str]] = None
        # merge cluster-wide defaults (reference compute.py:1963), routed
        # through the same parsing the constructor kwargs get
        for key, val in controller_defaults().items():
            if key == "tpu":
                if self.tpu is None and val:
                    self.tpu_spec = val
                    self.tpu = parse_tpu_spec(val)
            elif getattr(self, key, None) in (None, {}, []):
                setattr(self, key, val)

    # -- BYO manifest ---------------------------------------------------------

    @classmethod
    def from_manifest(cls, manifest: Union[Dict, str],
                      selector: Optional[Dict[str, str]] = None,
                      endpoint=None,
                      pod_template_path: Optional[Union[str, List[str]]] = None,
                      image: Optional[Image] = None,
                      namespace: Optional[str] = None) -> "Compute":
        """Wrap a user-provided workload manifest (reference ``from_manifest``
        compute.py:271): deploy kubetorch callables onto an existing K8s
        shape instead of a generated one.

        ``manifest`` is a dict or a path to a YAML file. ``selector``
        defaults to the manifest's ``spec.selector.matchLabels``.
        ``pod_template_path`` locates the pod template inside custom CRDs
        (dot-string or key list, reference ``navigate_path``
        compute/utils.py:18-54). ``endpoint`` (an :class:`Endpoint`) routes
        calls to a user URL or a pod subset."""
        if isinstance(manifest, str):
            import yaml

            with open(manifest) as f:
                manifest = yaml.safe_load(f)
        if "kind" not in manifest or "apiVersion" not in manifest:
            raise ValueError("manifest needs 'kind' and 'apiVersion'")
        new = cls(namespace=namespace or manifest.get("metadata", {})
                  .get("namespace"))
        new._user_manifest = copy.deepcopy(manifest)
        new._pod_template_path = (
            pod_template_path.split(".")
            if isinstance(pod_template_path, str) else pod_template_path)
        new.endpoint = endpoint
        if image is not None:
            new.image = image
        new.selector = selector or (manifest.get("spec", {})
                                    .get("selector", {}).get("matchLabels"))
        return new

    def _navigate_pod_template(self, manifest: Dict) -> Dict[str, Any]:
        """Walk to the pod template inside ``manifest``, creating the path
        (reference ``navigate_path`` compute/utils.py:18-54)."""
        node = manifest
        for key in (self._pod_template_path or ["spec", "template"]):
            node = node.setdefault(key, {})
        return node

    def _merged_user_manifest(self, name: str,
                              env: Dict[str, str]) -> Dict[str, Any]:
        """The user's manifest with the kt runtime grafted into its pod
        template (reference ``_build_and_merge_kubetorch_defaults``
        compute.py:391-425): kt env + server command onto the first
        container, kt labels onto template metadata — the user's image,
        resources, and selectors are preserved."""
        out = copy.deepcopy(self._user_manifest)
        out.setdefault("metadata", {}).setdefault("name", name)
        out["metadata"]["namespace"] = self.namespace
        labels = out["metadata"].setdefault("labels", {})
        labels.setdefault("kubetorch.com/service", name)

        kt_pod = self.pod_spec(env)      # our canonical template
        kt_container = kt_pod["spec"]["containers"][0]
        template = self._navigate_pod_template(out)
        tmeta = template.setdefault("metadata", {})
        tmeta.setdefault("labels", {}).update(
            kt_pod.get("metadata", {}).get("labels", {}))
        if self.gpu_memory:
            tmeta.setdefault("annotations", {})["gpu-memory"] = self.gpu_memory
        spec = template.setdefault("spec", {})
        containers = spec.setdefault("containers", [])
        if not containers:
            containers.append(kt_container)
        else:
            c = containers[0]
            have = {e["name"] for e in c.setdefault("env", [])}
            c["env"].extend(e for e in kt_container.get("env", [])
                            if e["name"] not in have)
            c.setdefault("command", kt_container.get("command"))
            c.setdefault("ports", kt_container.get("ports"))
        return out

    # -- fluent config --------------------------------------------------------

    def distribute(self, distribution_type: str = "jax",
                   workers: Optional[int] = None,
                   procs_per_worker: Optional[int] = None,
                   mesh: Optional[Dict[str, int]] = None,
                   restart_procs: bool = False) -> "Compute":
        """Declare the distribution strategy.

        ``workers`` defaults to the TPU slice's host count — a v5p-64 is
        8 hosts, so ``Compute(tpu="v5p-64").distribute("jax")`` is complete.
        """
        new = self.clone()
        if workers is None:
            workers = new.tpu.num_hosts if new.tpu is not None else 1
        new.distributed = DistributedConfig(
            distribution_type=distribution_type, workers=workers,
            procs_per_worker=procs_per_worker, mesh=mesh,
            restart_procs=restart_procs)
        return new

    def autoscale(self, **kwargs) -> "Compute":
        new = self.clone()
        new.autoscaling = AutoscalingConfig(**kwargs)
        return new

    def clone(self) -> "Compute":
        return copy.deepcopy(self)

    # -- derived --------------------------------------------------------------

    @property
    def replicas(self) -> int:
        if self.distributed is not None:
            return max(self.distributed.workers, 1)
        if self.tpu is not None:
            return self.tpu.num_hosts
        return 1

    def distributed_config_dict(self) -> Optional[Dict]:
        return self.distributed.to_dict() if self.distributed else None

    def scheduling_dict(self) -> Optional[Dict[str, Any]]:
        """The deploy body's ``scheduling`` block (ISSUE 8): priority/tier,
        the demanded device class and width, and the drain grace. None when
        the user set nothing — the scheduler then infers demand from the
        manifest and uses the default tier."""
        if self.priority is None and self.drain_grace_s is None:
            return None
        out: Dict[str, Any] = {
            "device_class": (self.tpu.generation.name if self.tpu
                             else "cpu"),
            "width": self.replicas,
        }
        if self.priority is not None:
            out["priority"] = self.priority
        if self.drain_grace_s is not None:
            out["drain_grace_s"] = float(self.drain_grace_s)
        return out

    @property
    def deployment_mode(self) -> str:
        if self._user_manifest is not None:
            return "manifest"               # from_manifest: kt applies it
        if self.selector is not None:
            return "byo"
        if self.autoscaling is not None:
            return "knative"
        if self.tpu is not None and self.tpu.num_hosts > 1:
            # checked BEFORE ray: a multi-host slice cannot give up JobSet's
            # atomic co-scheduling/exclusive-topology placement — the Ray
            # supervisor still forms its cluster inside the JobSet pods
            return "jobset"
        if (self.distributed is not None
                and self.distributed.distribution_type == "ray"):
            return "raycluster"             # KubeRay provisions head+workers
        return "deployment"

    # -- manifest -------------------------------------------------------------

    def pod_spec(self, env: Dict[str, str], command: Optional[List[str]] = None,
                 debug: bool = False) -> Dict[str, Any]:
        merged_env = {**self.env, **env}
        return build_pod_template(
            name="kt", image=self.image.base, env=merged_env,
            cpus=self.cpus, memory=self.memory, tpu=self.tpu,
            gpus=self.gpus, gpu_type=self.gpu_type,
            node_selector=self.node_selector, tolerations=self.tolerations,
            volumes=[v.mount_spec() if hasattr(v, "mount_spec") else v
                     for v in self.volumes],
            shm_size=self.shm_size, launch_timeout=self.launch_timeout,
            debug=debug, command=command,
            bootstrap=getattr(self.image, "bootstrap", True),
            # by reference only — values live in Secret objects (see
            # Secret.ref); inlining them here leaked plaintext into
            # persisted workload records (round-2 VERDICT weak #2)
            secrets=[s.ref() if hasattr(s, "ref") else {"name": str(s)}
                     for s in self.secrets])

    def manifest(self, name: str, env: Dict[str, str],
                 command: Optional[List[str]] = None) -> Dict[str, Any]:
        mode = self.deployment_mode
        if mode == "manifest":
            return self._merged_user_manifest(name, env)
        pod_spec = self.pod_spec(env, command)
        if mode == "knative":
            from ..provisioning.manifests import build_knative_manifest
            return build_knative_manifest(
                name, self.namespace, pod_spec,
                self.autoscaling.annotations(), username=config().username)
        if mode == "jobset":
            from ..provisioning.manifests import build_jobset_manifest
            return build_jobset_manifest(name, self.namespace, self.tpu,
                                         pod_spec, username=config().username)
        if mode == "raycluster":
            from ..provisioning.manifests import build_raycluster_manifest
            return build_raycluster_manifest(
                name, self.namespace, self.replicas, pod_spec,
                username=config().username)
        annotations = {}
        if self.inactivity_ttl:
            annotations["kubetorch.com/inactivity-ttl"] = str(self.inactivity_ttl)
        if self.gpu_memory:
            annotations["gpu-memory"] = self.gpu_memory
        return build_deployment_manifest(
            name, self.namespace, self.replicas, pod_spec,
            username=config().username, queue_name=self.queue_name,
            annotations=annotations or None)

    # -- launch ---------------------------------------------------------------

    def _launch(self, name: str, metadata: Dict[str, Any],
                launch_id: Optional[str] = None) -> Dict[str, Any]:
        """Deploy through the controller (reference ``_launch`` :2006)."""
        launch_id = launch_id or uuid.uuid4().hex
        client = controller_client()
        if self._user_manifest is None and self.selector is not None:
            return client.register_workload(
                self.namespace, name, metadata, selector=self.selector,
                launch_id=launch_id,
                service_url=self.endpoint.url if self.endpoint else None)
        # materialize Secret objects FIRST: the workload manifest references
        # them by name (envFrom / volume mounts), so they must exist before
        # any pod starts
        for secret in self.secrets:
            if hasattr(secret, "save"):
                secret.save(self.namespace)
        # seed the framework tree for bootstrap pods (cluster backend only:
        # local pods import from this checkout). Content-hashed — a warm
        # push with no framework changes is one round trip. Best-effort:
        # images that bundle the framework never read it.
        if client.cluster_config().get("backend") == "kubernetes":
            # resolve like the data plane does (config field, else the
            # controller's cluster config) — most clients never set the
            # raw config field
            from ..data_store.commands import _store_url
            try:
                store = _store_url()
            except Exception:  # noqa: BLE001
                store = None
            if store:
                try:
                    from ..provisioning.bootstrap import push_framework
                    push_framework(store)
                except Exception as e:  # noqa: BLE001
                    import warnings
                    warnings.warn(
                        f"framework push for bootstrap pods failed: {e}",
                        stacklevel=2)
            else:
                import warnings
                warnings.warn(
                    "no data store resolvable: bare-image pods cannot "
                    "bootstrap the framework (images bundling kubetorch_tpu "
                    "are unaffected)", stacklevel=2)
        manifest = self.manifest(name, env={})
        autoscaling = (dataclasses.asdict(self.autoscaling)
                       if self.autoscaling is not None else None)
        expected = self.replicas
        if self._user_manifest is not None:
            expected = int(manifest.get("spec", {}).get("replicas", 1))
        return client.deploy(self.namespace, name, manifest, metadata,
                             launch_id, inactivity_ttl=self.inactivity_ttl,
                             expected_pods=expected,
                             autoscaling=autoscaling,
                             scheduling=self.scheduling_dict(),
                             service_url=(self.endpoint.url
                                          if self.endpoint else None),
                             timeout=self.launch_timeout)

    def _check_service_ready(self, name: str, timeout: Optional[float] = None) -> None:
        """Wait for the controller to report readiness, streaming the K8s
        events it watched (ImagePullBackOff, FailedScheduling, …) as they
        happen and failing FAST — typed, with the event text — when the
        watcher marked the launch unrecoverable (reference live event
        stream during ``.to()`` waits, ``http_client.py:576``)."""
        import logging
        import time as _time

        log = logging.getLogger("kubetorch")
        client = controller_client()
        deadline = _time.monotonic() + (timeout or self.launch_timeout)
        delay = 0.25
        seen_events: Dict[str, None] = {}     # insertion-ordered
        while _time.monotonic() < deadline:
            status = client.check_ready(self.namespace, name)
            for msg in status.get("events") or []:
                if msg not in seen_events:
                    seen_events[msg] = None
                    log.info("%s: %s", name, msg)
            if status.get("ready"):
                return
            failure = status.get("failure")
            if failure:
                from .. import exceptions as _exc
                cls = getattr(_exc, failure.get("error_type", ""),
                              _exc.StartupError)
                raise cls(f"launch of {name!r} failed: "
                          f"{failure.get('message', '')}")
            _time.sleep(delay)
            delay = min(delay * 2, 5.0)
        tail = "".join(f"\n  {m}" for m in list(seen_events)[-5:])
        raise ServiceTimeoutError(
            f"Service {name!r} not ready after "
            f"{timeout or self.launch_timeout}s{tail}")

    def teardown(self, name: str) -> None:
        controller_client().delete_workload(self.namespace, name)


def controller_defaults() -> Dict[str, Any]:
    """Cluster-wide Compute defaults from the controller ConfigMap
    (reference ``service_manager.py:803``). Only consulted when a controller
    is already configured — constructing a Compute must never auto-start one.
    """
    if not config().api_url:
        return {}
    try:
        return controller_client().cluster_config().get("compute_defaults", {})
    except Exception:
        return {}
