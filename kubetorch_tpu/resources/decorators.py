"""Declarative decorators: ``@kt.compute / @kt.distribute / @kt.autoscale /
@kt.async_``.

Reference (``resources/compute/decorators.py``): decorators build a
``PartialModule`` chain that ``kt deploy`` unwinds in CLI deploy mode — at
import time in a normal run they are inert, so the same file works as a plain
script and as a deployable unit.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

DEPLOY_MODE_ENV = "KT_CLI_DEPLOY_MODE"

_REGISTRY: list = []   # PartialModules collected during a `kt deploy` import


class PartialModule:
    """A callable tagged with deployment intent, unwound by `kt deploy`."""

    def __init__(self, obj: Callable):
        self.obj = obj
        self.compute_kwargs: Dict[str, Any] = {}
        self.distribute_kwargs: Optional[Dict[str, Any]] = None
        self.autoscale_kwargs: Optional[Dict[str, Any]] = None
        self.is_async = False
        self.name: Optional[str] = None

    def __call__(self, *args, **kwargs):
        # undecorated behavior outside deploy mode
        return self.obj(*args, **kwargs)

    def build(self):
        """Materialize Fn/Cls + Compute (called by `kt deploy`)."""
        import inspect

        from .cls import cls as cls_factory
        from .compute import Compute
        from .fn import fn as fn_factory

        compute = Compute(**self.compute_kwargs)
        if self.distribute_kwargs:
            compute = compute.distribute(**self.distribute_kwargs)
        if self.autoscale_kwargs:
            compute = compute.autoscale(**self.autoscale_kwargs)
        factory = cls_factory if inspect.isclass(self.obj) else fn_factory
        module = factory(self.obj, name=self.name)
        return module, compute


def _as_partial(obj: Any) -> PartialModule:
    if isinstance(obj, PartialModule):
        return obj
    pm = PartialModule(obj)
    if os.environ.get(DEPLOY_MODE_ENV):
        _REGISTRY.append(pm)
    return pm


def compute(**compute_kwargs) -> Callable:
    """``@kt.compute(cpus=1, tpu="v5e-8")`` — attach a Compute spec."""
    def deco(obj):
        pm = _as_partial(obj)
        name = compute_kwargs.pop("name", None)
        if name:
            pm.name = name
        pm.compute_kwargs.update(compute_kwargs)
        return pm
    return deco


def distribute(distribution_type: str = "jax", **kwargs) -> Callable:
    def deco(obj):
        pm = _as_partial(obj)
        pm.distribute_kwargs = {"distribution_type": distribution_type, **kwargs}
        return pm
    return deco


def autoscale(**kwargs) -> Callable:
    def deco(obj):
        pm = _as_partial(obj)
        pm.autoscale_kwargs = kwargs
        return pm
    return deco


def async_(obj: Any) -> PartialModule:
    pm = _as_partial(obj)
    pm.is_async = True
    return pm


def collected_modules() -> list:
    return list(_REGISTRY)


def clear_registry() -> None:
    _REGISTRY.clear()
