"""Endpoint: custom routing for a service (reference
``resources/compute/endpoint.py``): either a user-provided URL (no Service
object created) or a custom pod selector (e.g. only the coordinator pod of a
slice), rewritten through the controller proxy."""

from __future__ import annotations

from typing import Dict, Optional


class Endpoint:
    def __init__(self, url: Optional[str] = None,
                 selector: Optional[Dict[str, str]] = None,
                 port: int = 32300):
        if (url is None) == (selector is None):
            raise ValueError("Endpoint needs exactly one of url= or selector=")
        self.url = url
        self.selector = selector
        self.port = port

    def to_service_config(self, name: str, namespace: str) -> Dict:
        if self.url is not None:
            return {"url": self.url}
        return {"selector": self.selector, "port": self.port,
                "name": name, "namespace": namespace}

    def __repr__(self) -> str:
        return f"Endpoint(url={self.url!r}, selector={self.selector!r})"
