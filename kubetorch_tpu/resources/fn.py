"""Fn: remote function proxy (reference ``resources/callables/fn/fn.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from .module import Module, module_factory


class Fn(Module):
    callable_type = "fn"

    def __call__(self, *args, workers=None, timeout: Optional[float] = None,
                 stream_logs: Optional[bool] = None,
                 debugger=None, metrics=None, logging=None,
                 **kwargs) -> Any:
        """``debugger=kt.DebugConfig(...)``, ``metrics=kt.MetricsConfig(...)``
        and ``logging=kt.LoggingConfig(...)`` carry per-call behavior
        (reference globals.py config objects)."""
        if not self.is_deployed:
            raise RuntimeError(
                f"{self.pointers.cls_or_fn_name} is not deployed; call "
                f".to(kt.Compute(...)) first")
        # only the TYPED objects are client config here — a plain dict named
        # `metrics`/`logging` belongs to the remote function's own kwargs
        # (pre-existing user signatures must keep working)
        from ..config import LoggingConfig, MetricsConfig
        if metrics is not None and not isinstance(metrics, MetricsConfig):
            kwargs["metrics"], metrics = metrics, None
        if logging is not None and not isinstance(logging, LoggingConfig):
            kwargs["logging"], logging = logging, None
        return self._http_client().call_method(
            self.pointers.cls_or_fn_name, args=args, kwargs=kwargs,
            workers=workers, timeout=timeout, stream_logs=stream_logs,
            debugger=debugger, metrics=metrics, logging=logging)

    async def call_async(self, *args, workers=None,
                         timeout: Optional[float] = None, **kwargs) -> Any:
        return await self._http_client().call_method_async(
            self.pointers.cls_or_fn_name, args=args, kwargs=kwargs,
            workers=workers, timeout=timeout)


def fn(function: Callable, name: Optional[str] = None) -> Fn:
    """``kt.fn(train)`` → deployable remote function."""
    return module_factory(function, name=name, cls_type=Fn)
