"""Fn: remote function proxy (reference ``resources/callables/fn/fn.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from .module import Module, module_factory


class Fn(Module):
    callable_type = "fn"

    def __call__(self, *args, workers=None, timeout: Optional[float] = None,
                 stream_logs: Optional[bool] = None,
                 debugger=None, metrics=None, logging=None,
                 **kwargs) -> Any:
        """``debugger=kt.DebugConfig(...)``, ``metrics=kt.MetricsConfig(...)``
        and ``logging=kt.LoggingConfig(...)`` carry per-call behavior
        (reference globals.py config objects)."""
        if not self.is_deployed:
            raise RuntimeError(
                f"{self.pointers.cls_or_fn_name} is not deployed; call "
                f".to(kt.Compute(...)) first")
        # only TYPED objects are client config — a plain dict named
        # `metrics`/`logging` belongs to the remote function's own kwargs
        # (pre-existing user signatures must keep working). Typed objects
        # under ANY kwarg name route the same way (shared with Cls proxies).
        from ..config import LoggingConfig, MetricsConfig
        from .module import extract_call_config
        if metrics is not None and not isinstance(metrics, MetricsConfig):
            kwargs["metrics"], metrics = metrics, None
        if logging is not None and not isinstance(logging, LoggingConfig):
            kwargs["logging"], logging = logging, None
        call_cfg = extract_call_config(kwargs)
        for slot, named in (("metrics", metrics), ("logging", logging),
                            ("debugger", debugger)):
            if named is not None and call_cfg[slot] is not None:
                raise ValueError(f"two {slot} configs in one call — pass "
                                 "exactly one")
        return self._http_client().call_method(
            self.pointers.cls_or_fn_name, args=args, kwargs=kwargs,
            workers=workers, timeout=timeout, stream_logs=stream_logs,
            debugger=debugger or call_cfg["debugger"],
            metrics=metrics or call_cfg["metrics"],
            logging=logging or call_cfg["logging"])

    async def call_async(self, *args, workers=None,
                         timeout: Optional[float] = None, **kwargs) -> Any:
        # typed config objects must not leak into the remote kwargs (they
        # aren't serializable); the async path has no streaming pumps, so
        # they are extracted and ignored rather than half-honored
        from .module import extract_call_config
        extract_call_config(kwargs)
        return await self._http_client().call_method_async(
            self.pointers.cls_or_fn_name, args=args, kwargs=kwargs,
            workers=workers, timeout=timeout)


def fn(function: Callable, name: Optional[str] = None) -> Fn:
    """``kt.fn(train)`` → deployable remote function."""
    return module_factory(function, name=name, cls_type=Fn)
