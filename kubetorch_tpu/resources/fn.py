"""Fn: remote function proxy (reference ``resources/callables/fn/fn.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from .module import Module, module_factory


class Fn(Module):
    callable_type = "fn"

    def __call__(self, *args, workers=None, timeout: Optional[float] = None,
                 stream_logs: Optional[bool] = None,
                 debugger=None, metrics=None, logging=None,
                 **kwargs) -> Any:
        """``debugger=kt.DebugConfig(...)``, ``metrics=kt.MetricsConfig(...)``
        and ``logging=kt.LoggingConfig(...)`` carry per-call behavior
        (reference globals.py config objects).

        Reserved client kwarg names: ``workers``, ``timeout``,
        ``stream_logs``, ``debugger`` — a remote function's own parameter
        with one of these names must be passed positionally. ``metrics``/
        ``logging`` are NOT reserved: only typed config objects route to
        the client; dicts with those names reach the remote function."""
        if not self.is_deployed:
            raise RuntimeError(
                f"{self.pointers.cls_or_fn_name} is not deployed; call "
                f".to(kt.Compute(...)) first")
        # only TYPED objects are client config — a plain dict named
        # `metrics`/`logging` belongs to the remote function's own kwargs
        # (pre-existing user signatures must keep working). Typed objects
        # under ANY kwarg name route the same way (shared with Cls proxies).
        from ..config import LoggingConfig, MetricsConfig
        from .module import extract_call_config
        if metrics is not None and not isinstance(metrics, MetricsConfig):
            kwargs["metrics"], metrics = metrics, None
        if logging is not None and not isinstance(logging, LoggingConfig):
            kwargs["logging"], logging = logging, None
        call_cfg = extract_call_config(kwargs, metrics=metrics,
                                       logging=logging, debugger=debugger)
        return self._http_client().call_method(
            self.pointers.cls_or_fn_name, args=args, kwargs=kwargs,
            workers=workers, timeout=timeout, stream_logs=stream_logs,
            **call_cfg)

    async def call_async(self, *args, workers=None,
                         timeout: Optional[float] = None, **kwargs) -> Any:
        # the async path has no streaming pumps/debug arming, so typed
        # config objects can't be honored — extracted with a WARNING, not
        # silently dropped (and not leaked into remote kwargs)
        from .module import extract_call_config
        dropped = {k: v for k, v in extract_call_config(kwargs).items() if v}
        if dropped:
            import warnings
            warnings.warn(f"call_async ignores client call-config objects "
                          f"({', '.join(sorted(dropped))}): streaming/debug "
                          "pumps are sync-call features", stacklevel=2)
        return await self._http_client().call_method_async(
            self.pointers.cls_or_fn_name, args=args, kwargs=kwargs,
            workers=workers, timeout=timeout)


def fn(function: Callable, name: Optional[str] = None) -> Fn:
    """``kt.fn(train)`` → deployable remote function."""
    return module_factory(function, name=name, cls_type=Fn)
