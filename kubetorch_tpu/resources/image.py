"""Image: a pseudo-Dockerfile whose instructions replay inside running pods.

Reference (``resources/images/image.py``): the Image is not (only) a build
recipe — its instruction list is diffed and replayed *inside live pods* by
the image-setup cache, which is what makes `pip_install` changes land in
seconds without a rebuild (SURVEY §2.5, §3.4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

DEFAULT_BASE = "python:3.12-slim"


@dataclass
class Instruction:
    kind: str           # RUN | ENV | COPY | CMD | SYNC
    value: str

    def render(self) -> str:
        return f"{self.kind} {self.value}"


class Image:
    def __init__(self, base: str = DEFAULT_BASE, bootstrap: bool = True):
        self.base = base
        self.instructions: List[Instruction] = []
        self.env_vars: Dict[str, str] = {}
        # bootstrap=False: exec the server directly (no /bin/sh) — for
        # shell-less images (distroless) that bundle the framework
        self.bootstrap = bootstrap

    # -- builders (chainable) -------------------------------------------------

    @classmethod
    def from_docker(cls, image: str) -> "Image":
        return cls(base=image)

    @classmethod
    def from_dockerfile(cls, path: str) -> "Image":
        img = cls()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if line.upper().startswith("FROM "):
                    img.base = line.split(None, 1)[1]
                else:
                    kind, _, value = line.partition(" ")
                    img.instructions.append(Instruction(kind.upper(), value))
        return img

    def pip_install(self, packages: List[str] | str) -> "Image":
        if isinstance(packages, str):
            packages = [packages]
        self.instructions.append(
            Instruction("RUN", "$KT_PIP_INSTALL_CMD " + " ".join(packages)))
        return self

    def run_bash(self, command: str) -> "Image":
        self.instructions.append(Instruction("RUN", command))
        return self

    def set_env_vars(self, env: Dict[str, str]) -> "Image":
        self.env_vars.update(env)
        for k, v in env.items():
            self.instructions.append(Instruction("ENV", f"{k}={v}"))
        return self

    def copy(self, src: str, dest: str) -> "Image":
        self.instructions.append(Instruction("COPY", f"{src} {dest}"))
        return self

    def sync_package(self, package: str) -> "Image":
        self.instructions.append(Instruction("SYNC", package))
        return self

    def rsync(self, src: str, dest: str) -> "Image":
        # kept for API parity; sync is the native mechanism
        self.instructions.append(Instruction("SYNC", f"{src} {dest}"))
        return self

    def cmd(self, command: str) -> "Image":
        self.instructions.append(Instruction("CMD", command))
        return self

    # -- rendering ------------------------------------------------------------

    def dockerfile(self) -> str:
        lines = [f"FROM {self.base}"]
        lines += [ins.render() for ins in self.instructions]
        return "\n".join(lines)

    def __repr__(self) -> str:
        return f"Image(base={self.base!r}, instructions={len(self.instructions)})"
