"""Builtin image presets (reference ``resources/images/images.py``)."""

from .image import Image


def debian() -> Image:
    return Image.from_docker("debian:bookworm-slim").run_bash(
        "apt-get update && apt-get install -y python3 python3-pip")


def python(version: str = "3.12") -> Image:
    return Image.from_docker(f"python:{version}-slim")


def jax_tpu() -> Image:
    """The TPU workhorse: libtpu-bundled JAX on a slim python base."""
    return Image.from_docker("python:3.12-slim").pip_install(
        ["jax[tpu]", "flax", "optax", "orbax-checkpoint"])


def pytorch() -> Image:
    return Image.from_docker("pytorch/pytorch:latest")


def ray() -> Image:
    return Image.from_docker("rayproject/ray:latest")
