"""Module: the deployable wrapper around a user callable.

Reference (``resources/callables/module.py``): ``.to(compute)`` is the
product's core verb — extract pointers, sync code, assemble metadata, launch
through the controller, wait for health — and a second ``.to()`` with the
same name is the 1-2s hot-reload loop (SURVEY §3.1/§3.4).
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Dict, Optional

from ..client import controller_client
from ..config import config
from ..exceptions import ServiceHealthError, ServiceTimeoutError
from ..serving.http_client import HTTPClient
from ..utils.naming import service_name_for
from .compute import Compute
from .pointers import Pointers, extract_pointers


def extract_call_config(kwargs: Dict[str, Any],
                        **seeds: Any) -> Dict[str, Any]:
    """Pop TYPED per-call config objects (kt.MetricsConfig /
    kt.LoggingConfig / kt.DebugConfig) out of a remote call's kwargs —
    keyed by TYPE, not name, so they work on any proxy (Fn, Cls methods,
    actors) without reserving kwarg names: a plain dict named ``metrics``
    still reaches the remote function. To send one of these types TO the
    remote function (pickle serialization), pass it positionally.

    ``seeds`` are configs already captured by a proxy's named params (Fn's
    ``metrics=``/``logging=``/``debugger=``); a second config of the same
    type is ambiguous and raises — never silently dropped."""
    from ..config import DebugConfig, LoggingConfig, MetricsConfig

    slot_for = {MetricsConfig: "metrics", LoggingConfig: "logging",
                DebugConfig: "debugger"}
    out: Dict[str, Any] = {"metrics": None, "logging": None, "debugger": None}
    out.update({k: v for k, v in seeds.items() if v is not None})
    for key in list(kwargs):
        for cfg_type, slot in slot_for.items():
            if isinstance(kwargs[key], cfg_type):
                if out[slot] is not None:
                    raise ValueError(
                        f"two {cfg_type.__name__} objects in one call "
                        f"(kwarg {key!r}) — pass exactly one")
                out[slot] = kwargs.pop(key)
                break
    return out


class Module:
    callable_type = "fn"

    def __init__(self, pointers: Pointers, name: Optional[str] = None,
                 init_args: Optional[Dict] = None):
        self.pointers = pointers
        self.name = service_name_for(pointers.cls_or_fn_name,
                                     username=config().username, name=name)
        self._explicit_name = name is not None
        self.init_args = init_args
        self.compute: Optional[Compute] = None
        self.service_url: Optional[str] = None
        self.launch_id: Optional[str] = None
        self._client: Optional[HTTPClient] = None

    # -- deploy ---------------------------------------------------------------

    def to(self, compute: Compute, name: Optional[str] = None,
           sync_code: bool = True) -> "Module":
        """Deploy (or hot-reload) this callable onto the given compute."""
        if name:
            self.name = service_name_for(self.pointers.cls_or_fn_name,
                                         username=config().username, name=name)
            self._explicit_name = True
        # Self-deploy guard: a pod worker importing the user's module runs
        # its top level — an unguarded driver script would re-deploy THIS
        # service from inside its own pod and then health-wait on itself
        # forever (the warmup can't finish while the import is blocked).
        # Deploying a DIFFERENT service from a pod is legitimate (nested
        # pipelines); deploying yourself never is. Same discipline torch
        # multiprocessing demands: guard driver code with
        # ``if __name__ == "__main__":``. Matching uses what the POD knows:
        # the recomputed name alone fails open whenever the in-pod username
        # differs from the deployer's (config().username feeds the name),
        # so the module pointers this pod was deployed FROM count too —
        # unless the caller chose a different explicit name, which is the
        # legitimate "replica of my own class" pattern.
        if os.environ.get("POD_NAME") and os.environ.get("KT_SERVICE_NAME"):
            same_name = os.environ.get("KT_SERVICE_NAME") == self.name
            same_callable = (
                not self._explicit_name
                and os.environ.get("KT_CLS_OR_FN_NAME")
                == self.pointers.cls_or_fn_name
                and os.environ.get("KT_MODULE_NAME")
                == self.pointers.module_name)
            if same_name or same_callable:
                raise RuntimeError(
                    f"refusing to deploy service {self.name!r} from inside "
                    f"pod {os.environ['POD_NAME']!r} of service "
                    f"{os.environ['KT_SERVICE_NAME']!r} — this almost always "
                    "means the module's top-level driver code ran on import; "
                    "guard it with `if __name__ == \"__main__\":`")
        self.compute = compute
        launch_id = uuid.uuid4().hex

        if sync_code:
            self._sync_code()

        result = compute._launch(self.name, self._metadata(), launch_id)
        self.launch_id = result.get("launch_id", launch_id)
        self.service_url = result.get("service_url")
        compute._check_service_ready(self.name)
        self._wait_for_http_health()
        return self

    async def to_async(self, compute: Compute, **kwargs) -> "Module":
        import asyncio
        return await asyncio.to_thread(self.to, compute, **kwargs)

    def _metadata(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {
            "KT_PROJECT_ROOT": self._remote_root(),
            "KT_MODULE_NAME": self.pointers.module_name,
            "KT_FILE_PATH": self.pointers.file_path,
            "KT_CLS_OR_FN_NAME": self.pointers.cls_or_fn_name,
            "KT_CALLABLE_TYPE": self.callable_type,
            "KT_SERVICE_NAME": self.name,
        }
        if self.init_args:
            meta["KT_INIT_ARGS"] = self.init_args
        if self.compute and self.compute.distributed is not None:
            meta["KT_DISTRIBUTED_CONFIG"] = self.compute.distributed.to_dict()
        if self.compute:
            meta["KT_DOCKERFILE"] = self.compute.image.dockerfile()
        ser_cfg = config().serialization
        if ser_cfg and ser_cfg != "json":
            meta["KT_ALLOWED_SERIALIZATION"] = f"json,msgpack,none,{ser_cfg}"
        return meta

    def _remote_root(self) -> str:
        """Where the pod finds the synced project tree. Local backend pods
        share this filesystem, so the local root is directly importable; real
        pods pull from the data store to /kt/app."""
        if config().api_url and "127.0.0.1" in config().api_url:
            return self.pointers.project_root
        if config().local_mode or not config().api_url:
            return self.pointers.project_root
        return "/kt/app"

    def _sync_code(self) -> None:
        """Ship the working dir to the data store (reference SURVEY §3.1
        RSYNC step). No-op when pods share our filesystem (local backend) or
        no data store is configured."""
        store = config().data_store_url
        if not store:
            return
        from ..data_store.sync import push_tree
        push_tree(store, f"__code__/{self.name}", self.pointers.project_root)

    # -- health ---------------------------------------------------------------

    def _wait_for_http_health(self, timeout: Optional[float] = None) -> None:
        """Poll /ready?launch_id until the deployed launch answers
        (reference ``_wait_for_http_health`` :1424)."""
        if self.service_url is None:
            record = controller_client().get_workload(
                self.compute.namespace, self.name)
            self.service_url = record.get("service_url")
        if self.service_url is None:
            if self._scaled_to_zero():
                return
            raise ServiceHealthError(f"No service URL for {self.name!r}")
        client = self._http_client()
        deadline = time.monotonic() + (timeout or
                                       (self.compute.launch_timeout
                                        if self.compute else 900))
        delay = 0.2
        while time.monotonic() < deadline:
            if client.is_ready(self.launch_id):
                return
            if self._scaled_to_zero():
                # an autoscaled service with no pods is healthy-by-design:
                # launch completed, then the idle window elapsed; the first
                # call cold-starts it through the controller proxy
                return
            time.sleep(delay)
            delay = min(delay * 2, 3.0)
        raise ServiceTimeoutError(
            f"Service {self.name!r} at {self.service_url} never became ready "
            f"for launch {self.launch_id}")

    def _scaled_to_zero(self) -> bool:
        """True only for DELIBERATE zero-pod states — the autoscaler reaped
        an idle service, or the deploy asked for initial_scale=0. Pods that
        crashed at boot leave neither marker, so a broken deploy still
        surfaces as the health-wait timeout it is."""
        if self.compute is None or self.compute.autoscaling is None:
            return False
        try:
            record = controller_client().get_workload(
                self.compute.namespace, self.name)
        except Exception:
            return False
        if record.get("pod_ips"):
            return False
        return (bool(record.get("scaled_to_zero"))
                or record.get("expected_pods") == 0)

    @property
    def is_deployed(self) -> bool:
        """True once this module has a route to the service: a pod URL, or a
        completed launch whose calls go through the controller proxy (an
        ``initial_scale=0`` / scaled-to-zero service never has a pod URL —
        the proxy cold-starts it on first call). launch_id is only set after
        ``_launch`` returns, so a deploy that raised mid-flight still reads
        as not deployed."""
        return self.service_url is not None or self.launch_id is not None

    def _http_client(self) -> HTTPClient:
        from ..config import config as _config
        from ..constants import DEFAULT_SERVER_PORT
        ns = self.compute.namespace if self.compute else "default"
        # the controller-proxy route doubles as the cold-start activator
        # for scaled-to-zero services (nothing listens at service_url —
        # which may itself be None after a scale-to-zero: then the proxy IS
        # the base URL)
        proxy = (f"{_config().api_url}/{ns}/{self.name}:"
                 f"{DEFAULT_SERVER_PORT}" if _config().api_url else None)
        base = self.service_url or proxy
        if base is None:
            raise ServiceHealthError(
                f"No service URL for {self.name!r} and no controller "
                "configured to route through")
        if self._client is None or self._client.base_url != base.rstrip("/"):
            self._client = HTTPClient(base, proxy_url=proxy,
                                      service=self.name)
        return self._client

    # -- lifecycle ------------------------------------------------------------

    @classmethod
    def from_name(cls, name: str, namespace: Optional[str] = None) -> "Module":
        """Reattach to a deployed service (reference ``from_name`` :338)."""
        record = controller_client().get_workload(
            namespace or config().namespace, name)
        meta = record.get("metadata", {})
        pointers = Pointers(
            project_root=meta.get("KT_PROJECT_ROOT", ""),
            module_name=meta.get("KT_MODULE_NAME", ""),
            file_path=meta.get("KT_FILE_PATH", ""),
            cls_or_fn_name=meta.get("KT_CLS_OR_FN_NAME", ""),
        )
        mod = cls.__new__(cls)
        Module.__init__(mod, pointers, name=name)
        mod.name = name
        mod.service_url = record.get("service_url")
        mod.launch_id = record.get("launch_id")
        return mod

    def teardown(self) -> None:
        controller_client().delete_workload(
            self.compute.namespace if self.compute else config().namespace,
            self.name)
        self.service_url = None
        self.launch_id = None
        self._client = None

    # -- pod ops (reference compute.py:2400-2493) ------------------------------

    @property
    def namespace(self) -> str:
        return self.compute.namespace if self.compute else config().namespace

    def pod_ips(self) -> list:
        """Live pod addresses of this service, from the controller."""
        record = controller_client().get_workload(self.namespace, self.name)
        return record.get("pod_ips") or []

    def _pod_exec_targets(self, node) -> list:
        """Resolve ``node`` to (ip, base_url, headers) per target pod.
        ``node``: None/"all" → every pod; int → pod index; str ip; list of
        either. Local-backend pods are directly reachable; otherwise the
        exec rides the controller proxy with pod-targeted routing."""
        ips = self.pod_ips()
        if not ips:
            raise ServiceHealthError(f"{self.name!r} has no running pods")
        if node in (None, "all"):
            chosen = ips
        else:
            nodes = node if isinstance(node, list) else [node]
            chosen = [ips[n] if isinstance(n, int) else n for n in nodes]
            unknown = [ip for ip in chosen if ip not in ips]
            if unknown:
                raise ValueError(f"not pods of {self.name!r}: {unknown}")
        from ..constants import DEFAULT_SERVER_PORT, server_port
        out = []
        for ip in chosen:
            if config().api_url and "127.0.0.1" not in config().api_url:
                base = (f"{config().api_url}/{self.namespace}/"
                        f"{self.name}:{DEFAULT_SERVER_PORT}")
                out.append((ip, base, {"X-KT-Pod-IP": ip}))
            else:
                out.append((ip, f"http://{ip}:{server_port()}", {}))
        return out

    def run_bash(self, commands, node=None, timeout: float = 600) -> list:
        """Run shell command(s) on pod(s); returns ``[(rc, stdout, stderr)]``
        per target pod (reference ``run_bash`` compute.py:2478; transport is
        the pod server's ``/_kt/exec`` instead of ``kubectl exec``, so it
        works identically on the local backend and through the controller
        proxy)."""
        import requests as _requests

        cmds = commands if isinstance(commands, list) else [commands]
        results = []
        for ip, base, headers in self._pod_exec_targets(node):
            for cmd in cmds:
                r = _requests.post(f"{base}/_kt/exec",
                                   json={"cmd": cmd, "timeout": timeout},
                                   headers=headers, timeout=timeout + 30)
                r.raise_for_status()
                body = r.json()
                results.append((body["rc"], body["stdout"], body["stderr"]))
        return results

    def pip_install(self, reqs, node=None,
                    override_remote_version: bool = False) -> None:
        """Pip-install packages onto the pod(s) (reference ``pip_install``
        compute.py:2423): skips packages already importable remotely unless
        ``override_remote_version`` pins the local version."""
        reqs = [reqs] if isinstance(reqs, str) else reqs
        for req in reqs:
            target = req
            mod_name = req.split("[")[0].replace("-", "_")
            if not override_remote_version:
                probe = self.run_bash(
                    f"python3 -c \"import importlib.util,sys; "
                    f"sys.exit(0 if importlib.util.find_spec('{mod_name}') "
                    f"else 1)\"", node=node)
                if all(rc == 0 for rc, _, _ in probe):
                    continue
            else:
                try:
                    from importlib.metadata import version as _v
                    target = f"{req}=={_v(mod_name)}"
                except Exception:
                    pass
            self.run_bash(f"python3 -m pip install {target}", node=node)

    def ssh(self, pod_name: Optional[str] = None) -> None:
        """Interactive shell into a pod (reference ``ssh`` compute.py:2400).
        Cluster mode execs via kubectl; on the local backend pods are host
        subprocesses, so this opens a shell in the service's synced root."""
        import subprocess

        from ..utils.kubectl import resolve_kubectl

        local = not config().api_url or "127.0.0.1" in config().api_url
        kubectl = None if local else resolve_kubectl()
        if kubectl:
            pod = pod_name or f"{self.name}-0"
            subprocess.run([kubectl, "exec", "-it", pod,
                            "-n", self.namespace, "--", "/bin/bash"],
                           check=True)
            return
        root = self.pointers.project_root or os.getcwd()
        subprocess.run(["/bin/bash"], cwd=root,
                       env={**os.environ, "KT_SERVICE_NAME": self.name})


def module_factory(obj: Any, name: Optional[str] = None,
                   init_args: Optional[Dict] = None,
                   cls_type: type = Module) -> Module:
    pointers = extract_pointers(obj)
    return cls_type(pointers, name=name, init_args=init_args)
