"""Callable pointer extraction — how a local function becomes addressable.

Reference ``resources/callables/utils.py``: ``extract_pointers`` (:53) derives
``(root_path, module_import_path, callable_name)`` from a live object via
``inspect``; ``locate_working_dir`` (:114) walks up from the defining file to
a project marker (``.git``, ``pyproject.toml``...) so the sync layer knows
which directory tree to ship; ``build_call_body`` (:255) shapes the RPC body.
"""

from __future__ import annotations

import inspect
import os
import sys
from dataclasses import dataclass, asdict
from pathlib import Path
from typing import Any, Dict, Optional

WORKING_DIR_MARKERS = (".git", "pyproject.toml", "setup.py", "setup.cfg", "requirements.txt")


@dataclass
class Pointers:
    """Where a callable lives, expressed relative to a shippable root."""

    project_root: str      # absolute local path of the dir that gets synced
    module_name: str       # dotted import path relative to project_root
    file_path: str         # file path relative to project_root
    cls_or_fn_name: str

    def to_dict(self) -> Dict[str, str]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, str]) -> "Pointers":
        return cls(**{k: d[k] for k in ("project_root", "module_name", "file_path", "cls_or_fn_name")})


def locate_working_dir(start: str) -> str:
    """Walk up from ``start`` to the nearest project marker (reference :114)."""
    path = Path(start).resolve()
    if path.is_file():
        path = path.parent
    for candidate in (path, *path.parents):
        for marker in WORKING_DIR_MARKERS:
            if (candidate / marker).exists():
                return str(candidate)
    return str(path)


def extract_pointers(obj: Any) -> Pointers:
    """Derive shippable pointers for a function or class (reference :53).

    Interactive callables (REPL / notebook cells) have no importable file; the
    reference extracts notebook functions to a file (:23). Here we serialize
    their source to ``__kt_interactive__.py`` under cwd at deploy time — see
    :func:`dump_interactive_source`.
    """
    if not (inspect.isfunction(obj) or inspect.isclass(obj)):
        raise TypeError(f"Expected a function or class, got {type(obj).__name__}")

    qualname = obj.__qualname__
    if "." in qualname:
        raise ValueError(
            f"{qualname!r} is a nested class/function — only module-top-level "
            "callables can be addressed remotely (the pod imports them by name)")
    name = obj.__name__
    try:
        src_file = inspect.getfile(obj)
    except TypeError:
        raise ValueError(f"Cannot locate source file for {name!r} (builtin?)")

    if src_file.startswith("<"):  # REPL / exec'd source
        return _interactive_pointers(obj, name)

    src_file = os.path.abspath(src_file)
    root = locate_working_dir(src_file)
    rel = os.path.relpath(src_file, root)
    if rel.startswith(".."):
        root = str(Path(src_file).parent)
        rel = os.path.basename(src_file)
    module_name = rel[:-3].replace(os.sep, ".") if rel.endswith(".py") else Path(rel).stem
    if module_name.endswith(".__init__"):
        module_name = module_name[: -len(".__init__")]
    return Pointers(project_root=root, module_name=module_name, file_path=rel, cls_or_fn_name=name)


_INTERACTIVE_FILE = "__kt_interactive__.py"
_SECTION_BEGIN = "# __kt_section__: "


def _interactive_pointers(obj: Any, name: str) -> Pointers:
    """Persist an interactive callable's source into a named section of the
    sync'd interactive module, *replacing* any previous version of the same
    name so reverts deploy what the user currently has."""
    try:
        source = inspect.getsource(obj)
    except OSError:
        raise ValueError(
            f"{name!r} is defined interactively and its source cannot be recovered; "
            "define it in a .py file."
        )
    root = os.getcwd()
    path = Path(root) / _INTERACTIVE_FILE
    sections: Dict[str, str] = {}
    if path.exists():
        current = None
        for line in path.read_text().splitlines(keepends=True):
            if line.startswith(_SECTION_BEGIN):
                current = line[len(_SECTION_BEGIN):].strip()
                sections[current] = ""
            elif current is not None:
                sections[current] += line
    sections[name] = source
    with open(path, "w") as f:
        for sec_name, sec_src in sections.items():
            f.write(f"{_SECTION_BEGIN}{sec_name}\n{sec_src.rstrip()}\n\n")
    return Pointers(project_root=root, module_name=_INTERACTIVE_FILE[:-3],
                    file_path=_INTERACTIVE_FILE, cls_or_fn_name=name)


def build_call_body(args: tuple, kwargs: dict, debugger: Optional[dict] = None) -> Dict[str, Any]:
    """RPC body shape (reference :255): args/kwargs plus optional debugger spec."""
    body: Dict[str, Any] = {"args": list(args), "kwargs": kwargs}
    if debugger:
        body["debugger"] = debugger
    return body


def patch_sys_path(root: str) -> None:
    """Ensure the synced project root is importable (reference http_server.py:1005)."""
    if root not in sys.path:
        sys.path.insert(0, root)


def import_callable(pointers: Pointers, reload: bool = False) -> Any:
    """Import ``cls_or_fn_name`` from its module, with file-path fallback.

    Mirrors ``load_callable_from_env`` (reference http_server.py:1039-1106):
    try a normal import of ``module_name``; if the module isn't importable
    (e.g. not a package member), exec the file directly.
    """
    import importlib
    import importlib.util

    patch_sys_path(pointers.project_root)
    mod = None
    try:
        mod = importlib.import_module(pointers.module_name)
        if reload:
            mod = importlib.reload(mod)
    except ImportError:
        file_path = os.path.join(pointers.project_root, pointers.file_path)
        spec = importlib.util.spec_from_file_location(pointers.module_name, file_path)
        if spec is None or spec.loader is None:
            raise ImportError(f"Cannot import {pointers.module_name} from {file_path}")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[pointers.module_name] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            # Mirror importlib's own cleanup: never cache a half-built module,
            # or retries would mask the real error with an AttributeError.
            sys.modules.pop(pointers.module_name, None)
            raise
    try:
        return getattr(mod, pointers.cls_or_fn_name)
    except AttributeError:
        raise ImportError(
            f"Module {pointers.module_name!r} has no attribute {pointers.cls_or_fn_name!r}"
        )
