"""Secret: credentials delivered to pods as env vars or file mounts.

Reference (``resources/secrets/``): K8s Secret CRUD via the controller, with
provider presets (aws/gcp/anthropic/huggingface/wandb/...) that know each
provider's default env vars and credential file paths.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List, Optional

from ..client import controller_client
from ..config import config

# provider → (env vars, default credentials path) — reference
# resources/secrets/provider_secrets/providers.py:92
PROVIDERS: Dict[str, Dict] = {
    "aws": {"env": ["AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY"],
            "path": "~/.aws/credentials"},
    "gcp": {"env": ["GOOGLE_APPLICATION_CREDENTIALS"],
            "path": "~/.config/gcloud/application_default_credentials.json"},
    "azure": {"env": ["AZURE_CLIENT_ID", "AZURE_CLIENT_SECRET",
                      "AZURE_TENANT_ID"], "path": None},
    "anthropic": {"env": ["ANTHROPIC_API_KEY"], "path": None},
    "openai": {"env": ["OPENAI_API_KEY"], "path": None},
    "cohere": {"env": ["COHERE_API_KEY"], "path": None},
    "github": {"env": ["GITHUB_TOKEN"], "path": "~/.config/gh/hosts.yml"},
    "huggingface": {"env": ["HF_TOKEN", "HUGGING_FACE_HUB_TOKEN"],
                    "path": "~/.cache/huggingface/token"},
    "kubeconfig": {"env": [], "path": "~/.kube/config"},
    "lambda": {"env": ["LAMBDA_API_KEY"], "path": "~/.lambda_cloud/lambda_keys"},
    "langchain": {"env": ["LANGCHAIN_API_KEY"], "path": None},
    "pinecone": {"env": ["PINECONE_API_KEY"], "path": None},
    "ssh": {"env": [], "path": "~/.ssh/id_rsa"},
    "wandb": {"env": ["WANDB_API_KEY"], "path": "~/.netrc"},
}


class Secret:
    def __init__(self, name: str, values: Optional[Dict[str, str]] = None,
                 file_path: Optional[str] = None,
                 mount_path: Optional[str] = None,
                 provider: Optional[str] = None,
                 namespace: Optional[str] = None):
        self.name = name
        self.values = dict(values or {})
        self.file_path = file_path
        self.mount_path = mount_path
        self.provider = provider
        # pinned by from_name(namespace=...): every later operation must
        # target the namespace the binding was verified in
        self.namespace = namespace

    def _ns(self, namespace: Optional[str]) -> str:
        return namespace or self.namespace or config().namespace

    # -- factories (reference secret_factory.py) ------------------------------

    @classmethod
    def from_provider(cls, provider: str, name: Optional[str] = None) -> "Secret":
        spec = PROVIDERS.get(provider)
        if spec is None:
            raise ValueError(f"Unknown provider {provider!r}; "
                             f"known: {sorted(PROVIDERS)}")
        values = {k: os.environ[k] for k in spec["env"] if k in os.environ}
        file_path = None
        if spec["path"]:
            p = Path(os.path.expanduser(spec["path"]))
            if p.exists():
                file_path = str(p)
        if not values and not file_path:
            raise ValueError(
                f"No local credentials found for provider {provider!r} "
                f"(looked for env {spec['env']} and {spec['path']})")
        return cls(name or f"{provider}-secret", values=values,
                   file_path=file_path, provider=provider,
                   mount_path=spec["path"])

    @classmethod
    def from_name(cls, name: str,
                  namespace: Optional[str] = None) -> "Secret":
        """Bind to an EXISTING cluster Secret by name — values stay in the
        object (reads return metadata/key names only); raises
        :class:`~kubetorch_tpu.exceptions.SecretNotFound` when absent."""
        from ..exceptions import SecretNotFound

        obj = controller_client().get_object(
            "Secret", namespace or config().namespace, name)
        if obj is None:
            raise SecretNotFound(f"no Secret {name!r} in "
                                 f"{namespace or config().namespace}")
        # reads are value-stripped by design; a name-only ref delivers via
        # envFrom on the pod template (keys unknown client-side)
        secret = cls(name, namespace=namespace)
        # by-reference binding: this object holds NO values, so save() must
        # never apply it — an empty stringData apply would WIPE the existing
        # cluster secret (and the Compute attach flow saves automatically)
        secret._by_reference = True
        return secret

    @classmethod
    def from_env(cls, keys: List[str], name: str = "env-secret") -> "Secret":
        missing = [k for k in keys if k not in os.environ]
        if missing:
            raise ValueError(f"Env vars not set: {missing}")
        return cls(name, values={k: os.environ[k] for k in keys})

    @classmethod
    def from_path(cls, path: str, mount_path: Optional[str] = None,
                  name: Optional[str] = None) -> "Secret":
        p = Path(os.path.expanduser(path))
        if not p.exists():
            raise ValueError(f"No file at {path}")
        return cls(name or f"file-{p.name}".lower().replace(".", "-"),
                   file_path=str(p), mount_path=mount_path or path)

    # -- pod delivery ---------------------------------------------------------

    def ref(self) -> Dict[str, Optional[str]]:
        """How a pod template references this secret — by NAME only.

        Values never enter the workload manifest (reference keeps secret
        material in K8s Secret objects, ``kubernetes_secrets_client.py``;
        round-2 VERDICT flagged the old inline-env delivery as a plaintext
        leak into persisted controller state). The k8s backend delivers via
        ``envFrom`` + Secret volume mounts; the local backend resolves the
        ref from its 0600 secret files at pod spawn. ``mount_path`` is
        advertised only when there is an actual file payload — a provider
        preset resolved from env vars alone must not emit a volume for a
        ``__file__`` key that ``save()`` never writes. ``keys`` (env var
        NAMES, not values) lets the pod template emit per-key
        ``valueFrom.secretKeyRef`` entries instead of a blanket ``envFrom``
        — envFrom would also inject the ``__file__`` credential payload as
        an environment variable on Kubernetes.
        """
        return {"name": self.name,
                "mount_path": self.mount_path if self.file_path else None,
                "keys": sorted(self.values)}

    # -- cluster CRUD through the controller ----------------------------------

    def save(self, namespace: Optional[str] = None) -> Dict:
        """Materialize the Secret object(s). File payloads go to a SEPARATE
        ``<name>-file`` Secret: the env object may legitimately be expanded
        with a blanket ``envFrom`` (name-only refs), and a ``__file__`` key
        there would inject the whole credential file into pod env."""
        if getattr(self, "_by_reference", False):
            # from_name binding: the cluster object is the source of truth;
            # applying this value-less handle would erase it
            return {"ok": True, "by_reference": True}
        ns = self._ns(namespace)
        client = controller_client()
        result = client.apply(
            ns, self.name,
            manifest={"apiVersion": "v1", "kind": "Secret",
                      "metadata": {"name": self.name},
                      "stringData": dict(self.values)})
        if self.file_path:
            client.apply(
                ns, f"{self.name}-file",
                manifest={"apiVersion": "v1", "kind": "Secret",
                          "metadata": {"name": f"{self.name}-file"},
                          "stringData": {
                              "__file__": Path(self.file_path).read_text(),
                              "__mount_path__": self.mount_path or ""}})
        return result

    def delete(self, namespace: Optional[str] = None) -> Dict:
        ns = self._ns(namespace)
        result = controller_client().delete_object("Secret", ns, self.name)
        controller_client().delete_object("Secret", ns, f"{self.name}-file")
        return result

    def exists(self, namespace: Optional[str] = None) -> bool:
        return controller_client().get_object(
            "Secret", self._ns(namespace), self.name) is not None

    def __repr__(self) -> str:
        return (f"Secret({self.name!r}, keys={sorted(self.values)}, "
                f"file={self.file_path!r})")


def secret(provider: Optional[str] = None,
           env: Optional[List[str]] = None,
           path: Optional[str] = None,
           name: Optional[str] = None,
           values: Optional[Dict[str, str]] = None) -> Secret:
    """Factory mirroring the reference's ``kt.secret(...)``
    (``secret_factory.py:8``): provider preset, explicit env var names, a
    credential file path, or literal values — exactly one source."""
    sources = [s for s in (provider, env, path, values) if s]
    if len(sources) != 1:
        raise ValueError("pass exactly one of provider=, env=, path=, "
                         "values=")
    if provider:
        return Secret.from_provider(provider, name=name)
    if env:
        return Secret.from_env(env, name=name or "env-secret")
    if path:
        return Secret.from_path(path, name=name)
    return Secret(name or "literal-secret", values=values)
