"""Volume: persistent storage attached to compute.

Reference (``resources/volumes/volume.py``): PVC create/delete/from_name,
mount path, scratch-pod ssh. The local backend maps a Volume to a host
directory under the store root so the same API works without a cluster.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..client import controller_client
from ..config import config


class Volume:
    def __init__(self, name: str, size: str = "10Gi",
                 mount_path: Optional[str] = None,
                 storage_class: Optional[str] = None,
                 access_mode: str = "ReadWriteOnce"):
        self.name = name
        self.size = size
        self.mount_path = mount_path or f"/mnt/{name}"
        self.storage_class = storage_class
        self.access_mode = access_mode

    def manifest(self, namespace: Optional[str] = None) -> Dict:
        spec: Dict = {
            "accessModes": [self.access_mode],
            "resources": {"requests": {"storage": self.size}},
        }
        if self.storage_class:
            spec["storageClassName"] = self.storage_class
        return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                "metadata": {"name": self.name,
                             "namespace": namespace or config().namespace},
                "spec": spec}

    def create(self, namespace: Optional[str] = None) -> Dict:
        return controller_client().apply(
            namespace or config().namespace, self.name, self.manifest(namespace))

    @classmethod
    def from_name(cls, name: str, mount_path: Optional[str] = None) -> "Volume":
        return cls(name=name, mount_path=mount_path)

    def delete(self, namespace: Optional[str] = None) -> Dict:
        return controller_client().delete_workload(
            namespace or config().namespace, self.name)

    def mount_spec(self) -> Dict:
        """Entry consumed by the pod-template builder."""
        return {"name": self.name, "claim": self.name,
                "mount_path": self.mount_path}

    def __repr__(self) -> str:
        return f"Volume({self.name!r}, {self.size}, mount={self.mount_path!r})"
