"""Volume: persistent storage attached to compute.

Reference (``resources/volumes/volume.py:1-400``): PVC create / delete(wait)
/ exists / from_name (spec round-trip), storage-class resolution, mount
path, scratch-pod ssh. TPU-first local analog: the local backend maps a PVC
to a host directory and advertises it to subprocess pods via
``KT_VOLUME_<NAME>`` env (a subprocess can't bind-mount a claim).
"""

from __future__ import annotations

import os
import subprocess
import time
import uuid
from typing import Dict, List, Optional

from ..client import controller_client
from ..config import config


class VolumeDeleteTimeout(TimeoutError):
    pass


class Volume:
    def __init__(self, name: str, size: str = "10Gi",
                 mount_path: Optional[str] = None,
                 storage_class: Optional[str] = None,
                 access_mode: str = "ReadWriteOnce"):
        self.name = name
        self.size = size
        self.mount_path = mount_path or f"/mnt/{name}"
        self.storage_class = storage_class
        self.access_mode = access_mode

    # -- manifest / lifecycle -------------------------------------------------

    def manifest(self, namespace: Optional[str] = None) -> Dict:
        spec: Dict = {
            "accessModes": [self.access_mode],
            "resources": {"requests": {"storage": self.size}},
        }
        if self.storage_class:
            spec["storageClassName"] = self.storage_class
        return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                "metadata": {"name": self.name,
                             "namespace": namespace or config().namespace},
                "spec": spec}

    def create(self, namespace: Optional[str] = None) -> Dict:
        """Apply the PVC. A ReadWriteMany request without an explicit storage
        class resolves one from the cluster (reference storage-class
        plumbing, volume.py:107-150): RWX needs an RWX-capable provisioner,
        which is rarely the default."""
        if self.storage_class is None and self.access_mode == "ReadWriteMany":
            self.storage_class = self._resolve_rwx_class()
        return controller_client().apply(
            namespace or config().namespace, self.name, self.manifest(namespace))

    def _resolve_rwx_class(self) -> Optional[str]:
        classes = self.storage_classes()
        # filestore/nfs/efs-style provisioners support RWX; GKE PD does not
        rwx = [c for c in classes
               if any(hint in (c.get("provisioner") or "")
                      for hint in ("filestore", "nfs", "efs", "cephfs",
                                   "azurefile", "local-dir"))]
        if not rwx:
            raise ValueError(
                "No RWX-capable storage class found; pass storage_class= "
                f"explicitly (available: {[c['name'] for c in classes]})")
        return rwx[0]["name"]

    @classmethod
    def storage_classes(cls) -> List[Dict]:
        return controller_client().storage_classes()

    @classmethod
    def from_name(cls, name: str, mount_path: Optional[str] = None,
                  namespace: Optional[str] = None) -> "Volume":
        """Bind to an existing PVC, reading size/class/mode back from the
        cluster (reference from_name, volume.py:156-187). Unknown PVCs still
        return a handle — create() materializes them."""
        vol = cls(name=name, mount_path=mount_path)
        obj = controller_client().get_object(
            "PersistentVolumeClaim", namespace or config().namespace, name)
        if obj:
            spec = obj.get("spec", {})
            vol.size = (spec.get("resources", {}).get("requests", {})
                        .get("storage", vol.size))
            vol.storage_class = spec.get("storageClassName")
            modes = spec.get("accessModes") or [vol.access_mode]
            vol.access_mode = modes[0]
        return vol

    def exists(self, namespace: Optional[str] = None) -> bool:
        return controller_client().get_object(
            "PersistentVolumeClaim", namespace or config().namespace,
            self.name) is not None

    def delete(self, namespace: Optional[str] = None, wait: bool = True,
               timeout: float = 60.0) -> Dict:
        """Kind-aware PVC delete through the controller's object store — NOT
        the workload sweep (round-2 VERDICT weak #3). Optionally waits out
        the Terminating phase."""
        ns = namespace or config().namespace
        result = controller_client().delete_object(
            "PersistentVolumeClaim", ns, self.name)
        if wait:
            deadline = time.monotonic() + timeout
            while self.exists(ns):
                if time.monotonic() >= deadline:
                    raise VolumeDeleteTimeout(
                        f"PVC {self.name} still terminating after {timeout}s")
                time.sleep(0.5)
        return result

    # -- pod wiring -----------------------------------------------------------

    def mount_spec(self) -> Dict:
        """Entry consumed by the pod-template builder."""
        return {"name": self.name, "claim": self.name,
                "mount_path": self.mount_path}

    def local_path(self) -> Optional[str]:
        """Host directory backing this volume inside a LOCAL pod — resolved
        from the ``KT_VOLUME_<NAME>`` env the local backend injects at pod
        spawn; None on real clusters (use ``mount_path`` there)."""
        return os.environ.get(
            "KT_VOLUME_" + self.name.upper().replace("-", "_"))

    # -- scratch-pod ssh (reference volume.py:336-400) ------------------------

    def scratch_pod_manifest(self, image: str = "alpine:latest",
                             pod_name: Optional[str] = None) -> Dict:
        pod_name = pod_name or f"debug-{self.name}-{uuid.uuid4().hex[:6]}"
        return {
            "apiVersion": "v1",
            "spec": {
                "containers": [{
                    "name": "debug", "image": image,
                    "stdin": True, "tty": True,
                    "volumeMounts": [{"name": "vol",
                                      "mountPath": self.mount_path}],
                }],
                "volumes": [{
                    "name": "vol",
                    "persistentVolumeClaim": {"claimName": self.name},
                }],
            },
        }

    def _ssh_cmd(self, image: str = "alpine:latest",
                 namespace: Optional[str] = None) -> List[str]:
        import json as _json

        from ..utils.kubectl import resolve_kubectl
        ns = namespace or config().namespace
        pod_name = f"debug-{self.name}-{uuid.uuid4().hex[:6]}"
        return [resolve_kubectl() or "kubectl", "run", pod_name, "--rm",
                "-it", "--namespace", ns, "--image", image,
                "--restart=Never", "--overrides",
                _json.dumps(self.scratch_pod_manifest(image, pod_name))]

    @staticmethod
    def _controller_is_local() -> bool:
        """Ask the controller which backend it runs — substring-matching
        127.0.0.1 in api_url would also match a kubectl port-forward to a
        REAL in-cluster controller and silently shell into an empty local
        dir instead of the PVC."""
        try:
            backend = controller_client().cluster_config().get("backend")
        except Exception:
            backend = None
        if backend:
            return backend == "local"
        return config().local_mode or not config().api_url

    def ssh(self, image: str = "alpine:latest",
            namespace: Optional[str] = None) -> None:
        """Interactive shell with this volume mounted: a scratch pod on k8s,
        or ``$SHELL`` in the backing host dir when the controller is local."""
        if self._controller_is_local():
            from ..controller.backends import default_local_volume_dir
            vdir = default_local_volume_dir(
                namespace or config().namespace, self.name)
            os.makedirs(vdir, exist_ok=True)
            subprocess.run([os.environ.get("SHELL", "/bin/sh")], cwd=vdir)
            return
        proc = subprocess.run(self._ssh_cmd(image, namespace),
                              stderr=subprocess.PIPE, text=True)
        if proc.returncode != 0 and proc.stderr:
            # surface real failures; the reference hid everything to mute a
            # noisy write-on-closed-stream on exit, which also hid "invalid
            # override" style errors entirely
            trimmed = "\n".join(line for line in proc.stderr.splitlines()
                                if "write on closed" not in line)
            if trimmed.strip():
                print(trimmed)

    def __repr__(self) -> str:
        return f"Volume({self.name!r}, {self.size}, mount={self.mount_path!r})"
