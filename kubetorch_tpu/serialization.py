"""Wire serialization for call bodies and results.

Reference behavior (``serving/http_server.py:1768-1891``): ``json`` by
default, ``pickle`` as base64 gated by a ``KT_ALLOWED_SERIALIZATION``
allowlist, ``none`` passthrough. Format travels in the ``X-Serialization``
header.

TPU-native redesign: arrays are first-class. A ``json``-serialized body may
embed numpy/JAX arrays — they are encoded as typed leaves
(``{"__kt_array__": {dtype, shape, data_b64}}``) so a JAX pytree survives the
wire without pickle. For bulk tensors the binary ``msgpack`` format packs raw
array bytes without base64 inflation (the data-plane path; see
``data_store``). Device arrays are pulled to host with ``np.asarray`` — the
transfer daemon, not the RPC layer, owns device placement (SURVEY §2.9: TPUs
have no CUDA-IPC equivalent, so host staging is the only cross-process path).
"""

from __future__ import annotations

import base64
import json
from typing import Any, Iterable, Optional

from .exceptions import SerializationError

JSON = "json"
PICKLE = "pickle"
MSGPACK = "msgpack"
NONE = "none"

DEFAULT_ALLOWED = (JSON, MSGPACK, NONE)

_ARRAY_KEY = "__kt_array__"
_BYTES_KEY = "__kt_bytes__"


def _is_array(obj: Any) -> bool:
    # numpy arrays/scalars and anything exposing __array__ + dtype/shape
    # (covers jax.Array without importing jax here).
    t = type(obj)
    mod = t.__module__
    if mod.startswith("numpy"):
        import numpy as np
        return isinstance(obj, (np.ndarray, np.generic))
    if mod.startswith(("jax", "jaxlib")):
        return hasattr(obj, "dtype") and hasattr(obj, "shape")
    return False


def _encode_array(obj: Any) -> dict:
    import numpy as np

    arr = np.asarray(obj)  # device→host for jax.Array
    return {
        _ARRAY_KEY: {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "data": base64.b64encode(arr.tobytes()).decode(),
        }
    }


def _np_dtype(dtype: str):
    import numpy as np

    # bfloat16 has no numpy builtin; ml_dtypes ships with jax.
    if dtype == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtype)


def _fill_array(raw: bytes, dtype: str, shape: list) -> Any:
    """Decode raw bytes into a freshly allocated writable array.
    ``frombuffer(...).copy()`` would hold the read-only view's copy AND the
    source alive together — 2× peak per array; filling a preallocated
    buffer keeps one allocation."""
    import numpy as np

    arr = np.empty(shape, dtype=_np_dtype(dtype))
    view = arr.reshape(-1).view(np.uint8)
    if view.nbytes != len(raw):
        raise SerializationError(
            f"array byte-size mismatch: {len(raw)}B payload for "
            f"{dtype}{list(shape)}")
    view[:] = np.frombuffer(raw, dtype=np.uint8)
    return arr


def _decode_array(spec: dict) -> Any:
    return _fill_array(base64.b64decode(spec["data"]), spec["dtype"],
                       spec["shape"])


def _jsonify(obj: Any) -> Any:
    """Recursively convert a pytree-ish object to JSON-safe form."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return {_BYTES_KEY: base64.b64encode(obj).decode()}
    if _is_array(obj):
        return _encode_array(obj)
    if isinstance(obj, (list, tuple)):
        return [_jsonify(x) for x in obj]
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise SerializationError(
                    f"JSON serialization requires string dict keys; got {type(k).__name__} "
                    f"key {k!r}. Use serialization='msgpack' or 'pickle'."
                )
        return {_escape_key(k): _jsonify(v) for k, v in obj.items()}
    raise SerializationError(
        f"Object of type {type(obj).__name__} is not json-serializable; "
        f"use serialization='pickle' (must be allowlisted server-side)."
    )


def _escape_key(k: str) -> str:
    """User keys that could collide with our typed-leaf sentinels get a '~'
    prefix (stacked if already present), reversed on decode."""
    return "~" + k if k.lstrip("~").startswith("__kt_") else k


def _unescape_key(k: str) -> str:
    return k[1:] if k.startswith("~") and k.lstrip("~").startswith("__kt_") else k


def _dejsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        if _ARRAY_KEY in obj and len(obj) == 1:
            return _decode_array(obj[_ARRAY_KEY])
        if _BYTES_KEY in obj and len(obj) == 1:
            return base64.b64decode(obj[_BYTES_KEY])
        return {_unescape_key(k): _dejsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dejsonify(x) for x in obj]
    return obj


def serialize(obj: Any, format: str = JSON) -> bytes:
    """Serialize ``obj`` to bytes in the given wire format."""
    if format == NONE:
        if obj is None:
            return b""
        if isinstance(obj, bytes):
            return obj
        if isinstance(obj, str):
            return obj.encode()
        raise SerializationError("serialization='none' requires bytes/str/None")
    if format == JSON:
        return json.dumps(_jsonify(obj)).encode()
    if format == PICKLE:
        import cloudpickle
        return base64.b64encode(cloudpickle.dumps(obj))
    if format == MSGPACK:
        return _msgpack_dumps(obj)
    raise SerializationError(f"Unknown serialization format: {format!r}")


def deserialize(data: bytes, format: str = JSON, allowed: Optional[Iterable[str]] = None) -> Any:
    """Deserialize bytes; enforce the server-side allowlist when given.

    ``allowed`` mirrors the reference's KT_ALLOWED_SERIALIZATION gate
    (``http_server.py:1777``): pickle is rejected unless explicitly enabled
    per-workload, because unpickling is code execution.
    """
    if allowed is not None and format not in allowed:
        raise SerializationError(
            f"Serialization format {format!r} not in server allowlist {sorted(allowed)}"
        )
    if format == NONE:
        return data
    if not data:
        return None
    if format == JSON:
        return _dejsonify(json.loads(data.decode()))
    if format == PICKLE:
        import cloudpickle
        return cloudpickle.loads(base64.b64decode(data))
    if format == MSGPACK:
        return _msgpack_loads(data)
    raise SerializationError(f"Unknown serialization format: {format!r}")


# -- msgpack binary path (efficient raw-bytes arrays, no b64) ---------------


def _msgpack_default(obj: Any) -> Any:
    if _is_array(obj):
        import numpy as np
        arr = np.asarray(obj)
        return {"__arr__": True, "d": str(arr.dtype), "s": list(arr.shape), "b": arr.tobytes()}
    raise SerializationError(f"msgpack cannot encode {type(obj).__name__}")


def _msgpack_escape_key(k: Any) -> Any:
    """'~'-stack keys that would trip the '__arr__' decode hook — the exact
    mirror of the JSON pair (:func:`_escape_key`): escape pushes one ``~``,
    unescape pops one, so any user key ``~*__arr__`` round-trips."""
    if isinstance(k, str) and k.lstrip("~") == "__arr__":
        return "~" + k
    return k


def _msgpack_unescape_key(k: Any) -> Any:
    if isinstance(k, str) and k.startswith("~") and k.lstrip("~") == "__arr__":
        return k[1:]
    return k


def _msgpack_needs_escape(obj: Any) -> bool:
    """Scan-only pass: does any dict key in the tree need '~'-escaping?
    Most payloads never touch the ``__arr__`` sentinel, so the common case
    is a cheap read-only walk instead of a full container rebuild."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(k, str) and k.lstrip("~") == "__arr__":
                return True
            if _msgpack_needs_escape(v):
                return True
        return False
    if isinstance(obj, (list, tuple)):
        # tuples still rebuild (msgpack encodes them as lists anyway), but
        # only the rebuild pass pays for that — scanning stays read-only
        return any(_msgpack_needs_escape(v) for v in obj)
    return False


def _msgpack_escape(obj: Any) -> Any:
    """Escape user dicts whose '__arr__' key would trip the decode hook.

    Fast path (ISSUE 10): when the scan finds nothing to escape, the
    ORIGINAL object is returned untouched — no container rebuild, and
    large ``bytes``/array leaves pass through by reference instead of
    riding a freshly allocated tree. Only payloads that actually use the
    sentinel key pay the rebuild."""
    if not _msgpack_needs_escape(obj):
        return obj
    return _msgpack_escape_rebuild(obj)


def _msgpack_escape_rebuild(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {_msgpack_escape_key(k): _msgpack_escape_rebuild(v)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_msgpack_escape_rebuild(v) for v in obj]
    return obj


def _msgpack_hook(obj: dict) -> Any:
    if obj.get("__arr__"):
        return _fill_array(obj["b"], obj["d"], obj["s"])
    return {_msgpack_unescape_key(k): v for k, v in obj.items()}


def _msgpack_dumps(obj: Any) -> bytes:
    import msgpack
    return msgpack.packb(_msgpack_escape(obj), default=_msgpack_default,
                         use_bin_type=True)


def _msgpack_loads(data: bytes) -> Any:
    import msgpack
    return msgpack.unpackb(data, object_hook=_msgpack_hook, raw=False, strict_map_key=False)
