"""Model serving: continuous-batching generation on TPU.

The reference serves inference by deploying a user fn/cls behind Knative
autoscaling (``resources/compute.py`` + the pod HTTP server) and leaves
batching to the user. Here the serving story goes further: a TPU-native
engine that keeps ONE compiled decode step hot over a fixed slot grid and
admits/retires requests mid-flight (continuous batching), so concurrent
callers share the chip instead of queueing whole generations behind each
other. Deploy it like any stateful service::

    import kubetorch_tpu as kt
    from kubetorch_tpu.serve import GenerationEngine

    svc = kt.cls(GenerationEngine).to(kt.Compute(tpu="v5e-4"))
"""

from ..models.quant import (dequantize_params, llama_init_quantized,
                            quantize_params, quantize_params_int4,
                            quantized_bytes)
from .engine import EngineStats, GenerationEngine, RequestHandle
from .kv_quant import QuantKVCache, dequantize_rows, quantize_rows
from .rollout import CanaryRollout, WeightRollout
from .sessions import EngineSessionBinder, SessionStats, session_key
from .spec_engine import SpeculativeEngine
from .speculative import SpecStats, speculative_generate

__all__ = ["GenerationEngine", "RequestHandle", "EngineStats",
           "EngineSessionBinder", "SessionStats", "session_key",
           "quantize_params", "quantize_params_int4",
           "llama_init_quantized", "dequantize_params", "quantized_bytes",
           "speculative_generate", "SpecStats", "SpeculativeEngine",
           "QuantKVCache", "quantize_rows", "dequantize_rows",
           "WeightRollout", "CanaryRollout",
           "OpenAIApp", "build_openai_app"]


def __getattr__(name):
    # lazy: the OpenAI surface pulls in aiohttp, which pure-compute users
    # of serve (engines in a training loop) never need
    if name in ("OpenAIApp", "build_openai_app"):
        from .openai_api import OpenAIApp, build_app
        return {"OpenAIApp": OpenAIApp, "build_openai_app": build_app}[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
