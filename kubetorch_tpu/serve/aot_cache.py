"""Persistent AOT compile cache for the serving engine (ISSUE 16).

Replica boot pays an XLA trace+compile for every prefill bucket plus the
decode step — tens of seconds that every freshly scaled pod repeats even
though the executables are a pure function of (model config, mesh shape,
bucket set, engine shape knobs, jax/backend version). This module makes
the fleet compile once ever:

- :class:`AOTKey` canonicalizes that tuple into a content digest. Any
  field changing (a jax upgrade, a different bucket set, a resharded
  mesh) lands in a different cache line, so a stale executable can never
  be *found*, only missed.
- :class:`AOTCompileCache` is a two-layer store: a local directory of
  serialized executables (``jax.experimental.serialize_executable``)
  with a blake2b content gate in front of every deserialize, and an
  optional store-ring layer (PR 7 content-addressed put/get) so the
  first replica to compile publishes for the whole fleet.
- :func:`warm_engine` pre-compiles the engine's common-signature
  executables (prefill per bucket + the decode step/block) through the
  cache and hands the engine an executable table its dispatch sites
  consult before falling back to the traced jits.

Miss paths are typed and counted (``kt_aot_cache_total{result=...}``):
an absent entry, a key mismatch (``incompatible``), and a corrupted
payload all fall back to a fresh compile — never a wrong executable.
This module is the ONLY compile-path entry in ``serve/`` (lint #14 in
``scripts/check_resilience.py`` pins that).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import time
from pathlib import Path
from typing import Any, Callable, Dict, Optional, Tuple

from ..exceptions import AOTCacheCorruptError, AOTCacheMissError

_DIGEST_LEN = 32          # hex chars of the key digest (128 bits)
_BIN_SUFFIX = ".bin"      # pickled (payload, in_tree, out_tree)
_META_SUFFIX = ".json"    # sidecar: content hash + provenance


def _canon(v: Any) -> Any:
    """Canonicalize a value for the key JSON: dataclasses to sorted
    dicts, tuples to lists, dtypes/callables/everything exotic to
    ``str`` — the digest must be stable across processes, so anything
    without a deterministic repr has no business in a key field."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {k: _canon(getattr(v, k))
                for k in sorted(f.name for f in dataclasses.fields(v))}
    if isinstance(v, dict):
        return {str(k): _canon(v[k]) for k in sorted(v, key=str)}
    if isinstance(v, (list, tuple)):
        return [_canon(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


@dataclasses.dataclass(frozen=True)
class AOTKey:
    """Everything a serialized executable is a function of. Two engines
    with equal keys can exchange executables; anything else is a miss."""

    model: Any                      # model config (dataclass or dict)
    mesh_shape: Optional[tuple]     # ((axis, size), ...) or None (no mesh)
    buckets: tuple                  # engine._buckets (sorted, deduped)
    slots: int
    max_len: int
    quantize_kv: bool
    decode_block: int
    # top_k is an engine constructor knob independent of cfg, baked into
    # every executable as a lower-time static (engine.py dispatch sites
    # pass top_k=self.top_k) — it MUST participate in the digest or two
    # engines differing only in top_k would swap executables and sample
    # wrong
    top_k: Optional[int] = None
    jax_version: str = ""
    jaxlib_version: str = ""
    backend: str = ""

    @staticmethod
    def for_engine(engine) -> "AOTKey":
        import jax
        import jaxlib

        mesh = getattr(engine, "_mesh", None)
        mesh_shape = (tuple(sorted(dict(mesh.shape).items()))
                      if mesh is not None else None)
        return AOTKey(
            model=_canon(engine.cfg),
            mesh_shape=mesh_shape,
            buckets=tuple(engine._buckets),
            slots=engine.slots,
            max_len=engine.max_len,
            quantize_kv=engine.quantize_kv,
            decode_block=engine.decode_block,
            top_k=engine.top_k,
            jax_version=jax.__version__,
            jaxlib_version=getattr(jaxlib, "__version__", ""),
            backend=jax.default_backend(),
        )

    def describe(self) -> Dict[str, Any]:
        return _canon(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.blake2b(blob, digest_size=_DIGEST_LEN // 2).hexdigest()


def default_cache_root() -> Path:
    """``KT_AOT_CACHE_DIR`` env → layered config ``aot_cache_dir`` →
    ``~/.cache/kubetorch_tpu/aot``."""
    env = os.environ.get("KT_AOT_CACHE_DIR", "").strip()
    if env:
        return Path(env)
    try:
        from ..config import config
        cfgd = str(config().get("aot_cache_dir", "") or "").strip()
        if cfgd:
            return Path(cfgd)
    except Exception:
        pass
    return Path.home() / ".cache" / "kubetorch_tpu" / "aot"


def _blake2b(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


class AOTCompileCache:
    """Layered executable cache: local directory + optional store ring.

    Layout: ``<root>/<digest>/<name>.bin`` (pickled serialize() triple)
    beside ``<name>.json`` (blake2b of the bin, sizes, jax versions) and
    one ``key.json`` describing the digest's full key for operators.
    Writes commit through ``durable_replace`` so a crash mid-publish
    leaves no truncated payload under a final name; reads verify the
    sidecar hash BEFORE deserializing, so a corrupt entry becomes a
    typed :class:`AOTCacheCorruptError` (counted, then recompiled) and
    never reaches XLA's loader.
    """

    def __init__(self, root: Optional[os.PathLike] = None, *,
                 store: bool = False, store_url: Optional[str] = None):
        self.root = Path(root) if root is not None else default_cache_root()
        self.store = bool(store)
        self.store_url = store_url
        # local mirror of the kt_aot_cache_total counter: tests and
        # engine.aot_stats() read this without parsing telemetry text
        self.counts: Dict[str, int] = {}

    # -- accounting ---------------------------------------------------------

    def _count(self, result: str) -> None:
        self.counts[result] = self.counts.get(result, 0) + 1
        try:
            from .. import telemetry
            telemetry.cold_start_metrics()["aot"].inc(result=result)
        except Exception:
            pass

    # -- paths --------------------------------------------------------------

    def entry_dir(self, key: AOTKey) -> Path:
        return self.root / key.digest()

    def _store_key(self, key: AOTKey, name: str, content_hash: str) -> str:
        # the payload key is CONTENT-ADDRESSED: the blake2b of the bytes
        # is part of the name, so a fetched payload is verifiable against
        # its own key before anything deserializes it
        return f"aot/{key.digest()}/{name}/{content_hash}"

    def _store_ptr_key(self, key: AOTKey, name: str) -> str:
        return f"aot/{key.digest()}/{name}.ptr"

    # -- store ring layer ---------------------------------------------------
    #
    # Trust model: the executable payload rides pickle + XLA's loader, so
    # loading one is code execution. The content-addressed key pins the
    # payload to the hash its publisher named — a torn copy, a partial
    # overwrite, or a blob swapped under an existing key is rejected
    # before pickle ever sees it. What it cannot provide is provenance: a
    # writer who controls BOTH the pointer and the payload can still name
    # its own hash. Enabling ``store=True`` therefore asserts that every
    # principal with write access to the ``aot/`` prefix (and to the
    # local cache dir) is trusted to run code on this fleet — the same
    # trust the weight-distribution path already extends to the ring.

    def _store_fetch(self, key: AOTKey, name: str, bin_path: Path) -> bool:
        """Pull ``name`` from the store ring into the local layer. Any
        failure (store down, key absent, content-address mismatch) is a
        plain miss — the store is an accelerator, never a correctness
        dependency."""
        if not self.store:
            return False
        tmp = bin_path.with_name(f"{bin_path.name}.fetch.tmp")
        try:
            from ..data_store import commands as ds
            tmp.parent.mkdir(parents=True, exist_ok=True)
            try:
                ds.get(self._store_ptr_key(key, name), dest=str(tmp),
                       store_url=self.store_url)
                want = tmp.read_bytes().decode("ascii").strip()
            finally:
                tmp.unlink(missing_ok=True)
            if len(want) != 32 or not all(c in "0123456789abcdef"
                                          for c in want):
                self._count("store_corrupt")
                return False
            ds.get(self._store_key(key, name, want), dest=str(tmp),
                   store_url=self.store_url)
            data = tmp.read_bytes()
            tmp.unlink(missing_ok=True)
            if _blake2b(data) != want:
                # the payload does not match the hash its own key names:
                # never let it near pickle, never cache it locally
                self._count("store_corrupt")
                return False
            self._write_entry(key, name, data)
            self._count("store_hit")
            return True
        except Exception:
            tmp.unlink(missing_ok=True)
            return False

    def _store_publish(self, key: AOTKey, name: str, bin_path: Path) -> None:
        if not self.store:
            return
        try:
            from ..data_store import commands as ds
            content_hash = _blake2b(bin_path.read_bytes())
            # payload first, pointer last: a reader that wins the race
            # sees either a complete pair or a plain miss
            ds.put(self._store_key(key, name, content_hash), str(bin_path),
                   store_url=self.store_url)
            ptr = bin_path.with_name(f"{bin_path.name}.ptr.tmp")
            ptr.write_text(content_hash)
            try:
                ds.put(self._store_ptr_key(key, name), str(ptr),
                       store_url=self.store_url)
            finally:
                ptr.unlink(missing_ok=True)
            self._count("store_publish")
        except Exception:
            pass

    # -- local layer --------------------------------------------------------

    def _write_entry(self, key: AOTKey, name: str, data: bytes) -> None:
        from ..data_store.durability import durable_write_bytes
        import jax

        d = self.entry_dir(key)
        d.mkdir(parents=True, exist_ok=True)
        keyfile = d / "key.json"
        if not keyfile.exists():
            durable_write_bytes(keyfile, json.dumps(
                key.describe(), indent=2, sort_keys=True).encode())
        meta = {
            "blake2b": _blake2b(data),
            "nbytes": len(data),
            "jax": jax.__version__,
            "created": time.time(),
        }
        # bin first, meta last: a reader requires BOTH, so a crash
        # between the two commits reads as an absent entry, not a corrupt
        # one
        durable_write_bytes(d / f"{name}{_BIN_SUFFIX}", data)
        durable_write_bytes(d / f"{name}{_META_SUFFIX}",
                            json.dumps(meta).encode())

    def _other_digest_has(self, digest: str, name: str) -> bool:
        """A sibling cache line holding this executable name means the
        miss is a key MISMATCH (version/mesh/bucket drift), not a cold
        cache — operators want those distinguished."""
        try:
            for p in self.root.iterdir():
                if (p.is_dir() and p.name != digest
                        and (p / f"{name}{_BIN_SUFFIX}").exists()):
                    return True
        except OSError:
            pass
        return False

    def load(self, key: AOTKey, name: str):
        """Return the loaded executable for ``(key, name)`` or raise a
        typed miss. Never returns a wrong executable: the digest gates
        compatibility, the sidecar hash gates integrity."""
        d = self.entry_dir(key)
        bin_path = d / f"{name}{_BIN_SUFFIX}"
        meta_path = d / f"{name}{_META_SUFFIX}"
        if not (bin_path.exists() and meta_path.exists()):
            if not self._store_fetch(key, name, bin_path):
                reason = ("incompatible"
                          if self._other_digest_has(key.digest(), name)
                          else "absent")
                raise AOTCacheMissError(
                    f"AOT cache {reason} for {name!r}",
                    key=key.digest(), name=name, reason=reason)
        data = bin_path.read_bytes()
        try:
            meta = json.loads(meta_path.read_text())
            expected = meta["blake2b"]
        except Exception as e:
            raise AOTCacheCorruptError(
                f"AOT cache sidecar unreadable for {name!r}: {e}",
                key=key.digest(), name=name) from e
        actual = _blake2b(data)
        if actual != expected:
            raise AOTCacheCorruptError(
                f"AOT cache content hash mismatch for {name!r}",
                key=key.digest(), name=name,
                expected=expected, actual=actual)
        try:
            from jax.experimental import serialize_executable
            payload, in_tree, out_tree = pickle.loads(data)
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:
            raise AOTCacheCorruptError(
                f"AOT cache deserialize failed for {name!r}: {e}",
                key=key.digest(), name=name, expected=expected,
                actual=actual) from e

    def put(self, key: AOTKey, name: str, compiled) -> None:
        """Serialize ``compiled`` under ``(key, name)`` and (when the
        store layer is on) publish it for the fleet."""
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        data = pickle.dumps((payload, in_tree, out_tree))
        self._write_entry(key, name, data)
        self._count("publish")
        self._store_publish(key, name,
                            self.entry_dir(key) / f"{name}{_BIN_SUFFIX}")

    def get_or_compile(self, key: AOTKey, name: str,
                       build: Callable[[], Any]) -> Tuple[Any, str]:
        """The engine-facing path: hit → loaded executable; any typed
        miss → ``build()`` a fresh one, publish it, return it. The second
        element is the result tag (``hit``/``miss``/``incompatible``/
        ``corrupt``) for callers that report boot anatomy."""
        try:
            exe = self.load(key, name)
            self._count("hit")
            return exe, "hit"
        except AOTCacheCorruptError:
            result = "corrupt"
        except AOTCacheMissError as e:
            result = e.reason if e.reason == "incompatible" else "miss"
        self._count(result)
        compiled = build()
        try:
            self.put(key, name, compiled)
        except Exception:
            # a failed publish (read-only dir, disk full) must never fail
            # the boot that just paid for the compile
            pass
        return compiled, result


# -- engine warm-up ----------------------------------------------------------

def warm_engine(engine, cache: AOTCompileCache,
                key: Optional[AOTKey] = None) -> Dict[tuple, Any]:
    """Pre-compile the engine's common-signature executables through the
    cache and return the dispatch table ``engine._aot_exec`` consults:

    - ``("prefill", bucket)`` for every prefill bucket — the plain
      admission path (no adapter / nucleus / penalty kwargs),
    - ``("decode", k)`` for the configured decode block — the common
      decode dispatch whose only extra kwarg is ``skeys``.

    Uncommon signatures (LoRA banks, top-p, penalties, logit bias) keep
    riding the traced jits; they are sticky per-engine and rare at boot.
    Arguments here MUST mirror the engine call sites exactly — a drifted
    aval would compile a valid-but-never-hit executable and the engine
    would silently re-trace (the equivalence test in
    ``tests/test_cold_start.py`` pins token-exact agreement).
    """
    import jax
    import jax.numpy as jnp

    from . import engine as _eng

    t0 = time.monotonic()
    if key is None:
        key = AOTKey.for_engine(engine)
    exes: Dict[tuple, Any] = {}
    rng = jax.random.PRNGKey(0)
    for b in engine._buckets:
        def build(b=b):
            tokens = jnp.zeros((1, b), jnp.int32)
            return _eng._prefill.lower(
                engine.params, tokens, jnp.int32(1), rng,
                jnp.zeros((1,), jnp.float32), engine.cfg,
                top_k=engine.top_k).compile()
        exes[("prefill", b)], _ = cache.get_or_compile(
            key, f"prefill_{b}", build)
    k = engine.decode_block
    pos = jnp.zeros((engine.slots,), jnp.int32)
    toks = jnp.zeros((engine.slots,), jnp.int32)
    temps = jnp.zeros((engine.slots,), jnp.float32)
    skeys = jnp.zeros((engine.slots, 2), jnp.uint32)

    def build_decode():
        if k > 1:
            return _eng._decode_block.lower(
                engine.params, engine._cache, pos, toks, rng, temps,
                engine.cfg, n_steps=k, top_k=engine.top_k,
                skeys=skeys).compile()
        return _eng._decode_step.lower(
            engine.params, engine._cache, pos, toks, rng, temps,
            engine.cfg, top_k=engine.top_k, skeys=skeys).compile()

    exes[("decode", k)], _ = cache.get_or_compile(
        key, f"decode_{k}", build_decode)
    try:
        from .. import telemetry
        telemetry.cold_start_metrics()["phase_seconds"].observe(
            time.monotonic() - t0, phase="compile_or_cache")
    except Exception:
        pass
    return exes
