"""Continuous-batching generation engine.

The scanned :func:`kubetorch_tpu.models.generate.generate` compiles one
program per (batch, prompt-length, new-token-count) and runs each batch to
completion — right for offline eval, wrong for serving, where requests
arrive whenever they like and a finished sequence must hand its chip share
to the next caller immediately.

TPU-first design — everything the chip executes has a static shape:

- **Slot grid.** The KV cache is one fixed ``(L, SLOTS, S_max, NKV, Hd)``
  buffer. A request occupies a slot for its lifetime; admission and
  retirement are host-side bookkeeping, never a recompile.
- **One decode step for the whole grid.** Every step decodes ALL slots in a
  single jitted call — per-slot absolute positions (a ``(SLOTS,)`` vector)
  drive RoPE and the causal mask, so slots at different depths batch into
  the same matmuls. Idle slots compute masked garbage; that cost is the
  price of never changing shape, and it is what keeps the MXU busy when
  the grid is full.
- **Bucketed prefill.** Prompts are right-padded to a small set of bucket
  lengths (one compile each) and run through the same layer math as
  ``generate``'s prefill (flash kernel on TPU when shapes allow); the
  resulting K/V rows are spliced into the slot with a donated
  ``dynamic_update_slice`` — no host round-trip, no cache copy.
- **Buffer donation everywhere.** The decode step and the slot-splice
  donate the cache, so HBM holds exactly one grid regardless of step rate.

Under an ambient mesh (``parallel.mesh_context.use_mesh``) the same jits
run GSPMD-partitioned: NKV shards over ``tensor``, slots over data axes —
multi-chip serving is the training sharding story, unchanged.

Reference parity note: the reference has no engine analog (it serves
user-written handlers; batching is the user's problem) — this subsystem is
a deliberate beyond-parity capability on the serving side, sized for the
RLHF rollout actors (BASELINE config 4) and autoscaled inference services.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.generate import (KVCache, _layer_step, ffn_block, init_cache,
                               rope_freqs)
from ..models.llama import rmsnorm
from ..models.lora import lora_proj
from ..models.moe import moe_prefill_keep_capacity as _moe_keep_capacity
from ..models.quant import dequant_layer, lm_head_dot

NEG_INF = -1e30

# Decode-attention dispatch, frozen at import like generate's flash flag
# (the gate runs at trace time inside jits whose cache key never sees env):
# "1" forces the Pallas flash-decode kernel on (interpret mode off-TPU —
# how tests cover the branch), "0" forces the masked einsum, "auto" uses
# the kernel on the TPU backend.
_DECODE_KERNEL_FLAG = os.environ.get("KT_DECODE_KERNEL", "auto")


def _decode_kernel_wanted() -> bool:
    if _DECODE_KERNEL_FLAG == "1":
        return True
    if _DECODE_KERNEL_FLAG == "0":
        return False
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# device side
# ---------------------------------------------------------------------------


def _cache_shardings(cache):
    """NamedSharding pytree for the grid cache under the ambient mesh, or
    None off-mesh: slots over the batch axes, the SEQUENCE dim over
    ``context`` (long-context serving: 1/C of the cache per chip), heads
    over ``tensor``. Without the explicit constraint GSPMD is free to
    replicate the scan-carried cache even though the attention shard_map
    consumes it sharded — correct, but forfeiting the memory split."""
    from ..parallel.mesh_context import current_mesh
    mesh = current_mesh()
    if mesh is None:
        return None
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import live_axes
    live = live_axes(mesh)
    if not live:
        return None
    import math

    from ..parallel.mesh import normalize_batch_axes
    ba_all = tuple(a for a in ("dcn", "data", "fsdp") if a in live)

    def fit(axes, dim):
        """Largest prefix of ``axes`` whose total size divides ``dim`` —
        an explicit sharding must divide evenly (GSPMD pads on its own,
        device_put does not)."""
        while axes and dim % math.prod(live[a] for a in axes):
            axes = axes[:-1]
        return axes

    def leaf_sharding(x):
        # values (L, B, S, NKV, Hd); quant scales (L, B, S, NKV)
        ba = normalize_batch_axes(live, fit(ba_all, x.shape[1]))
        ctx = "context" if ("context" in live
                            and x.shape[2] % live["context"] == 0) else None
        tp = "tensor" if ("tensor" in live
                          and x.shape[3] % live["tensor"] == 0) else None
        spec = (P(None, ba, ctx, tp, None) if x.ndim == 5
                else P(None, ba, ctx, tp))
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(leaf_sharding, cache)


def _constrain_cache(cache):
    """In-jit layout pin (trace-time ambient mesh, like the MoE gate)."""
    sh = _cache_shardings(cache)
    if sh is None:
        return cache
    return jax.tree_util.tree_map(jax.lax.with_sharding_constraint,
                                  cache, sh)


def _rope_slot(x: jax.Array, freqs: jax.Array) -> jax.Array:
    """RoPE with a PER-SLOT rotation: x (B, N, Hd), freqs (B, Hd/2) complex.

    ``models.llama.apply_rope`` broadcasts one (T, Hd/2) table over the
    batch — decode slots sit at different absolute positions, so here the
    table is indexed per slot instead."""
    b, n, hd = x.shape
    xf = x.astype(jnp.float32).reshape(b, n, hd // 2, 2)
    xc = lax.complex(xf[..., 0], xf[..., 1])
    rotated = xc * freqs[:, None, :]
    out = jnp.stack([jnp.real(rotated), jnp.imag(rotated)], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _decode_layer(cfg, x, lw, ck, cv, pos, freqs, lora=None):
    """One layer over one new token per slot.

    x: (B, 1, D); ck/cv: (B, S, NKV, Hd); pos: (B,) absolute position of
    each slot's new token (also its cache row); freqs: (B, Hd/2) complex.
    ``lora``: per-slot adapters already gathered to (B, D, R)/(B, R, O)
    per target (multi-LoRA serving — see ``GenerationEngine`` docs).
    """
    b = x.shape[0]
    hd = cfg.head_dim
    nh, nkv = cfg.n_heads, cfg.n_kv_heads
    lw = dequant_layer(lw, cfg.dtype)    # int8 serving weights (models.quant)
    h = rmsnorm(x, lw["attn_norm"], cfg.norm_eps)
    q = lora_proj(h, lw["wq"], lora, "wq").reshape(b, nh, hd)
    k = lora_proj(h, lw["wk"], lora, "wk").reshape(b, nkv, hd)
    v = lora_proj(h, lw["wv"], lora, "wv").reshape(b, nkv, hd)
    q, k = _rope_slot(q, freqs), _rope_slot(k, freqs)

    bi = jnp.arange(b)
    ck = ck.at[bi, pos].set(k.astype(ck.dtype))
    cv = cv.at[bi, pos].set(v.astype(cv.dtype))

    from ..parallel.mesh_context import current_mesh
    from ..parallel.ring_attention import (sp_decode_attention_sharded,
                                           sp_decode_supported)
    mesh = current_mesh()
    if mesh is not None and sp_decode_supported(mesh, b, ck.shape[1],
                                                nkv, nh):
        # long-context serving: the cache's sequence axis is sharded over
        # the context mesh axis; local attention + one online-softmax
        # combine beats the all-gather GSPMD would otherwise insert (and
        # the Pallas kernel, which needs all rows on one chip). Trace-time
        # gate like the MoE gather (mesh fixed per engine — captured at
        # construction and re-installed on whichever thread traces);
        # shapes that don't divide the mesh fall back to the dense path.
        attn = sp_decode_attention_sharded(
            q, ck, cv, pos, mesh, scale=hd ** -0.5).reshape(b, 1, nh * hd)
    elif _decode_kernel_wanted():
        # fused flash-decode: streams K/V tiles, skips tiles past each
        # slot's frontier entirely (ops/decode_attention.py)
        from ..ops.decode_attention import decode_attention
        attn = decode_attention(q, ck, cv, pos,
                                scale=hd ** -0.5).reshape(b, 1, nh * hd)
    else:
        group = nh // nkv
        qg = q.reshape(b, nkv, group, hd)
        logits = (jnp.einsum("bkgh,bskh->bkgs", qg, ck).astype(jnp.float32)
                  * (hd ** -0.5))
        s = ck.shape[1]
        mask = jnp.arange(s)[None, :] <= pos[:, None]      # (B, S)
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1).astype(cv.dtype)
        attn = jnp.einsum("bkgs,bskh->bkgh", probs,
                          cv).reshape(b, 1, nh * hd)
    x = x + lora_proj(attn, lw["wo"], lora, "wo")
    h = rmsnorm(x, lw["ffn_norm"], cfg.norm_eps)
    return x + ffn_block(cfg, h, lw), ck, cv


def _decode_layer_quant(cfg, x, lw, kq, ks, vq, vs, pos, freqs, lora=None):
    """One layer over one new token per slot against an int8 cache
    (``kv_quant``): identical projection/RoPE/FFN math to ``_decode_layer``,
    but the new row is QUANTIZED before it is written and attention folds
    the row scales in (logits columns ·ks, probs ·vs) instead of
    materializing fp rows — the reference math the Pallas quant kernel is
    bit-compatible with."""
    from .kv_quant import quantize_rows
    b = x.shape[0]
    hd = cfg.head_dim
    nh, nkv = cfg.n_heads, cfg.n_kv_heads
    lw = dequant_layer(lw, cfg.dtype)
    h = rmsnorm(x, lw["attn_norm"], cfg.norm_eps)
    q = lora_proj(h, lw["wq"], lora, "wq").reshape(b, nh, hd)
    k = lora_proj(h, lw["wk"], lora, "wk").reshape(b, nkv, hd)
    v = lora_proj(h, lw["wv"], lora, "wv").reshape(b, nkv, hd)
    q, k = _rope_slot(q, freqs), _rope_slot(k, freqs)

    bi = jnp.arange(b)
    k_row, ks_row = quantize_rows(k)
    v_row, vs_row = quantize_rows(v)
    kq = kq.at[bi, pos].set(k_row)
    ks = ks.at[bi, pos].set(ks_row)
    vq = vq.at[bi, pos].set(v_row)
    vs = vs.at[bi, pos].set(vs_row)

    from ..parallel.mesh_context import current_mesh
    from ..parallel.ring_attention import (
        sp_decode_attention_quant_sharded, sp_decode_supported)
    mesh = current_mesh()
    if mesh is not None and sp_decode_supported(mesh, b, kq.shape[1],
                                                nkv, nh):
        # int8 cache × context sharding compose: 1/(2C) of the fp cache
        # bytes per chip, scales folded into the per-shard combine
        attn = sp_decode_attention_quant_sharded(
            q, kq, ks, vq, vs, pos, mesh,
            scale=hd ** -0.5).reshape(b, 1, nh * hd).astype(x.dtype)
    elif _decode_kernel_wanted():
        from ..ops.decode_attention import decode_attention_quant
        attn = decode_attention_quant(
            q, kq, ks, vq, vs, pos,
            scale=hd ** -0.5).reshape(b, 1, nh * hd).astype(x.dtype)
    else:
        group = nh // nkv
        s = kq.shape[1]
        qg = q.reshape(b, nkv, group, hd).astype(jnp.float32)
        logits = jnp.einsum("bkgh,bskh->bkgs", qg,
                            kq.astype(jnp.float32)) * (hd ** -0.5)
        logits = logits * ks.transpose(0, 2, 1)[:, :, None, :]
        mask = jnp.arange(s)[None, :] <= pos[:, None]
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = probs * vs.transpose(0, 2, 1)[:, :, None, :]
        attn = jnp.einsum("bkgs,bskh->bkgh", probs,
                          vq.astype(jnp.float32)).reshape(
                              b, 1, nh * hd).astype(x.dtype)
    x = x + lora_proj(attn, lw["wo"], lora, "wo")
    h = rmsnorm(x, lw["ffn_norm"], cfg.norm_eps)
    return x + ffn_block(cfg, h, lw), kq, ks, vq, vs


def _sample_slots(logits, key, temps, top_k: Optional[int], top_ps=None,
                  lp_logits=None, keys=None):
    """Per-slot sampling: temps (B,) — 0 means greedy for THAT slot;
    ``top_ps`` (B,) — nucleus mass per slot, 1.0 disables. Vectorized
    (traced arrays, not statics) so requests with different temperatures /
    top-p share one compiled step. ``top_ps=None`` (static) skips the
    full-vocab sort entirely — engines never pay for nucleus sampling
    until a request asks for it. ``keys`` (B, 2) uint32 draws each ROW
    from its own key (per-request seeded streams — decode path); ``key``
    drives the whole batch otherwise (prefill, spec drafts). Agrees with
    ``sample_logits`` slot-wise: argmax for temp 0,
    temperature/top-k/top-p categorical otherwise."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.where(temps > 0, temps, 1.0)[:, None]
    if top_k is not None:
        kth = lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, NEG_INF, scaled)
    if top_ps is not None:
        from ..models.generate import nucleus_mask
        scaled = nucleus_mask(scaled, top_ps)
    if keys is not None:
        sampled = jax.vmap(jax.random.categorical)(keys, scaled) \
            .astype(jnp.int32)
    else:
        sampled = jax.random.categorical(key, scaled,
                                         axis=-1).astype(jnp.int32)
    tok = jnp.where(temps > 0, sampled, greedy)
    # raw-model (temperature-independent) logprob of the chosen token —
    # the OpenAI ``logprobs`` number; one logsumexp against the matmuls.
    # ``lp_logits`` lets penalty-adjusted callers pass the PRE-penalty
    # logits here, keeping the score raw while the choice is steered.
    logp = jax.nn.log_softmax(logits if lp_logits is None else lp_logits,
                              axis=-1)
    lp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
    return tok, lp


def _decode_step_impl(params, cache, pos, toks, rng, temps, cfg,
                      top_k: Optional[int] = None, banks=None, aidx=None,
                      lora_scale: float = 1.0, top_ps=None,
                      counts=None, fpen=None, ppen=None,
                      bias=None, bmask=None, skeys=None):
    """Single-step decode math shared by the jitted one-step
    :func:`_decode_step` and the scanned K-step :func:`_decode_block`.
    ``bias`` (SLOTS, V) + ``bmask`` (SLOTS,): per-slot OpenAI logit_bias,
    added before sampling for slots whose mask is 1 (stale rows from past
    occupants are neutralized by the mask, like the penalty multipliers).
    ``skeys`` (SLOTS, 2) uint32: per-slot sampling keys, folded with each
    slot's position — every request's sampled stream is a pure function
    of (its key, its positions), independent of neighbors, step batching,
    and the engine-wide chain (what makes per-request ``seed`` exact and
    block decode bit-equal to one-step even when sampling).
    Always returns the 4-tuple (cache', next_tok, logprobs, counts') —
    ``counts'`` is None when ``counts`` is."""
    from .kv_quant import QuantKVCache
    quant = isinstance(cache, QuantKVCache)
    s_max = cache.kq.shape[2] if quant else cache.k.shape[2]
    x = params["embed"][toks[:, None]].astype(cfg.dtype)   # (B, 1, D)
    freqs = rope_freqs(cfg, s_max)[pos]                     # (B, Hd/2)

    from ..models.lora import gather_slot_adapters

    def make_lora(bank_l):
        return gather_slot_adapters(bank_l, aidx, lora_scale, banks)

    if quant:
        def body(carry, layer):
            lw, kq, ks, vq, vs, bank_l = layer
            h, kq, ks, vq, vs = _decode_layer_quant(
                cfg, carry, lw, kq, ks, vq, vs, pos, freqs,
                lora=make_lora(bank_l))
            return h, (kq, ks, vq, vs)

        x, leaves = lax.scan(body, x, (params["layers"], cache.kq, cache.ks,
                                       cache.vq, cache.vs, banks or {}))
        new_cache = QuantKVCache(*leaves)
    else:
        def body(carry, layer):
            lw, ck, cv, bank_l = layer
            h, ck, cv = _decode_layer(cfg, carry, lw, ck, cv, pos, freqs,
                                      lora=make_lora(bank_l))
            return h, (ck, cv)

        x, (nk, nv) = lax.scan(body, x, (params["layers"], cache.k, cache.v,
                                         banks or {}))
        new_cache = KVCache(nk, nv)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = lm_head_dot(x[:, 0], params, cfg.dtype)
    raw_logits = logits
    if counts is not None:
        # OpenAI-style repetition control: subtract per-token penalties
        # derived from each slot's seen-token counts (prompt + generated)
        # BEFORE sampling — greedy slots with zero penalties see logits
        # unchanged, so isolation holds bit-exactly. Reported logprobs
        # stay RAW-model (penalties steer the choice, not the score).
        logits = logits - (fpen[:, None] * counts.astype(jnp.float32)
                           + ppen[:, None] * (counts > 0))
    if bias is not None:
        logits = logits + bias * bmask[:, None]
    step_keys = (jax.vmap(jax.random.fold_in)(skeys, pos)
                 if skeys is not None else None)
    nxt, lps = _sample_slots(logits, rng, temps, top_k, top_ps,
                             lp_logits=raw_logits, keys=step_keys)
    if counts is not None:
        counts = counts.at[jnp.arange(counts.shape[0]), nxt].add(1)
    return _constrain_cache(new_cache), nxt, lps, counts


@partial(jax.jit, static_argnames=("cfg", "top_k", "lora_scale"),
         donate_argnums=(1,), donate_argnames=("counts",))
def _decode_step(params, cache, pos, toks, rng, temps, cfg,
                 top_k: Optional[int] = None, banks=None, aidx=None,
                 lora_scale: float = 1.0, top_ps=None,
                 counts=None, fpen=None, ppen=None,
                 bias=None, bmask=None, skeys=None):
    """Advance EVERY slot one token. toks (B,) is each slot's current input
    token; pos (B,) its absolute position; temps (B,) its sampling
    temperature. ``banks`` (target → (A (L,N,D,R), B (L,N,R,O))) + ``aidx``
    (B,) select each slot's LoRA adapter (index 0 = the zero adapter =
    base model). ``cache`` is a ``KVCache`` or an int8 ``QuantKVCache``
    (``kv_quant``) — the pytree structure keys the jit, so each engine
    compiles exactly one of the two bodies. Returns (cache', next_tok)."""
    cache, nxt, lps, counts = _decode_step_impl(
        params, cache, pos, toks, rng, temps, cfg, top_k=top_k, banks=banks,
        aidx=aidx, lora_scale=lora_scale, top_ps=top_ps, counts=counts,
        fpen=fpen, ppen=ppen, bias=bias, bmask=bmask, skeys=skeys)
    if counts is not None:
        return cache, nxt, lps, counts
    return cache, nxt, lps


@partial(jax.jit, static_argnames=("cfg", "top_k", "lora_scale", "n_steps"),
         donate_argnums=(1,), donate_argnames=("counts",))
def _decode_block(params, cache, pos, toks, rng, temps, cfg, n_steps: int,
                  top_k: Optional[int] = None, banks=None, aidx=None,
                  lora_scale: float = 1.0, top_ps=None,
                  counts=None, fpen=None, ppen=None,
                  bias=None, bmask=None, skeys=None):
    """Advance every slot ``n_steps`` tokens in ONE dispatch: a ``lax.scan``
    over :func:`_decode_step_impl`, so the host pays the dispatch/sync
    overhead once per block instead of once per token — the difference
    between ~dispatch-bound and ~HBM-bound serving decode (on the remote
    relay each dispatch is tens of ms; the per-step math is ~2ms).

    A slot that retires mid-block (eos/stop/budget) keeps computing garbage
    for the rest of the block; the host discards those tokens at emit time.
    Its overshoot cache writes at positions ≥ S_max are XLA scatter-drops
    (out-of-bounds scatter indices are dropped, not clipped), and rows past
    a retired frontier are never attended before being rewritten — so the
    garbage is unobservable. Returns
    (cache', final_pos, final_tok, toks (K, B), logprobs (K, B), counts')."""

    def step_fn(carry, k):
        cache, pos, toks, counts = carry
        key = jax.random.fold_in(rng, k)
        cache, nxt, lps, counts = _decode_step_impl(
            params, cache, pos, toks, key, temps, cfg, top_k=top_k,
            banks=banks, aidx=aidx, lora_scale=lora_scale, top_ps=top_ps,
            counts=counts, fpen=fpen, ppen=ppen, bias=bias, bmask=bmask,
            skeys=skeys)
        return (cache, pos + 1, nxt, counts), (nxt, lps)

    (cache, pos, toks, counts), (toks_k, lps_k) = lax.scan(
        step_fn, (cache, pos, toks, counts), jnp.arange(n_steps))
    return cache, pos, toks, toks_k, lps_k, counts


@partial(jax.jit, static_argnames=("cfg", "top_k", "lora_scale"))
def _prefill(params, tokens, true_len, rng, temps, cfg,
             top_k: Optional[int] = None, adapter=None,
             lora_scale: float = 1.0, top_ps=None, pen_row=None):
    """Prompt pass at one bucket length. tokens (1, T_bucket) right-padded;
    logits are taken at the REAL last position ``true_len - 1`` (padding
    rows only pollute their own cache rows, which decode overwrites before
    ever attending to them). Returns (first_token (1,), k, v) with k/v
    (L, 1, T_bucket, NKV, Hd)."""
    b, t = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    freqs_full = rope_freqs(cfg, t)
    q_pos = jnp.arange(t)
    from ..models.generate import _flash_prefill_wanted
    flash = _flash_prefill_wanted(cfg, t)
    cache = init_cache(cfg, b, t)
    # Padding must not perturb MoE routing: masked tokens never claim a
    # capacity slot, and the overflow-drop threshold is the REAL length's
    # capacity (the static buffer stays bucket-sized) — so a bucketed
    # prompt routes bit-identically to its unpadded solo run.
    token_mask = (q_pos < true_len)[None, :]
    keep_capacity = _moe_keep_capacity(cfg, true_len)

    def body(carry, layer):
        lw, ck, cv, ad_l = layer
        lora = (ad_l, lora_scale) if adapter else None
        h, ck, cv = _layer_step(cfg, carry, lw, ck, cv, q_pos, freqs_full,
                                flash_prefill=flash, token_mask=token_mask,
                                keep_capacity=keep_capacity, lora=lora,
                                causal_prefill=True)
        return h, (ck, cv)

    x, (nk, nv) = lax.scan(body, x, (params["layers"], cache.k, cache.v,
                                     adapter or {}))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    h_last = x[jnp.arange(b), true_len - 1]                 # (1, D)
    logits = lm_head_dot(h_last, params, cfg.dtype)
    raw_logits = logits
    if pen_row is not None:
        logits = logits - pen_row[None, :]
    first, lps = _sample_slots(logits, rng, temps, top_k, top_ps,
                               lp_logits=raw_logits)
    return first, nk, nv, lps




@partial(jax.jit, static_argnames=("cfg", "top_k", "lora_scale"))
def _prefill_suffix(params, tokens, true_len, prefix_k, prefix_v, prefix_len,
                    rng, temps, cfg, top_k: Optional[int] = None,
                    adapter=None, lora_scale: float = 1.0, top_ps=None,
                    pen_row=None):
    """Suffix prompt pass behind a cached prefix: tokens (1, T_bucket)
    right-padded run at absolute positions ``prefix_len + i`` attending the
    prefix's REAL K/V rows plus themselves. The prefix stays padded to its
    BUCKET (``prefix_k``: (L, 1, P_bucket, NKV, Hd); ``prefix_len`` is the
    traced true length), so compiles are bounded by bucket pairs, never by
    distinct prefix lengths. Suffix rows are written starting at
    ``prefix_len`` — over the prefix's padding garbage — and the causal
    mask (kv_pos <= q_pos) never admits an unwritten row. Returns
    (first_token, k, v) with k/v covering rows [0, P_bucket + T_bucket),
    ready to splice into a slot.

    Exact for dense models (same math as a from-zero prefill of
    prefix+suffix). For MoE, expert capacity is per SEGMENT (the prefix
    routed at registration, the suffix here), so overflow-drop pressure can
    differ from a solo full-prompt run — the standard prefix-cache trade;
    identical whenever no expert overflows."""
    b, t = tokens.shape
    p_bucket = prefix_k.shape[2]
    x = params["embed"][tokens].astype(cfg.dtype)
    freqs_full = rope_freqs(cfg, p_bucket + t)
    q_pos = prefix_len + jnp.arange(t)
    token_mask = (jnp.arange(t) < true_len)[None, :]
    keep_capacity = _moe_keep_capacity(cfg, true_len)
    pad = jnp.zeros((prefix_k.shape[0], b, t) + prefix_k.shape[3:],
                    prefix_k.dtype)
    ck0 = jnp.concatenate([prefix_k, pad], axis=2)
    cv0 = jnp.concatenate([prefix_v, pad], axis=2)

    def body(carry, layer):
        lw, ck, cv, ad_l = layer
        lora = (ad_l, lora_scale) if adapter else None
        h, ck, cv = _layer_step(cfg, carry, lw, ck, cv, q_pos, freqs_full,
                                flash_prefill=False, token_mask=token_mask,
                                keep_capacity=keep_capacity, lora=lora)
        return h, (ck, cv)

    x, (nk, nv) = lax.scan(body, x, (params["layers"], ck0, cv0,
                                     adapter or {}))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    h_last = x[jnp.arange(b), true_len - 1]
    logits = lm_head_dot(h_last, params, cfg.dtype)
    raw_logits = logits
    if pen_row is not None:
        logits = logits - pen_row[None, :]
    first, lps = _sample_slots(logits, rng, temps, top_k, top_ps,
                               lp_logits=raw_logits)
    return first, nk, nv, lps


@partial(jax.jit, donate_argnums=(0,))
def _set_counts_row(counts, slot, row):
    """Seed one slot's seen-token counts at admission (prompt + prefix +
    first sampled token); stale rows from prior occupants never matter —
    zero-penalty slots multiply them by 0."""
    return counts.at[slot].set(row)


@partial(jax.jit, donate_argnums=(0,))
def _splice_slot(cache, slot, k_new, v_new):
    """Write a prefill's K/V rows into one slot of the grid cache, donated
    (no second grid-sized buffer ever exists). k/v_new: (L, 1, T_b, ...) in
    the model dtype; for an int8 ``QuantKVCache`` grid the rows quantize
    HERE — prefill itself always runs full-precision math."""
    from .kv_quant import QuantKVCache, quantize_rows
    if isinstance(cache, QuantKVCache):
        kq, ks = quantize_rows(k_new)
        vq, vs = quantize_rows(v_new)
        start = (0, slot, 0, 0, 0)
        sstart = (0, slot, 0, 0)
        return _constrain_cache(QuantKVCache(
            kq=lax.dynamic_update_slice(cache.kq, kq, start),
            ks=lax.dynamic_update_slice(cache.ks, ks, sstart),
            vq=lax.dynamic_update_slice(cache.vq, vq, start),
            vs=lax.dynamic_update_slice(cache.vs, vs, sstart)))
    start = (0, slot, 0, 0, 0)
    return _constrain_cache(KVCache(
        k=lax.dynamic_update_slice(cache.k, k_new.astype(cache.k.dtype), start),
        v=lax.dynamic_update_slice(cache.v, v_new.astype(cache.v.dtype), start)))


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------


def _normalize_stop(stop) -> tuple:
    """One token-id sequence or a list of them → tuple of non-empty int
    tuples. An int-leading sequence is ONE stop sequence, not a list."""
    if stop is None or len(stop) == 0:
        return ()
    # scalar-leading (python or numpy int) → ONE sequence; else a list of
    # sequences (tokenizer pipelines hand numpy ids, not python ints)
    seqs = [stop] if not hasattr(stop[0], "__len__") else list(stop)
    if any(len(q) == 0 for q in seqs):
        raise ValueError("empty stop sequence")
    return tuple(tuple(int(t) for t in q) for q in seqs)


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: Optional[float] = None      # None → engine default
    top_p: Optional[float] = None            # None → engine default
    frequency_penalty: float = 0.0           # OpenAI-style repetition ctl
    presence_penalty: float = 0.0
    logit_bias: Optional[Dict[int, float]] = None  # token id → additive bias
    seed: Optional[int] = None               # reproducible sampling stream
    stop: tuple = ()                         # stop token-id sequences
    prefix_id: Optional[int] = None          # cached shared-prefix K/V
    full_prompt: Optional[List[int]] = None  # pre-strip prompt (auto match)
    adapter_id: Optional[int] = None         # registered LoRA adapter
    cancelled: bool = False                  # reaped at the next step
    error: Optional[BaseException] = None    # admission failure, surfaced
    out: "queue.Queue[Optional[int]]" = field(default_factory=queue.Queue)
    tail: list = field(default_factory=list)  # last max(len(stop)) tokens
    logprobs: list = field(default_factory=list)  # raw-model lp per token
    generated: int = 0
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None


class RequestHandle:
    """Streaming view of one request: iterate tokens as they decode, or
    block for the full completion. Tokens drained from the queue are kept on
    the handle, so a ``result()`` that times out loses nothing — a retry
    (or a later iteration) sees the full stream from the start. Single
    consumer: share the handle's results, not the handle, across threads."""

    def __init__(self, req: _Request, engine: "GenerationEngine" = None):
        self._req = req
        self._engine = engine
        self._collected: List[int] = []
        self._done = False

    @property
    def request_id(self) -> int:
        return self._req.rid

    @property
    def logprobs(self):
        """Raw-model (temperature-independent) logprob per DRAINED token,
        aligned with the tokens this handle has yielded so far (the full
        completion after ``result()``). Entries are None on paths that
        don't compute them (speculative verify)."""
        return list(self._req.logprobs[:len(self._collected)])

    def cancel(self) -> bool:
        """Abandon this request (``GenerationEngine.cancel``): the stream
        ends cleanly with whatever tokens already decoded."""
        return (self._engine.cancel(self._req.rid)
                if self._engine is not None else False)

    def _pull(self, timeout: Optional[float]) -> bool:
        """Move one queue item into ``_collected``; False once finished.
        ``timeout=0`` means the item must already be queued."""
        if self._done:
            return False
        try:
            tok = (self._req.out.get_nowait() if timeout is not None
                   and timeout <= 0 else self._req.out.get(timeout=timeout))
        except queue.Empty:
            raise TimeoutError(
                f"request {self._req.rid} still decoding") from None
        if tok is None:
            self._done = True
            if self._req.error is not None:
                raise self._req.error
            return False
        self._collected.append(tok)
        return True

    def __iter__(self):
        i = 0
        while True:
            while i < len(self._collected):
                yield self._collected[i]
                i += 1
            if not self._pull(None):
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """All generated tokens (prompt excluded), blocking to completion.
        ``timeout=0`` requires the request to already be complete."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._done:
            left = (None if deadline is None
                    else deadline - time.monotonic())
            self._pull(left)
        if self._req.error is not None:
            raise self._req.error
        return list(self._collected)

    def time_to_first_token(self) -> Optional[float]:
        if self._req.first_token_at is None:
            return None
        return self._req.first_token_at - self._req.submitted_at


@dataclass
class EngineStats:
    slots: int
    active: int
    queued: int
    admitted_total: int
    finished_total: int
    tokens_generated: int
    decode_steps: int
    tokens_per_sec: float
    # rolling mean time-to-first-token over the last admissions (secs);
    # 0.0 until anything has admitted
    ttft_avg: float = 0.0


class GenerationEngine:
    """Continuous-batching decode over a fixed slot grid (module docstring
    has the design). Drive it manually with :meth:`step` (deterministic —
    how the tests use it) or start the background loop with :meth:`start`.

    ``params``/``cfg`` are any decoder family ``models.generate`` handles:
    Llama-dense or MoE (a ``router`` leaf switches the FFN). ``eos_id``
    retires a slot early; ``max_len`` caps prompt+completion per request.
    """

    def __init__(self, params: Dict[str, Any], cfg, *, slots: int = 8,
                 max_len: int = 1024, eos_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 prefill_buckets: Sequence[int] = (128, 256, 512, 1024),
                 quantize_kv: bool = False, seed: int = 0,
                 decode_block: int = 1, auto_prefix: bool = False,
                 prefill_chunk: Optional[int] = None, aot_cache=None):
        self.params = params
        self.cfg = cfg
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = top_k
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        self.top_p = None if top_p is None else float(top_p)
        self.quantize_kv = bool(quantize_kv)
        if decode_block < 1:
            raise ValueError(f"decode_block must be >= 1, got {decode_block}")
        # K decode steps per dispatch (_decode_block): amortizes the
        # per-dispatch host/relay overhead across K tokens. Admission,
        # retirement, and cancellation stay host-side, honored at block
        # boundaries — worst-case K-1 garbage steps per retiring slot and
        # up to one block of extra latency on cancel and admission. Every
        # dispatch runs the full K (one compiled variant, honored exactly
        # as configured). 1 = the historical one-token step() (what the
        # deterministic tests drive).
        self.decode_block = int(decode_block)
        # chunked prefill: a prompt longer than this admits over multiple
        # engine steps — one fixed-size chunk of prefill between decode
        # blocks — so a long admission never stalls the active streams for
        # more than one chunk. Chunk i extends the accumulated K/V through
        # the prefix-suffix math (exact for dense models; MoE expert
        # capacity becomes per-CHUNK, the standard chunked-prefill trade).
        # None = one-shot admission (the historical behavior).
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        # (req, slot, k_acc, v_acc, consumed, frontier, adapter_kw, aidx,
        #  prefix_tokens)
        self._chunking: Optional[tuple] = None
        # constant key for non-sampling (intermediate) prefill chunks
        self._dummy_key = jax.random.PRNGKey(0)
        # per-slot sampling keys: each slot's stream is a pure function of
        # (its key, its positions) — a request with seed=S decodes the
        # same tokens whatever slot it lands in, whoever its neighbors
        # are, and whatever decode_block is; unseeded requests draw their
        # key from the engine chain at admission
        self._skeys = np.zeros((self.slots, 2), np.uint32)
        # the ambient mesh is THREAD-LOCAL trace state: capture it at
        # construction and re-install it around every trace site, or an
        # engine driven by its background loop thread (start()/generate(),
        # the kt.cls deployment mode) would silently lose the mesh-aware
        # dispatch (context-sharded decode, MoE gather gating)
        from ..parallel.mesh_context import current_mesh
        self._mesh = current_mesh()
        self._buckets = sorted({min(b, self.max_len)
                                for b in prefill_buckets} | {self.max_len})
        if self.quantize_kv:
            # int8 grid (kv_quant): halves the decode HBM stream + cache
            # footprint; prefill/prefix math stays full-precision, rows
            # quantize at the splice
            from .kv_quant import init_quant_cache
            self._cache = init_quant_cache(cfg, self.slots, self.max_len)
        else:
            self._cache = init_cache(cfg, self.slots, self.max_len)
        shardings = _cache_shardings(self._cache)
        if shardings is not None:
            # grid lives sharded from step 0 (slots over data axes, the
            # sequence dim over context, heads over tensor)
            self._cache = jax.device_put(self._cache, shardings)
        self._pos = np.zeros(self.slots, np.int32)     # next write position
        self._tok = np.zeros(self.slots, np.int32)     # next decode input
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        self._pending: "deque[_Request]" = deque()
        self._temps = np.zeros(self.slots, np.float32)
        self._top_ps = np.ones(self.slots, np.float32)
        self._fpen = np.zeros(self.slots, np.float32)
        self._ppen = np.zeros(self.slots, np.float32)
        # (SLOTS, V) seen-token counts, allocated on the first penalized
        # request (sticky, like _nucleus): V-sized buffers and the per-step
        # scatter only exist once someone pays for them
        self._counts = None
        # (SLOTS, V) logit_bias rows + per-slot mask, allocated on the
        # first biased request (same sticky pattern); the mask neutralizes
        # stale rows, so retirement never needs a device write
        self._bias = None
        self._bmask = np.zeros(self.slots, np.float32)
        # sticky: flips on the first nucleus request so the common
        # no-top-p engine never compiles (or pays for) the vocab sort;
        # afterwards both step variants stay in the jit cache
        self._nucleus = self.top_p is not None and self.top_p < 1.0
        # id → (k_bucketed, v_bucketed, true_len, tokens, adapter_id)
        self._prefixes: Dict[int, tuple] = {}
        self._prefix_ids = itertools.count()
        # auto_prefix: submit() reuses the LONGEST registered prefix the
        # prompt starts with (same adapter), no prefix_id needed — register
        # the system prompts / few-shot headers once, every matching
        # request skips recomputing them
        self.auto_prefix = bool(auto_prefix)
        self._prefix_hits = 0
        # multi-LoRA: stacked adapter banks, target → (A (L,N,D,R),
        # B (L,N,R,O)); bank index 0 is the all-zero adapter (= base model),
        # which idle and base-traffic slots point at
        self._lora_cfg = None
        self._banks: Optional[Dict[str, tuple]] = None
        self._adapter_slots: Dict[int, int] = {}   # public id → bank index
        self._free_bank: List[int] = []
        self._adapter_ids = itertools.count(1)
        self._aidx = np.zeros(self.slots, np.int32)
        self._admitting: Optional[_Request] = None   # cancel() window
        self._rng = jax.random.PRNGKey(seed)
        self._rid = itertools.count()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # start()/stop() are reached concurrently when the engine serves as
        # a kt.cls (the pod runs sync methods on an executor): exactly one
        # loop thread may ever exist — two would interleave _decode_step on
        # the same donated cache
        self._lifecycle = threading.Lock()
        # callables queued for the next batch boundary (weight hot swap —
        # serve/rollout.py is the only assigner of self.params after
        # construction; see at_batch_boundary)
        self._boundary_hooks: "deque[tuple]" = deque()
        # continuous-learning tap (flywheel/ledger.py): when set — e.g. to
        # flywheel.ledger.engine_feedback_hook(ledger) — every retired
        # request's summary passes through it once, on the step thread.
        # The sink owns sampling and MUST swallow its own errors; the
        # retire path still guards, because a raised sink would wedge the
        # decode loop for every live slot, not just the sampled one.
        self.feedback_sink = None
        # stats
        self._admitted = self._finished = 0
        self._tokens = self._steps = 0
        self._ttfts = deque(maxlen=256)   # rolling TTFT window
        self._t0 = time.monotonic()
        # persistent AOT compile cache (ISSUE 16): pre-load the
        # common-signature executables (prefill per bucket + the decode
        # step) so a warm replica skips tracing entirely. Mesh-sharded
        # engines keep the traced-jit path: serialized executables bake
        # device assignments, which don't survive a different pod's mesh.
        self._aot_cache = aot_cache
        self._aot_exec: Dict[tuple, Any] = {}
        if aot_cache is not None and self._mesh is None:
            from .aot_cache import warm_engine
            self._aot_exec = warm_engine(self, aot_cache)

    # -- adapters -----------------------------------------------------------

    def register_adapter(self, adapters: Dict[str, Any], lora_cfg) -> int:
        """Install a LoRA adapter (``models.lora.lora_init`` layout:
        ``layers`` dict of per-target stacked ``{t}__a`` (L, D, R) /
        ``{t}__b`` (L, R, O) factors) for UNMERGED activation-path serving:
        requests submitted with the returned id run ``x·W + s·(x·A)·B``
        through one compiled step shared with every other adapter and the
        base model — different slots, different adapters, no weight swap.

        All adapters on one engine must share the first registration's
        rank, targets, and scale (they stack into one bank per target).
        Growing the bank (a registration with no free slot) changes the
        decode step's shapes — one recompile; prefer registering the fleet
        up front. Freed slots (:meth:`unregister_adapter`) are reused
        without recompiling."""
        layers = adapters.get("layers", adapters)
        served = {"wq", "wk", "wv", "wo"}
        extra = set(lora_cfg.targets) - served
        if extra:
            # training (lora_loss/merge_lora) adapts ANY layer leaf, but the
            # serving path applies lora_proj only at the attention
            # projections — banking other targets would silently drop them
            raise ValueError(
                f"activation-path serving supports targets {sorted(served)}; "
                f"got {sorted(extra)} — serve those via merge_lora instead")
        pairs = {}
        for t in lora_cfg.targets:
            try:
                pairs[t] = (jnp.asarray(layers[f"{t}__a"]),
                            jnp.asarray(layers[f"{t}__b"]))
            except KeyError:
                raise KeyError(
                    f"adapter missing factors for target {t!r} "
                    f"(have {sorted(layers)})") from None
        with self._lock:
            # config check under the lock: two racing first registrations
            # must not both pass the None check and stack mismatched
            # factors (the loser would serve with the winner's scale)
            if self._lora_cfg is not None and (
                    lora_cfg.rank != self._lora_cfg.rank
                    or tuple(lora_cfg.targets) != tuple(self._lora_cfg.targets)
                    or lora_cfg.scale != self._lora_cfg.scale):
                raise ValueError(
                    f"adapter config {lora_cfg} does not match the engine's "
                    f"existing bank config {self._lora_cfg} (one bank per "
                    "engine: rank/targets/scale must agree)")
            self._lora_cfg = self._lora_cfg or lora_cfg
            if self._banks is None:
                self._banks = {
                    t: (jnp.stack([jnp.zeros_like(a), a], axis=1),
                        jnp.stack([jnp.zeros_like(b), b], axis=1))
                    for t, (a, b) in pairs.items()}
                idx = 1
            elif self._free_bank:
                idx = self._free_bank.pop()
                self._banks = {
                    t: (A.at[:, idx].set(pairs[t][0]),
                        B.at[:, idx].set(pairs[t][1]))
                    for t, (A, B) in self._banks.items()}
            else:
                idx = next(iter(self._banks.values()))[0].shape[1]
                self._banks = {
                    t: (jnp.concatenate([A, pairs[t][0][:, None]], axis=1),
                        jnp.concatenate([B, pairs[t][1][:, None]], axis=1))
                    for t, (A, B) in self._banks.items()}
            aid = next(self._adapter_ids)
            self._adapter_slots[aid] = idx
        return aid

    def unregister_adapter(self, adapter_id: int) -> bool:
        """Free an adapter's bank slot (reused by the next registration —
        no recompile). The slot's factors are zeroed and any request still
        DECODING on it is repointed at bank index 0, so it falls back to
        the base model mid-stream — never onto whatever tenant reuses the
        slot next. Queued requests against the id fail at admission through
        their handle."""
        with self._lock:
            idx = self._adapter_slots.pop(adapter_id, None)
            if idx is None:
                return False
            self._banks = {t: (A.at[:, idx].set(0.0), B.at[:, idx].set(0.0))
                           for t, (A, B) in self._banks.items()}
            self._aidx[self._aidx == idx] = 0
            self._free_bank.append(idx)
        return True

    def _resolve_adapter(self, adapter_id: Optional[int]):
        """(per-layer-stacked adapter dict for prefill, bank index) — under
        the lock so a concurrent unregister can't hand back a half-freed
        slot."""
        if adapter_id is None:
            return None, 0
        with self._lock:
            if adapter_id not in self._adapter_slots:
                raise KeyError(f"unknown adapter_id {adapter_id}")
            idx = self._adapter_slots[adapter_id]
            banks = self._banks
        return {t: (A[:, idx], B[:, idx])
                for t, (A, B) in banks.items()}, idx

    # -- submission ---------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 64,
               temperature: Optional[float] = None,
               prefix_id: Optional[int] = None,
               adapter_id: Optional[int] = None,
               top_p: Optional[float] = None,
               frequency_penalty: float = 0.0,
               presence_penalty: float = 0.0,
               stop: Optional[Sequence] = None,
               logit_bias: Optional[Dict[int, float]] = None,
               seed: Optional[int] = None) -> RequestHandle:
        """Queue one request. ``temperature`` overrides the engine default
        for THIS request only (0 = greedy) — per-slot temperatures share the
        same compiled step. ``prefix_id`` (from :meth:`register_prefix`)
        reuses a cached shared prefix's K/V: only the suffix is prefilled,
        and generation continues as if prefix+prompt had been submitted.
        ``adapter_id`` (from :meth:`register_adapter`) runs THIS request
        through its LoRA adapter — prefill and every decode step — while
        neighboring slots run theirs (or the base model). ``top_p``
        overrides the engine default for THIS request (nucleus sampling;
        applies only when its temperature is > 0 — greedy slots ignore
        it). ``stop`` is one token-id sequence or a list of them: the
        request retires as soon as its generated tokens end with any stop
        sequence (the matching tokens ARE emitted, mirroring eos_id).

        With ``auto_prefix=True`` (engine ctor) and no explicit
        ``prefix_id``, the longest registered prefix the prompt starts
        with (same adapter) is reused automatically — pass the FULL
        prompt; the engine strips the cached part itself."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "always samples the first token)")
        full_prompt = None
        if prefix_id is None and self.auto_prefix:
            prefix_id, stripped = self._match_prefix(prompt, adapter_id,
                                                     int(max_new_tokens))
            if prefix_id is not None:
                full_prompt, prompt = prompt, stripped
        prefix_bucket = 0
        if prefix_id is not None:
            # fetch ONCE: a concurrent unregister between an existence
            # check and a later read must not blow up mid-validation
            pref = self._prefixes.get(prefix_id)
            if pref is None:
                if full_prompt is not None:
                    # the engine matched this prefix itself (auto_prefix)
                    # and lost the race with an eviction — the caller never
                    # asked for it, so serve the full prompt instead
                    prompt, full_prompt, prefix_id = full_prompt, None, None
                else:
                    raise KeyError(f"unknown prefix_id {prefix_id}")
            else:
                # validate against the BUCKETED length: the spliced rows
                # span the bucket, so that is what must fit under max_len
                prefix_bucket = pref[0].shape[2]
        if prefix_bucket + len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix bucket ({prefix_bucket}) + prompt ({len(prompt)}) "
                f"+ max_new_tokens ({max_new_tokens}) exceeds the engine's "
                f"max_len ({self.max_len})")
        if adapter_id is not None and adapter_id not in self._adapter_slots:
            raise KeyError(f"unknown adapter_id {adapter_id}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if logit_bias:
            import math
            logit_bias = {int(t): float(b) for t, b in logit_bias.items()}
            bad = [t for t in logit_bias
                   if not 0 <= t < self.cfg.vocab_size]
            if bad:
                raise ValueError(f"logit_bias token ids out of vocab "
                                 f"range [0, {self.cfg.vocab_size}): {bad}")
            nonfin = [t for t, b in logit_bias.items()
                      if not math.isfinite(b)]
            if nonfin:
                # a single NaN/inf bias poisons the whole logits row
                raise ValueError(
                    f"logit_bias values must be finite; got "
                    f"{ {t: logit_bias[t] for t in nonfin} }")
        req = _Request(next(self._rid), prompt, int(max_new_tokens),
                       temperature=temperature, prefix_id=prefix_id,
                       adapter_id=adapter_id, top_p=top_p,
                       frequency_penalty=float(frequency_penalty),
                       presence_penalty=float(presence_penalty),
                       stop=_normalize_stop(stop), full_prompt=full_prompt,
                       logit_bias=logit_bias or None,
                       seed=None if seed is None else int(seed))
        with self._lock:
            self._pending.append(req)
        self._work.set()
        return RequestHandle(req, engine=self)

    def register_prefix(self, tokens: Sequence[int],
                        adapter_id: Optional[int] = None) -> int:
        """Prefill a shared prefix (system prompt, few-shot header) ONCE and
        cache its K/V; subsequent :meth:`submit` calls with the returned id
        skip recomputing it. Exact for dense models; for MoE, expert
        capacity is per segment (see ``_prefill_suffix``). ``adapter_id``
        computes the prefix K/V through that adapter — pair it with
        requests running the SAME adapter, or the cached rows won't match
        what a solo run would have produced."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("empty prefix")
        if len(tokens) >= self.max_len:
            raise ValueError(f"prefix ({len(tokens)}) must leave room under "
                             f"max_len ({self.max_len})")
        with self._mesh_scope():
            return self._register_prefix(tokens, adapter_id)

    def _register_prefix(self, tokens, adapter_id) -> int:
        t = len(tokens)
        adapter, _ = self._resolve_adapter(adapter_id)
        lkw = ({"adapter": adapter, "lora_scale": self._lora_cfg.scale}
               if adapter is not None else {})
        bucket = next(b for b in self._buckets if b >= t)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :t] = tokens
        _, k_new, v_new, _lp = _prefill(
            self.params, jnp.asarray(padded), jnp.int32(t), self._next_key(),
            jnp.zeros((1,), jnp.float32), self.cfg, top_k=self.top_k, **lkw)
        # Keep BUCKETED K/V: _prefill_suffix takes the true length as a
        # traced scalar, so one compile covers every prefix sharing the
        # bucket (padding rows are overwritten by the suffix / masked).
        # The STORAGE bucket must leave room for at least a 1-token suffix
        # + 1 generated token under max_len — when the run bucket doesn't
        # (e.g. the smallest bucket is most of max_len), trim to the exact
        # length instead (a compile per distinct prefix length only in
        # that degenerate config).
        store = next((b for b in self._buckets
                      if b >= t and b + 2 <= self.max_len), t)
        if store != bucket:
            k_new = k_new[:, :, :store]
            v_new = v_new[:, :, :store]
        pid = next(self._prefix_ids)
        self._prefixes[pid] = (k_new, v_new, t, tuple(tokens), adapter_id)
        return pid

    def _match_prefix(self, prompt: List[int], adapter_id: Optional[int],
                      max_new_tokens: int):
        """Longest registered prefix this prompt starts with (auto_prefix):
        returns (prefix_id, suffix) or (None, prompt). Candidates must have
        been computed through the SAME adapter (a prefix cached through
        adapter A holds A's K/V — serving it to base traffic would splice
        the wrong activations), leave a non-empty suffix, and fit the
        bucket + suffix + budget under max_len."""
        with self._lock:
            items = list(self._prefixes.items())
        best = None
        for pid, (pk, _v, _t, toks, pad) in items:
            n = len(toks)
            if (pad == adapter_id and n < len(prompt)
                    and (best is None or n > best[1])
                    and pk.shape[2] + (len(prompt) - n)
                    + max_new_tokens <= self.max_len
                    and list(toks) == prompt[:n]):
                best = (pid, n)
        if best is None:
            return None, prompt
        return best[0], prompt[best[1]:]

    def unregister_prefix(self, prefix_id: int) -> bool:
        """Free a cached prefix's K/V buffers. The caller owns prefix
        lifetime — the engine never evicts on its own, and each live prefix
        pins ~2·L·P·NKV·Hd device bytes. Requests already queued against
        the id fail with a KeyError surfaced through their handle."""
        return self._prefixes.pop(prefix_id, None) is not None

    def cancel(self, request_id: int) -> bool:
        """Abandon a request: a queued one never admits, an ACTIVE one
        frees its slot at the next step boundary (the in-flight decode
        step finishes — shapes are static, there is nothing to interrupt
        mid-jit). A request caught MID-ADMISSION (popped from the queue,
        prefill in flight) is flagged and reaped right after its
        admission completes. The handle's stream ends cleanly with
        whatever tokens already decoded. False if the id is unknown,
        already finished, or already cancelled — the second of two racing
        cancels always reads False, whatever state the request is in."""
        with self._lock:
            for i, req in enumerate(self._pending):
                if req.rid == request_id:
                    del self._pending[i]
                    req.out.put(None)
                    return True
        # active slots are only mutated on the step path; flag the request
        # and let the next step boundary retire it
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.rid == request_id:
                if req.cancelled:
                    return False
                req.cancelled = True
                self._work.set()
                return True
        # the admission window: _admit popped it, _admit_one's prefill is
        # running — without this check a disconnect during a seconds-long
        # first compile would be silently lost and the request would decode
        # its full budget anyway
        adm = self._admitting
        if adm is not None and adm.rid == request_id and not adm.cancelled:
            adm.cancelled = True
            self._work.set()
            return True
        # mid-chunked-admission: the next _chunk_step abandons it
        ck = self._chunking
        if (ck is not None and ck[0].rid == request_id
                and not ck[0].cancelled):
            ck[0].cancelled = True
            self._work.set()
            return True
        return False

    def _retire_slot(self, slot: int) -> None:
        """THE slot-retirement path (natural finish, eos, cancel): end the
        handle's stream, free the grid slot, clear every ledger — one
        definition so a new piece of per-slot state can't be cleared on
        one path and leak on another. Step-thread only."""
        req = self._slot_req[slot]
        if req is None:
            return
        if self.feedback_sink is not None:
            # snapshot BEFORE state clears: after this method the slot's
            # ledgers are gone and the request object is unreachable
            try:
                self.feedback_sink({
                    "request_id": req.rid,
                    "prompt": list(req.full_prompt or req.prompt),
                    "generated": int(req.generated),
                    "cancelled": bool(req.cancelled),
                    "ttft_s": (req.first_token_at - req.submitted_at
                               if req.first_token_at is not None else None),
                    "latency_s": time.monotonic() - req.submitted_at,
                })
            except Exception:  # noqa: BLE001 — never wedge the step thread
                pass
        req.out.put(None)
        self._slot_req[slot] = None
        self._pos[slot] = 0
        self._tok[slot] = 0
        self._temps[slot] = 0.0
        self._top_ps[slot] = 1.0
        self._bmask[slot] = 0.0
        self._fpen[slot] = 0.0
        self._ppen[slot] = 0.0
        self._aidx[slot] = 0
        self._finished += 1
        self._free_slot_ledgers(slot)

    def _reap_cancelled(self) -> None:
        """Step-boundary retirement for cancelled active slots (the only
        thread that mutates slot state is the stepping thread)."""
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.cancelled:
                self._retire_slot(slot)

    def _free_slot_ledgers(self, slot: int) -> None:
        """Subclass hook: extra per-slot state to clear on retirement."""

    # -- batch-boundary scheduling ------------------------------------------

    def at_batch_boundary(self, fn, timeout: Optional[float] = None):
        """Run ``fn()`` between decode batches, on the stepping thread.

        THE safe point for anything that mutates engine-wide device state
        — above all the live weight hot swap (``serve/rollout.py``, the
        only sanctioned ``engine.params`` writer after construction): no
        decode dispatch is in flight when the hook runs, so donated
        buffers can be freed and replaced without racing a jit. Blocks the
        CALLER until the hook has run (the decode loop itself never
        blocks on anything but the device); with no loop thread running,
        runs inline under the engine's mesh scope — the caller is the
        de-facto stepping thread. Exceptions propagate to the caller,
        never into the decode loop. Returns ``fn()``'s result."""
        with self._lifecycle:
            thread = self._thread
        running = thread is not None and thread.is_alive()
        if not running or threading.current_thread() is thread:
            with self._mesh_scope():
                return fn()
        box: Dict[str, Any] = {"done": threading.Event()}
        self._boundary_hooks.append((fn, box))
        self._work.set()
        if not box["done"].wait(timeout):
            raise TimeoutError(
                "engine did not reach a batch boundary in time")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _run_boundary_hooks(self) -> None:
        """Drain queued boundary hooks (stepping thread, between batches)."""
        while self._boundary_hooks:
            fn, box = self._boundary_hooks.popleft()
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — hand to the waiter
                box["error"] = e
            finally:
                box["done"].set()

    # -- engine loop --------------------------------------------------------

    def _mesh_scope(self):
        """use_mesh(self._mesh) on the CURRENT thread (no-op off-mesh)."""
        import contextlib
        if self._mesh is None:
            return contextlib.nullcontext()
        from ..parallel.mesh_context import use_mesh
        return use_mesh(self._mesh)

    def _next_key(self) -> jax.Array:
        # under _lock: register_prefix runs on caller threads while the
        # loop thread decodes — an unsynchronized split can hand two
        # consumers the same key (correlated samples)
        with self._lock:
            self._rng, sub = jax.random.split(self._rng)
        return sub

    def _free_slots(self) -> List[int]:
        busy = self._chunking[1] if self._chunking is not None else None
        return [i for i, r in enumerate(self._slot_req)
                if r is None and i != busy]

    def _admit(self) -> None:
        if self._chunking is not None:
            # one chunk of the in-progress long admission per engine step
            # (decode blocks run in between — that's the point)
            self._chunk_step()
        free = self._free_slots()
        while free:
            with self._lock:
                if not self._pending:
                    return
                req = self._pending.popleft()
            slot = free.pop(0)
            if (self.prefill_chunk is not None and self._chunking is not None
                    and len(req.prompt) > self.prefill_chunk):
                # a second long prompt while the chunker is busy: requeue
                # and stop admitting this step (FIFO preserved) rather
                # than falling back to a one-shot prefill at the max_len
                # bucket — a giant compile + the exact stall chunking
                # exists to avoid. The chunker frees within a few steps.
                with self._lock:
                    self._pending.appendleft(req)
                return
            if (self.prefill_chunk is not None and self._chunking is None
                    and len(req.prompt) > self.prefill_chunk):
                # long prompt with the chunker free: reserve the slot and
                # prefill one chunk per step (a long prompt arriving while
                # the chunker is BUSY requeued above and waits for it).
                # _admitting makes the request cancellable during the
                # first chunk's (possibly compile-long) prefill; once
                # _chunking is set, cancel() finds it there instead.
                self._admitting = req
                try:
                    self._start_chunking(req, slot)
                except Exception as e:   # noqa: BLE001
                    req.error = e
                    req.out.put(None)
                    free.insert(0, slot)
                finally:
                    self._admitting = None
                continue
            # visible to cancel() during the (possibly seconds-long)
            # prefill below; the flag it may set is honored by the reap at
            # the next step boundary once the slot is assigned
            self._admitting = req
            try:
                self._admit_one(req, slot)
            except Exception as e:   # noqa: BLE001 — per-request failure
                # (unregistered prefix, bad state) fails THAT request via
                # its handle; the loop thread must survive
                req.error = e
                req.out.put(None)
                free.insert(0, slot)
            finally:
                self._admitting = None

    # -- chunked prefill ----------------------------------------------------

    def _start_chunking(self, req: _Request, slot: int) -> None:
        """First chunk of a long admission: seed the FIXED-capacity
        accumulator (max_len rows — one compiled chunk-step shape for the
        engine's lifetime, and the final splice is exactly cache-width)
        from the request's cached prefix when it has one, else from a
        plain prefill of the first chunk. Costs one extra slot's worth of
        K/V while a chunked admission is in flight."""
        pref = self._resolve_prefix(req)
        adapter, aidx = self._resolve_adapter(req.adapter_id)
        lkw = ({"adapter": adapter, "lora_scale": self._lora_cfg.scale}
               if adapter is not None else {})
        c = self.prefill_chunk
        if req.prefix_id is not None:
            # the registered prefix IS the seed; chunks run behind it
            rows_k, rows_v, p_real = pref[0], pref[1], pref[2]
            self._prefix_hits += 1
            consumed, frontier = 0, int(p_real)
        else:
            toks = req.prompt[:c]                  # len(prompt) > c
            padded = np.zeros((1, c), np.int32)
            padded[0, :] = toks
            # greedy dummy key: intermediate chunks never sample, and
            # drawing real keys here would shift the engine's key stream
            # vs one-shot admission (breaking sampled-mode equivalence)
            _f, rows_k, rows_v, _lp = _prefill(
                self.params, jnp.asarray(padded), jnp.int32(c),
                self._dummy_key, jnp.zeros((1,), jnp.float32), self.cfg,
                top_k=self.top_k, **lkw)
            consumed = frontier = c
        pad_w = self.max_len - rows_k.shape[2]
        widen = [(0, 0)] * rows_k.ndim
        widen[2] = (0, pad_w)
        k_acc = jnp.pad(rows_k, widen)
        v_acc = jnp.pad(rows_v, widen)
        self._chunking = (req, slot, k_acc, v_acc, consumed, frontier,
                          lkw, aidx, pref[3] if pref is not None else None)

    def _chunk_step(self) -> None:
        """Advance the in-progress chunked admission by one chunk; the
        LAST chunk samples the first token and seats the request. The
        accumulator stays max_len-wide: ``_prefill_suffix`` returns
        max_len + C rows (scattered at absolute positions < max_len), and
        the trailing pad is sliced back off."""
        (req, slot, k_acc, v_acc, consumed, frontier,
         lkw, aidx, pref_toks) = self._chunking
        if req.cancelled:
            self._chunking = None
            req.out.put(None)
            return
        c = self.prefill_chunk
        rest = len(req.prompt) - consumed
        take = min(c, rest)
        toks = req.prompt[consumed:consumed + take]
        padded = np.zeros((1, c), np.int32)
        padded[0, :take] = toks
        last = take == rest
        try:
            if not last:
                _f, k_acc, v_acc, _lp = _prefill_suffix(
                    self.params, jnp.asarray(padded), jnp.int32(take),
                    k_acc, v_acc, jnp.int32(frontier), self._dummy_key,
                    jnp.zeros((1,), jnp.float32), self.cfg,
                    top_k=self.top_k, **lkw)
                self._chunking = (req, slot, k_acc[:, :, :self.max_len],
                                  v_acc[:, :, :self.max_len],
                                  consumed + take, frontier + take,
                                  lkw, aidx, pref_toks)
                return
            temp, temps, tp, pkw, row, bias_vec = self._sampling_setup(
                req, pref_toks)
            first, k_new, v_new, flp = _prefill_suffix(
                self.params, jnp.asarray(padded), jnp.int32(take),
                k_acc, v_acc, jnp.int32(frontier),
                self._request_prefill_key(req, frontier + take),
                temps, self.cfg, top_k=self.top_k, **lkw, **pkw)
            self._chunking = None
            self._finish_admission(req, slot, first, flp,
                                   k_new[:, :, :self.max_len],
                                   v_new[:, :, :self.max_len],
                                   frontier + take, temp, tp, row, aidx,
                                   bias_vec=bias_vec)
        except Exception as e:   # noqa: BLE001 — fail THIS request only
            self._chunking = None
            req.error = e
            req.out.put(None)

    def _resolve_prefix(self, req: _Request):
        """Fetch the request's prefix tuple ONCE (every later use reads
        the returned local, so an unregister racing admission can't fail
        a request that passed the check here). An evicted AUTO-matched
        prefix falls back to the full prompt; an evicted explicit one is
        the caller's error."""
        pref = (self._prefixes.get(req.prefix_id)
                if req.prefix_id is not None else None)
        if req.prefix_id is not None and pref is None:
            if req.full_prompt is not None:
                req.prompt, req.full_prompt = req.full_prompt, None
                req.prefix_id = None
            else:
                raise KeyError(f"unknown prefix_id {req.prefix_id}")
        return pref

    def _sampling_setup(self, req: _Request, pref_toks):
        """Per-request sampling state for the admission prefill
        (``pref_toks``: the request's cached-prefix token tuple, or None).
        Returns (temp, temps (1,), tp, pkw jit-kwargs, row counts-seed,
        bias_vec (V,) float32 or None)."""
        temp = (self.temperature if req.temperature is None
                else float(req.temperature))
        temps = jnp.full((1,), temp, jnp.float32)
        tp = (self.top_p if req.top_p is None else float(req.top_p))
        tp = 1.0 if tp is None else tp
        if tp < 1.0:
            self._nucleus = True
        pkw = {"top_ps": jnp.full((1,), tp, jnp.float32)} \
            if self._nucleus else {}
        fp, pp = req.frequency_penalty, req.presence_penalty
        if (fp or pp) and self._counts is None:
            self._counts = jnp.zeros((self.slots, self.cfg.vocab_size),
                                     jnp.int32)
        row = None
        if fp or pp:
            # only penalized requests pay the V-sized row (zero-penalty
            # neighbors neutralize any stale row by multiplying it by 0,
            # so they need no seeding at all)
            seen = list(req.prompt)
            if pref_toks is not None:
                seen += list(pref_toks)
            row = np.zeros(self.cfg.vocab_size, np.int32)
            np.add.at(row, np.asarray(seen, np.int64), 1)
            # penalties apply to the FIRST sampled token too (the prompt
            # is "text so far" — OpenAI semantics)
            pkw["pen_row"] = jnp.asarray(
                fp * row.astype(np.float32)
                + pp * (row > 0).astype(np.float32))
        bias_vec = None
        if req.logit_bias:
            bias_vec = np.zeros(self.cfg.vocab_size, np.float32)
            for tid, b in req.logit_bias.items():
                bias_vec[tid] = b
            # pen_row is SUBTRACTED from the prefill logits, so the bias
            # folds in negated — the first sampled token is biased too
            prev = pkw.get("pen_row")
            pkw["pen_row"] = ((0.0 if prev is None else prev)
                              - jnp.asarray(bias_vec))
        return temp, temps, tp, pkw, row, bias_vec

    def _request_prefill_key(self, req: _Request, start: int):
        """Sampling key for the admission prefill (the FIRST token, placed
        at position ``start``): seeded requests fold their own base key by
        ``start - 1`` — disjoint from the decode folds at start, start+1,
        … — and draw nothing from the engine chain."""
        if req.seed is None:
            return self._next_key()
        return jax.random.fold_in(jax.random.PRNGKey(req.seed), start - 1)

    def _finish_admission(self, req: _Request, slot: int, first, flp,
                          k_new, v_new, start: int, temp: float, tp: float,
                          row, aidx: int, bias_vec=None) -> None:
        """Post-prefill slot bookkeeping shared by one-shot and chunked
        admission: splice the K/V rows, seat the request, seed ledgers,
        re-check the adapter mapping, emit the first sampled token."""
        self._cache = _splice_slot(self._cache, jnp.int32(slot),
                                   k_new, v_new)
        first_tok = int(first[0])
        self._slot_req[slot] = req
        self._skeys[slot] = np.asarray(
            jax.random.PRNGKey(req.seed) if req.seed is not None
            else self._next_key(), np.uint32)
        self._pos[slot] = start
        self._tok[slot] = first_tok
        self._temps[slot] = temp
        self._top_ps[slot] = tp
        self._fpen[slot] = req.frequency_penalty
        self._ppen[slot] = req.presence_penalty
        if row is not None:
            row[first_tok] += 1
            self._counts = _set_counts_row(self._counts, jnp.int32(slot),
                                           jnp.asarray(row))
        if bias_vec is not None:
            if self._bias is None:
                self._bias = jnp.zeros((self.slots, self.cfg.vocab_size),
                                       jnp.float32)
            self._bias = _set_counts_row(self._bias, jnp.int32(slot),
                                         jnp.asarray(bias_vec))
            self._bmask[slot] = 1.0
        with self._lock:
            # prefill ran outside the lock: if the adapter was evicted in
            # that window (and its index possibly reused by a new tenant),
            # pointing at the stale index would decode through the WRONG
            # factors — re-check the mapping and fall back to base
            if (req.adapter_id is not None
                    and self._adapter_slots.get(req.adapter_id) != aidx):
                aidx = 0
            self._aidx[slot] = aidx
        self._admitted += 1
        self._emit(slot, first_tok, float(flp[0]))
        # TTFT sample at the only place it's defined: the first emit
        if req.first_token_at is not None:
            self._ttfts.append(req.first_token_at - req.submitted_at)

    def _admit_one(self, req: _Request, slot: int) -> None:
        pref = self._resolve_prefix(req)
        t = len(req.prompt)
        temp, temps, tp, pkw, row, bias_vec = self._sampling_setup(
            req, pref[3] if pref is not None else None)
        adapter, aidx = self._resolve_adapter(req.adapter_id)
        lkw = ({"adapter": adapter, "lora_scale": self._lora_cfg.scale}
               if adapter is not None else {})
        if req.prefix_id is not None:
            pk, pv, p_real, p_toks, _pad = pref
            p_bucket = pk.shape[2]
            bucket = next((b for b in self._buckets if b >= t
                           and p_bucket + b <= self.max_len), None)
            if bucket is None:
                # no bucket leaves room behind the prefix: pad the
                # suffix to exactly what fits (still one compile per
                # distinct size, bounded by max_len)
                bucket = self.max_len - p_bucket
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :t] = req.prompt
            start = p_real + t
            first, k_new, v_new, flp = _prefill_suffix(
                self.params, jnp.asarray(padded), jnp.int32(t), pk, pv,
                jnp.int32(p_real), self._request_prefill_key(req, start),
                temps, self.cfg, top_k=self.top_k, **lkw, **pkw)
            self._prefix_hits += 1
        else:
            bucket = next(b for b in self._buckets if b >= t)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :t] = req.prompt
            start = t
            # common signature (no adapter/nucleus/penalty kwargs): use
            # the pre-loaded AOT executable when the cache warmed one —
            # statics (cfg, top_k) are baked in, so only dynamic args pass
            exe = (self._aot_exec.get(("prefill", bucket))
                   if not lkw and not pkw else None)
            if exe is not None:
                first, k_new, v_new, flp = exe(
                    self.params, jnp.asarray(padded), jnp.int32(t),
                    self._request_prefill_key(req, start), temps)
            else:
                first, k_new, v_new, flp = _prefill(
                    self.params, jnp.asarray(padded), jnp.int32(t),
                    self._request_prefill_key(req, start), temps, self.cfg,
                    top_k=self.top_k, **lkw, **pkw)
        self._finish_admission(req, slot, first, flp, k_new, v_new, start,
                               temp, tp, row, aidx, bias_vec=bias_vec)

    def _emit(self, slot: int, tok: int,
              logprob: Optional[float] = None) -> None:
        req = self._slot_req[slot]
        if req is None:
            return
        if req.first_token_at is None:
            req.first_token_at = time.monotonic()
        # appended before the queue put: a consumer that has seen token i
        # can always read logprob i (None for paths that don't compute it,
        # e.g. speculative verify)
        req.logprobs.append(logprob)
        req.out.put(tok)
        req.generated += 1
        self._tokens += 1
        done = (req.generated >= req.max_new_tokens
                or (self.eos_id is not None and tok == self.eos_id))
        if req.stop and not done:
            req.tail.append(tok)
            maxlen = max(len(q) for q in req.stop)
            del req.tail[:-maxlen]
            done = any(len(q) <= len(req.tail)
                       and req.tail[len(req.tail) - len(q):] == list(q)
                       for q in req.stop)
        if done:
            self._retire_slot(slot)

    def step(self) -> int:
        """Admit pending requests, then decode one BLOCK of tokens
        (``decode_block`` device steps, default 1) for every active slot.
        Returns the remaining work — active slots plus queued requests — so
        ``while eng.step(): ...`` runs the backlog dry even when a step
        retires every active slot with the queue non-empty."""
        with self._mesh_scope():
            return self._step_once()

    def _step_once(self) -> int:
        # boundary hooks first: we are BETWEEN decode batches here (the
        # previous dispatch retired at the end of the last _step_once), so
        # a weight swap scheduled via at_batch_boundary never overlaps a
        # decode dispatch on the old params
        self._run_boundary_hooks()
        self._reap_cancelled()
        self._admit()
        active = [i for i, r in enumerate(self._slot_req) if r is not None]
        if active:
            with self._lock:
                banks = self._banks
            # once a bank exists every step pays the per-slot gather, base
            # traffic included (aidx 0 = the zero adapter) — the price of
            # one shared compiled step
            lkw = ({"banks": banks, "aidx": jnp.asarray(self._aidx),
                    "lora_scale": self._lora_cfg.scale} if banks else {})
            if self._nucleus:
                lkw["top_ps"] = jnp.asarray(self._top_ps)
            if self._counts is not None:
                lkw.update(counts=self._counts,
                           fpen=jnp.asarray(self._fpen),
                           ppen=jnp.asarray(self._ppen))
            if self._bias is not None:
                lkw.update(bias=self._bias,
                           bmask=jnp.asarray(self._bmask))
            lkw["skeys"] = jnp.asarray(self._skeys)
            # always the FULL configured block — never a tail-sized one:
            # n_steps is a static argname, so a variable tail would compile
            # a fresh variant mid-serving (a multi-second stall for every
            # concurrent stream) to save at most K-1 ~ms-scale garbage
            # steps on the final dispatch of a draining backlog
            k = self.decode_block
            # common decode signature (lkw is exactly {skeys}: no banks,
            # nucleus, penalties, or bias): the warm AOT executable takes
            # the dispatch; sticky features fall back to the traced jits
            aot = (self._aot_exec.get(("decode", k))
                   if set(lkw) == {"skeys"} else None)
            if k > 1:
                if aot is not None:
                    (self._cache, _fp, _ft, toks_k, lps_k,
                     counts) = aot(
                        self.params, self._cache, jnp.asarray(self._pos),
                        jnp.asarray(self._tok), self._next_key(),
                        jnp.asarray(self._temps), skeys=lkw["skeys"])
                else:
                    (self._cache, _fp, _ft, toks_k, lps_k,
                     counts) = _decode_block(
                        self.params, self._cache, jnp.asarray(self._pos),
                        jnp.asarray(self._tok), self._next_key(),
                        jnp.asarray(self._temps), self.cfg, n_steps=k,
                        top_k=self.top_k, **lkw)
                if self._counts is not None:
                    self._counts = counts
            else:
                if aot is not None:
                    out = aot(
                        self.params, self._cache, jnp.asarray(self._pos),
                        jnp.asarray(self._tok), self._next_key(),
                        jnp.asarray(self._temps), skeys=lkw["skeys"])
                else:
                    out = _decode_step(
                        self.params, self._cache, jnp.asarray(self._pos),
                        jnp.asarray(self._tok), self._next_key(),
                        jnp.asarray(self._temps), self.cfg, top_k=self.top_k,
                        **lkw)
                if self._counts is not None:
                    self._cache, nxt, lps, self._counts = out
                else:
                    self._cache, nxt, lps = out
                toks_k, lps_k = nxt[None], lps[None]    # (1, B)
            toks_k, lps_k = np.asarray(toks_k), np.asarray(lps_k)
            self._steps += k
            for i in range(k):
                for slot in active:
                    # a slot retired at emit i' < i skips the rest of its
                    # block (garbage past the stop point). Each emitted
                    # token consumed position _pos[slot]; the next feeds
                    # back one position later.
                    if self._slot_req[slot] is None:
                        continue
                    self._pos[slot] += 1
                    self._tok[slot] = int(toks_k[i, slot])
                    self._emit(slot, int(toks_k[i, slot]),
                               float(lps_k[i, slot]))
        with self._lock:
            queued = len(self._pending)
        return (sum(r is not None for r in self._slot_req) + queued
                + (1 if self._chunking is not None else 0))

    def _run(self) -> None:
        while not self._stop.is_set():
            n = self.step()
            if n == 0 and not self._pending:
                self._work.clear()
                self._work.wait(timeout=0.5)

    def start(self) -> "GenerationEngine":
        with self._lifecycle:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(target=self._run, daemon=True,
                                                name="kt-gen-engine")
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._lifecycle:
            self._stop.set()
            self._work.set()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
        with self._lifecycle:
            # only forget a thread that actually exited: clearing a live
            # straggler would let the next start() run a second loop beside
            # it on the same donated cache
            if self._thread is thread and (thread is None
                                           or not thread.is_alive()):
                self._thread = None
        # hooks enqueued in the stop race would otherwise strand their
        # waiters: with the loop gone, this thread is the stepping thread
        with self._mesh_scope():
            self._run_boundary_hooks()

    # -- introspection ------------------------------------------------------

    def aot_stats(self) -> Dict[str, int]:
        """AOT compile-cache lookup counts for THIS engine's warm-up
        (``hit``/``miss``/``incompatible``/``corrupt``/``publish``…, the
        local mirror of ``kt_aot_cache_total``), plus the number of
        executables the dispatch sites can consult. Empty counts when the
        engine was built without a cache."""
        out = dict(self._aot_cache.counts) if self._aot_cache else {}
        out["executables"] = len(self._aot_exec)
        return out

    def stats(self) -> EngineStats:
        dt = max(time.monotonic() - self._t0, 1e-9)
        return EngineStats(
            slots=self.slots,
            active=sum(r is not None for r in self._slot_req),
            # a request mid-chunked-admission is neither seated nor in
            # _pending; count it as queued so load gauges never read an
            # idle engine while it prefills
            queued=len(self._pending)
            + (1 if self._chunking is not None else 0),
            admitted_total=self._admitted,
            finished_total=self._finished,
            tokens_generated=self._tokens,
            decode_steps=self._steps,
            tokens_per_sec=self._tokens / dt,
            ttft_avg=(sum(self._ttfts) / len(self._ttfts)
                      if self._ttfts else 0.0))

    def __kt_metrics__(self) -> Dict[str, float]:
        """Pod-scrape hook (``serving.process_worker`` — the
        ``__kt_warmup__`` sibling): a deployed engine's live gauges land
        on the pod's ``/metrics`` under ``kt_user_`` with no exporter
        code. Cheap (host counters only); runs per 3s scrape."""
        s = self.stats()
        out = {"engine_slots": float(s.slots),
               "engine_active": float(s.active),
               # the router packs against free slots: exported so `kt
               # serve status` and the bench can see per-replica headroom
               "engine_slots_free": float(s.slots - s.active),
               "engine_queued": float(s.queued),
               "engine_admitted_total": float(s.admitted_total),
               "engine_finished_total": float(s.finished_total),
               "engine_tokens_generated": float(s.tokens_generated),
               "engine_decode_steps": float(s.decode_steps),
               "engine_tokens_per_sec": float(s.tokens_per_sec),
               "engine_ttft_avg_seconds": float(s.ttft_avg),
               "engine_prefix_hits": float(self._prefix_hits)}
        spec = getattr(self, "spec_stats", None)
        if spec is not None:
            out["engine_spec_rounds"] = float(spec.rounds)
            out["engine_spec_acceptance_rate"] = float(spec.acceptance_rate)
            # adaptive draft length (ISSUE 12): the k the EWMA controller
            # currently bets per round
            out["engine_spec_draft_len"] = float(getattr(self, "k", 0))
        return out

    # remote-service surface: a deployed engine (kt.cls) exposes a blocking
    # generate() so callers don't need the handle/iterator machinery
    def generate(self, prompt: Sequence[int], max_new_tokens: int = 64,
                 timeout: Optional[float] = 300.0, *,
                 temperature: Optional[float] = None,
                 prefix_id: Optional[int] = None,
                 adapter_id: Optional[int] = None,
                 top_p: Optional[float] = None,
                 frequency_penalty: float = 0.0,
                 presence_penalty: float = 0.0,
                 stop: Optional[Sequence] = None,
                 logit_bias: Optional[Dict[int, float]] = None,
                 seed: Optional[int] = None) -> List[int]:
        # timeout keeps its historical positional slot; the newer knobs are
        # keyword-only so generate(tokens, 64, 30.0) still means timeout=30
        self.start()
        return self.submit(prompt, max_new_tokens, temperature=temperature,
                           prefix_id=prefix_id, adapter_id=adapter_id,
                           top_p=top_p, frequency_penalty=frequency_penalty,
                           presence_penalty=presence_penalty,
                           stop=stop, logit_bias=logit_bias, seed=seed
                           ).result(timeout=timeout)
