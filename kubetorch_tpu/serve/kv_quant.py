"""int8 KV cache for the serving engine.

Decode reads the ENTIRE cache every step — at serving lengths the K/V
stream is the HBM bill of the latency-critical op, twice the size of the
weights stream once contexts are long. Quantizing cache rows to int8 with
one fp32 scale per written row halves that stream (and the grid's HBM
footprint): Hd=128 bf16 rows go 256B → 132B per head.

Scheme — symmetric per-row-per-head absmax: a row ``x`` (one token's
(NKV, Hd) K or V values) stores ``round(x / s)`` int8 with
``s = max|x| / 127`` kept per (slot, pos, head). Dequantization folds into
the attention math WITHOUT materializing fp rows or transposing scales:

    logits_j = (q · k_j) * scale * ks_j        # ks scales logits COLUMNS
    out      = Σ_j (p_j * vs_j) · v_j          # vs folds into the probs

so the Pallas kernel streams int8 tiles plus one (1, block_k) scale row
per tile, and the einsum fallback is the same math in fp32 — the two are
asserted bit-compatible (tests/test_kv_quant.py).

Accuracy: absmax-int8 keeps per-row relative error ≤ 1/254 of the row's
peak; serving quality loss is negligible next to bf16 attention itself.
Opt in per engine: ``GenerationEngine(params, cfg, quantize_kv=True)``.

Reference analog: none (the reference has no serving engine) — part of
the beyond-parity serving stack, like int8 WEIGHT quantization
(``models.quant``), which composes with this (quantized weights +
quantized cache are independent switches).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class QuantKVCache(NamedTuple):
    """Slot-grid cache in int8: values (L, B, S, NKV, Hd) int8, scales
    (L, B, S, NKV) fp32 — one scale per written row per head."""
    kq: jax.Array
    ks: jax.Array
    vq: jax.Array
    vs: jax.Array


def init_quant_cache(cfg, batch: int, max_len: int) -> QuantKVCache:
    vshape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    sshape = vshape[:-1]
    return QuantKVCache(kq=jnp.zeros(vshape, jnp.int8),
                        ks=jnp.zeros(sshape, jnp.float32),
                        vq=jnp.zeros(vshape, jnp.int8),
                        vs=jnp.zeros(sshape, jnp.float32))


def quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(..., Hd) → (int8 (..., Hd), fp32 scale (...,)). All-zero rows
    (unwritten cache, padding) keep scale 0 → dequantize back to exact
    zeros."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = absmax / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_rows(q: jax.Array, scale: jax.Array) -> jax.Array:
    """fp32 rows back; exact inverse of the fold-into-attention math for
    callers that need plain rows (tests, debugging)."""
    return q.astype(jnp.float32) * scale[..., None]
