"""OpenAI-compatible HTTP surface over a ``GenerationEngine``.

The lingua franca of LLM serving: ``/v1/completions``, ``/v1/chat/completions``
(streaming and blocking), and ``/v1/models``, so off-the-shelf clients
(openai-python, LangChain, curl scripts) talk to a kubetorch-tpu engine
unchanged. The reference stack has no serving engine at all — this is the
beyond-parity surface users coming from vLLM/TGI-on-kubetorch expect.

Design:

- **A thin aiohttp app around one engine.** The engine already owns
  batching, sampling, stop handling, and streaming; the handlers only
  translate JSON ↔ ``submit()``. Deployable three ways: mounted on the pod
  server's extra-routes hook, standalone
  (``python -m kubetorch_tpu.serve.openai_api --ckpt DIR``), or under
  ``kt.app`` with that command.
- **Tokenizer optional.** With a HF tokenizer (``AutoTokenizer`` or any
  object with encode/decode), prompts and outputs are text and string
  ``stop`` is honored by incremental decode + cut. Without one, prompts
  must be token-id lists and outputs are ids — the hermetic test mode, and
  the honest mode for callers that tokenize client-side.
- **Streaming via SSE** (``data: {...}\\n\\n`` chunks, ``data: [DONE]``),
  one chunk per decoded token. The engine's handle iterator is blocking, so
  a worker thread pumps tokens into an asyncio queue.

Wire-format compatibility is scoped to the fields the engine supports:
``max_tokens``, ``temperature``, ``top_p``, ``stop``, ``stream``, ``seed``
is ignored (engine RNG is per-process), ``n > 1``/``logprobs``/tool calls
are rejected with an OpenAI-shaped error rather than half-implemented.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional

from aiohttp import web

__all__ = ["OpenAIApp", "build_app"]


_POOLED_HIDDEN_JIT = None


def _pooled_hidden(params, tokens, true_len, cfg):
    """(1, T_bucket) right-padded → (D,) fp32 mean over the real tokens of
    the final-norm hidden states. jit'd ONCE at module level (per bucket ×
    cfg, like prefill) — rebuilding the jit per call would recompile."""
    global _POOLED_HIDDEN_JIT
    if _POOLED_HIDDEN_JIT is None:
        import jax
        from functools import partial as _partial

        @_partial(jax.jit, static_argnames=("cfg",))
        def run(params, tokens, true_len, cfg):
            import jax.numpy as jnp

            from ..models.llama import llama_hidden
            h = llama_hidden(params, tokens, cfg).astype(jnp.float32)
            mask = (jnp.arange(h.shape[1]) < true_len)[None, :, None]
            return (jnp.sum(h * mask, axis=(0, 1))
                    / jnp.maximum(true_len, 1).astype(jnp.float32))

        _POOLED_HIDDEN_JIT = run
    return _POOLED_HIDDEN_JIT(params, tokens, true_len, cfg)


def _error(status: int, message: str, err_type: str = "invalid_request_error"):
    return web.json_response(
        {"error": {"message": message, "type": err_type, "param": None,
                   "code": None}},
        status=status)


class _TextStopCutter:
    """Incremental string-stop matching over a decoded stream: feed text
    pieces, returns (emittable_text, done). Holds back a window of
    ``max_stop - 1`` chars so a stop string split across tokens still
    matches; on match, everything before the stop is emitted and the stop
    itself is dropped (OpenAI semantics — unlike token-id stops, which
    mirror eos and emit)."""

    def __init__(self, stops: List[str]):
        self.stops = [s for s in stops if s]
        self.buf = ""
        self.hold = max((len(s) for s in self.stops), default=1) - 1

    def feed(self, piece: str):
        if not self.stops:
            return piece, False
        self.buf += piece
        cut = min((i for i in (self.buf.find(s) for s in self.stops)
                   if i >= 0), default=-1)
        if cut >= 0:
            out, self.buf = self.buf[:cut], ""
            return out, True
        out = self.buf[:-self.hold] if self.hold else self.buf
        self.buf = self.buf[len(out):]
        return out, False

    def flush(self) -> str:
        out, self.buf = self.buf, ""
        return out


class OpenAIApp:
    """``build()`` → aiohttp Application serving the OpenAI surface over
    ``engine``. ``tokenizer`` is any HF-style object (``encode``/``decode``,
    optionally ``apply_chat_template``); None = token-id mode."""

    def __init__(self, engine, tokenizer=None,
                 model_name: str = "kubetorch-tpu"):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        self._req_ids = iter(range(1, 1 << 62))

    # -- translation helpers ------------------------------------------------

    def _encode_prompt(self, prompt) -> List[int]:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts need a tokenizer; this deployment is "
                    "token-id mode — send a list of token ids")
            return list(self.tokenizer.encode(prompt))
        if isinstance(prompt, list) and all(isinstance(t, int) for t in prompt):
            return prompt
        raise ValueError("prompt must be a string or a list of token ids")

    def _split_stops(self, stop) -> (List[str], List[List[int]]):
        """OpenAI ``stop`` (str or list of str; we also accept token-id
        lists) → (text_stops, token_stops)."""
        if stop is None:
            return [], []
        items = [stop] if isinstance(stop, str) else list(stop)
        if len(items) > 4:
            raise ValueError("at most 4 stop sequences")
        text, toks = [], []
        for s in items:
            if isinstance(s, str):
                text.append(s)
            elif isinstance(s, list) and all(isinstance(t, int) for t in s):
                toks.append(s)
            else:
                raise ValueError("stop entries must be strings or "
                                 "token-id lists")
        if text and self.tokenizer is None:
            raise ValueError("string stop sequences need a tokenizer")
        return text, toks

    def _chat_prompt(self, messages) -> List[int]:
        if not isinstance(messages, list) or not messages:
            raise ValueError("messages must be a non-empty list")
        for m in messages:
            if not isinstance(m, dict) or "role" not in m or "content" not in m:
                raise ValueError("each message needs role and content")
        if self.tokenizer is None:
            raise ValueError("chat completions need a tokenizer")
        apply = getattr(self.tokenizer, "apply_chat_template", None)
        if apply is not None:
            try:
                return list(apply(messages, add_generation_prompt=True,
                                  tokenize=True))
            except Exception:
                pass  # template-less tokenizer: fall through
        text = "".join(f"<|{m['role']}|>{m['content']}\n" for m in messages)
        return list(self.tokenizer.encode(text + "<|assistant|>"))

    def _decode(self, ids: List[int]) -> str:
        return self.tokenizer.decode(ids) if self.tokenizer else ""

    def _submit(self, body: Dict[str, Any], prompt_ids: List[int],
                choice_index: int = 0):
        lp = body.get("logprobs")
        if (isinstance(lp, int) and lp > 1) or body.get("top_logprobs"):
            raise ValueError("only the chosen token's logprob is available "
                             "(logprobs=1/true); top-k logprobs are not "
                             "supported")
        text_stops, tok_stops = self._split_stops(body.get("stop"))
        temperature = float(body.get("temperature", 1.0))
        top_p = body.get("top_p")
        # OpenAI wire shape {"token_id_string": bias_float} passes through
        # raw: engine.submit normalizes and range-validates the dict
        bias = body.get("logit_bias") or None
        handle = self.engine.submit(
            prompt_ids,
            max_new_tokens=int(body.get("max_tokens", 16)),
            temperature=temperature,
            top_p=None if top_p is None else float(top_p),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            stop=tok_stops or None, logit_bias=bias,
            # a seeded stream is a pure function of (seed, prompt), so n>1
            # with one seed would return n identical choices — each index
            # gets its own derived seed, and index 0 reproduces solo calls
            seed=(None if body.get("seed") is None
                  else int(body["seed"]) + choice_index))
        return handle, _TextStopCutter(text_stops), tok_stops

    # -- handlers -----------------------------------------------------------

    def _embed_ids(self, ids: List[int]):
        """Mean-pooled final-norm hidden state for one input (dense models;
        the engine's prefill buckets bound the compile count)."""
        import jax.numpy as jnp
        import numpy as np

        from ..models.llama import llama_hidden

        eng = self.engine
        if "router" in eng.params.get("layers", {}):
            raise ValueError("embeddings are not supported for MoE models")
        from ..models.quant import is_quantized
        if any(is_quantized(v) for v in eng.params["layers"].values()):
            # llama_hidden is the full-precision forward; refuse cleanly
            # instead of crashing inside its jit on a dict leaf
            raise ValueError(
                "embeddings need full-precision params — this engine "
                "serves quantized weights (generation only)")
        if len(ids) > eng.max_len:
            raise ValueError(f"input ({len(ids)} tokens) exceeds max_len "
                             f"({eng.max_len})")
        bucket = next((b for b in eng._buckets if b >= len(ids)),
                      eng.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(ids)] = ids
        with eng._mesh_scope():
            hidden = _pooled_hidden(eng.params, jnp.asarray(padded),
                                    jnp.int32(len(ids)), eng.cfg)
        return np.asarray(hidden).tolist()

    async def embeddings(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return _error(400, "body must be JSON")
        raw = body.get("input")
        if isinstance(raw, str):
            items = [raw]
        elif isinstance(raw, list) and raw \
                and all(isinstance(t, int) for t in raw):
            items = [raw]            # one token-id sequence
        elif isinstance(raw, list) and raw:
            items = raw
        else:
            return _error(400, "input must be a string, a token-id list, "
                               "or a list of those")
        loop = asyncio.get_running_loop()
        data, total = [], 0
        try:
            for i, item in enumerate(items):
                ids = self._encode_prompt(item)
                total += len(ids)
                emb = await loop.run_in_executor(None, self._embed_ids, ids)
                data.append({"object": "embedding", "index": i,
                             "embedding": emb})
        except ValueError as e:
            return _error(400, str(e))
        return web.json_response(
            {"object": "list", "data": data, "model": self.model_name,
             "usage": {"prompt_tokens": total, "total_tokens": total}})

    async def models(self, request: web.Request) -> web.Response:
        return web.json_response({"object": "list", "data": [
            {"id": self.model_name, "object": "model",
             "created": int(time.time()), "owned_by": "kubetorch-tpu"}]})

    async def completions(self, request: web.Request) -> web.Response:
        return await self._serve(request, chat=False)

    async def chat_completions(self, request: web.Request) -> web.Response:
        return await self._serve(request, chat=True)

    async def _serve(self, request: web.Request, chat: bool) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return _error(400, "body must be JSON")
        raw_n = body.get("n")
        # null means "use the default", per OpenAI; bools and floats are
        # not integers (int() would silently truncate 2.9 to 2)
        if raw_n is None:
            n = 1
        elif isinstance(raw_n, int) and not isinstance(raw_n, bool):
            n = raw_n
        else:
            return _error(400, f"n must be an integer, got {raw_n!r}")
        if not 1 <= n <= 128:        # OpenAI's own cap
            return _error(400, f"n must be in [1, 128], got {n}")
        if n > 1 and body.get("stream"):
            return _error(400, "streaming with n > 1 is not supported")
        best_of = body.get("best_of")
        if best_of is not None:
            if chat:
                return _error(400, "best_of applies to /v1/completions only")
            if not (isinstance(best_of, int)
                    and not isinstance(best_of, bool)):
                return _error(400, f"best_of must be an integer, "
                                   f"got {best_of!r}")
            if not n <= best_of <= 128:
                return _error(400, f"best_of must be in [n, 128], "
                                   f"got {best_of} (n={n})")
            if body.get("stream"):
                return _error(400, "streaming with best_of is not supported")
        if body.get("echo"):
            # explicit refusals mirror OpenAI: echo is a completions-only,
            # non-streaming field — silently dropping it would hand back
            # wrong output to a client relying on it
            if chat:
                return _error(400, "echo applies to /v1/completions only")
            if body.get("stream"):
                return _error(400, "streaming with echo is not supported")
        n_submit = best_of if best_of is not None else n
        try:
            prompt_ids = (self._chat_prompt(body.get("messages"))
                          if chat else self._encode_prompt(body.get("prompt")))
            # the candidates decode concurrently on the slot grid, each
            # drawing its own sampling keys
            pairs = []
            try:
                for i in range(n_submit):
                    h, cutter, tok_stops = self._submit(body, prompt_ids,
                                                        choice_index=i)
                    pairs.append((h, cutter))
            except Exception:
                for h, _c in pairs:      # don't strand earlier submissions
                    h.cancel()
                raise
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            # TypeError/AttributeError: malformed wire fields (a list
            # logit_bias, a null bias value) surface from the submit
            # normalization — client errors, not server faults
            return _error(400, str(e))
        rid = f"{'chatcmpl' if chat else 'cmpl'}-{next(self._req_ids)}"
        want_logprobs = bool(body.get("logprobs"))
        if body.get("stream"):
            (handle, cutter), = pairs
            return await self._stream(request, handle, cutter, rid, chat,
                                      tok_stops, want_logprobs)
        return await self._blocking(pairs, rid, chat, prompt_ids,
                                    tok_stops, want_logprobs, keep_n=n,
                                    echo=bool(body.get("echo"))
                                    and not chat)

    def _finished_by_stop(self, ids: List[int], tok_stops) -> bool:
        if (self.engine.eos_id is not None and ids
                and ids[-1] == self.engine.eos_id):
            return True
        return any(len(q) <= len(ids) and ids[len(ids) - len(q):] == list(q)
                   for q in tok_stops)

    async def _blocking(self, pairs, rid, chat, prompt_ids,
                        tok_stops, want_logprobs=False, keep_n=None,
                        echo=False):
        loop = asyncio.get_running_loop()
        n_prompt = len(prompt_ids)
        results = []
        for index, (handle, cutter) in enumerate(pairs):
            try:
                ids = await loop.run_in_executor(None, handle.result)
            except Exception as e:  # admission error surfaced via handle
                for h, _c in pairs[index + 1:]:
                    h.cancel()
                return _error(400, str(e))
            results.append((ids, handle.logprobs, cutter))
        total = sum(len(ids) for ids, _lp, _c in results)
        if keep_n is not None and keep_n < len(results):
            # best_of: rank candidates by mean token logprob (the OpenAI
            # rule) over the VISIBLE tokens — a text stop hides the tail
            # at response-build time, and scoring dropped text would let
            # a worse visible completion win. Token stops/eos retire the
            # request in-engine, so only text stops can leave a tail.
            # Usage still counts EVERY candidate's tokens (all decoded).
            def visible(ids, cutter):
                if self.tokenizer is None or not cutter.stops:
                    return len(ids)
                acc = ""
                for i, t in enumerate(ids):
                    acc += self._decode([t])
                    if any(s in acc for s in cutter.stops):
                        return i + 1
                return len(ids)

            def score(r):
                ids, lp_list, cutter = r
                lps = [lp for lp in lp_list[:visible(ids, cutter)]
                       if lp is not None]
                return sum(lps) / len(lps) if lps else float("-inf")
            results = sorted(results, key=score, reverse=True)[:keep_n]
        echo_text = (self._decode(list(prompt_ids))
                     if echo and self.tokenizer is not None else None)
        choices = []
        for index, (ids, lp_list, cutter) in enumerate(results):
            text = None
            finish = "stop" if self._finished_by_stop(ids, tok_stops) \
                else "length"
            if self.tokenizer is not None:
                piece, matched = cutter.feed(self._decode(ids))
                text = piece if matched else piece + cutter.flush()
                if matched:
                    finish = "stop"
            lps = lp_list if want_logprobs else None
            if echo:
                # OpenAI echo: the prompt rides in front of the
                # completion (prompt tokens carry no logprobs)
                ids = list(prompt_ids) + ids
                if text is not None:
                    text = echo_text + text
                if lps is not None:
                    lps = [None] * n_prompt + lps
            if chat:
                choice = {"index": index, "finish_reason": finish,
                          "message": {"role": "assistant",
                                      "content": text if text is not None
                                      else None,
                                      "token_ids": ids}}
                if lps is not None:
                    choice["logprobs"] = {"content": [
                        {"token": self._decode([t]) if self.tokenizer
                         else str(t),
                         "logprob": lp, "bytes": None}
                        for t, lp in zip(ids, lps)]}
            else:
                choice = {"index": index, "finish_reason": finish,
                          "text": text if text is not None else "",
                          "token_ids": ids}
                if lps is not None:
                    choice["logprobs"] = {
                        "tokens": [self._decode([t]) if self.tokenizer
                                   else str(t) for t in ids],
                        "token_logprobs": lps,
                        "top_logprobs": None, "text_offset": None}
            choices.append(choice)
        usage = {"prompt_tokens": n_prompt, "completion_tokens": total,
                 "total_tokens": n_prompt + total}
        obj = "chat.completion" if chat else "text_completion"
        return web.json_response(
            {"id": rid, "object": obj, "created": int(time.time()),
             "model": self.model_name, "choices": choices, "usage": usage})

    async def _stream(self, request, handle, cutter, rid, chat,
                      tok_stops, want_logprobs=False):
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache"})
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def pump():
            try:
                for tok in handle:
                    lp = handle.logprobs[-1] if want_logprobs else None
                    loop.call_soon_threadsafe(q.put_nowait, ("tok", (tok, lp)))
                loop.call_soon_threadsafe(q.put_nowait, ("end", None))
            except Exception as e:  # pragma: no cover - admission errors
                loop.call_soon_threadsafe(q.put_nowait, ("err", str(e)))

        threading.Thread(target=pump, daemon=True,
                         name="kt-openai-pump").start()

        async def send(payload):
            await resp.write(f"data: {json.dumps(payload)}\n\n".encode())

        def chunk(piece, ids, finish=None, lp=None):
            delta_key = "delta" if chat else "text"
            content = ({"content": piece} if chat else piece)
            c = {"index": 0, delta_key: content, "token_ids": ids,
                 "finish_reason": finish}
            if lp is not None:
                c["logprob"] = lp
            return {"id": rid,
                    "object": ("chat.completion.chunk" if chat
                               else "text_completion"),
                    "created": int(time.time()), "model": self.model_name,
                    "choices": [c]}

        all_ids: List[int] = []
        try:
            while True:
                kind, val = await q.get()
                if kind == "err":
                    await send(chunk("", [], "error"))
                    break
                if kind == "end":
                    tail = cutter.flush() if self.tokenizer else ""
                    if tail:
                        await send(chunk(tail, []))
                    finish = ("stop" if self._finished_by_stop(
                        all_ids, tok_stops) else "length")
                    await send(chunk("" if chat else "", [], finish))
                    break
                val, lp = val
                ids = [val]
                all_ids.append(val)
                if self.tokenizer is not None:
                    piece, matched = cutter.feed(self._decode(ids))
                    if piece:
                        await send(chunk(piece, ids, lp=lp))
                    if matched:
                        # everything after the stop string is not ours to
                        # emit: cancel the request (frees the slot at the
                        # next step boundary) and close the stream now
                        handle.cancel()
                        await send(chunk("", [], "stop"))
                        break
                else:
                    await send(chunk("", ids, lp=lp))
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            handle.cancel()     # client hung up: free the slot
            raise
        return resp

    async def register_prefix(self, request: web.Request) -> web.Response:
        """Operator surface for the engine's prefix cache (non-OpenAI
        extension): POST {"text": "..."} or {"tokens": [...]} prefills the
        prefix once and caches its K/V. With the engine's ``auto_prefix``
        on, every subsequent completion whose prompt starts with it skips
        recomputing those rows — register the system prompt here and the
        standard OpenAI calls speed up with no client change."""
        try:
            body = await request.json()
        except Exception:
            return _error(400, "body must be JSON")
        if "tokens" in body:
            try:
                ids = [int(t) for t in body["tokens"]]
            except (TypeError, ValueError):
                return _error(400, "tokens must be a list of ints")
        elif "text" in body:
            if self.tokenizer is None:
                return _error(400, "no tokenizer loaded; pass token ids")
            ids = self.tokenizer.encode(body["text"])
        else:
            return _error(400, "pass 'text' or 'tokens'")
        adapter_id = body.get("adapter_id")   # adapter-keyed: LoRA traffic
        try:                                  # only matches its own prefixes
            # the prefill (and possibly its first compile) runs on-device
            # for seconds — off the event loop, like completions/embeddings
            loop = asyncio.get_running_loop()
            pid = await loop.run_in_executor(
                None, partial(self.engine.register_prefix, ids,
                              adapter_id=adapter_id))
        except (ValueError, KeyError) as e:
            return _error(400, str(e))
        return web.json_response({"prefix_id": pid, "n_tokens": len(ids)})

    async def unregister_prefix(self, request: web.Request) -> web.Response:
        pid = int(request.match_info["pid"])
        if not self.engine.unregister_prefix(pid):
            return _error(404, f"unknown prefix_id {pid}", "not_found")
        return web.json_response({"deleted": pid})

    def build(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/v1/models", self.models)
        app.router.add_post("/v1/completions", self.completions)
        app.router.add_post("/v1/chat/completions", self.chat_completions)
        app.router.add_post("/v1/embeddings", self.embeddings)
        app.router.add_post("/v1/prefixes", self.register_prefix)
        app.router.add_delete("/v1/prefixes/{pid:\\d+}",
                              self.unregister_prefix)
        return app


def build_app(engine, tokenizer=None,
              model_name: str = "kubetorch-tpu") -> web.Application:
    return OpenAIApp(engine, tokenizer, model_name).build()


def main(argv=None):
    """Standalone server: HF checkpoint dir → engine → OpenAI API."""
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--ckpt", required=True,
                        help="HF save_pretrained directory")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--max-len", type=int, default=2048)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument("--decode-block", type=int, default=32,
                        help="device decode steps per dispatch (amortizes "
                             "host/relay overhead; 1 = step-per-token; "
                             "on-chip sweep: 8→386, 32→1081, 128→1913 "
                             "tok/s/chip on the 0.5B model)")
    parser.add_argument("--auto-prefix", action="store_true",
                        help="reuse registered prefixes (POST /v1/prefixes) "
                             "for any prompt that starts with one")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="chunked prefill: admit prompts longer than "
                             "this C tokens at a time between decode "
                             "blocks, so long admissions never stall "
                             "active streams (default: one-shot)")
    parser.add_argument("--no-tokenizer", action="store_true",
                        help="token-id mode (skip AutoTokenizer)")
    args = parser.parse_args(argv)

    from ..models.convert_hf import load_hf
    from . import GenerationEngine, quantize_params

    params, cfg = load_hf(args.ckpt, max_seq_len=args.max_len)
    if args.int8:
        params = quantize_params(params)
    tokenizer = None
    if not args.no_tokenizer:
        import transformers
        tokenizer = transformers.AutoTokenizer.from_pretrained(args.ckpt)
    eos = getattr(tokenizer, "eos_token_id", None)
    engine = GenerationEngine(params, cfg, slots=args.slots,
                              max_len=args.max_len, eos_id=eos,
                              decode_block=args.decode_block,
                              auto_prefix=args.auto_prefix,
                              prefill_chunk=args.prefill_chunk).start()
    web.run_app(build_app(engine, tokenizer), port=args.port)


if __name__ == "__main__":
    main()
