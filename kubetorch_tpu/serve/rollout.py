"""Live weight rollout: zero-downtime engine hot swap (ISSUE 11).

THE weight-swap site. A running :class:`~kubetorch_tpu.serve.engine.
GenerationEngine` never has its parameter tree assigned from anywhere but
this module — ``scripts/check_resilience.py`` lints for strays — because a
swap that skips this path silently skips every guarantee the online-
learning loop rests on:

- **Delta fetch over the broadcast tree, off the decode thread.** The
  trainer pushes a checkpoint through the content-addressed delta path
  (only changed leaves move bytes at all) and publishes a *rollout
  manifest* via the ring's write-quorum ``put_json``
  (``train.checkpoint.publish_rollout``). Each replica's
  :class:`WeightRollout` polls the manifest, diffs the index's per-leaf
  blake2b hashes against its own verified ledger, and prefetches exactly
  the changed leaves through the P2P broadcast tree
  (``data_store/store_server.py`` ``/route``) — so a fleet-wide multi-GB
  rollout leaves the origin's NIC O(delta), not O(replicas × delta), and
  the decode loop never blocks on the network.
- **Bit-equality gate before any swap.** The staged tree's composed
  fingerprint (:func:`~kubetorch_tpu.data_store.commands.
  tree_fingerprint_of_hashes` over already-verified leaf hashes) must
  equal the manifest's ``tree_fingerprint`` — the same value the trainer
  computed from its live state. Mismatch → typed
  :class:`~kubetorch_tpu.exceptions.RolloutError`, engine untouched. A
  replica is ALWAYS either fully on version N or fully on version M,
  never silently mixed.
- **Swap between decode batches, with buffer donation.** The actual
  assignment runs on the engine's stepping thread via
  ``engine.at_batch_boundary`` — no decode dispatch is in flight — and
  proceeds leaf-by-leaf: the old device buffer is freed *before* its
  replacement lands, so peak HBM overhead is one leaf, never 2× the
  model. In a deployed pod the staged host arrays reach the rank worker
  as ordinary call args — i.e. over the ISSUE-10 shared-memory envelope
  path — before this module applies them.
- **Canary-first, auto-rollback.** A ``phase="canary"`` manifest swaps
  ONLY the named replica; :class:`CanaryRollout` pins a router traffic
  slice to it and watches error-rate/latency against the pre-swap EWMA
  (``serving.router.Router.set_canary``), then promotes
  (``phase="fleet"``) or publishes a typed rollback. The pre-swap leaves
  are stashed host-side (delta-sized), so rollback is a local batch-
  boundary swap — no refetch.

Telemetry: ``kt_rollout_seconds{phase}``, ``kt_rollout_bytes_total
{source}``, ``kt_rollout_rollbacks_total{reason}``, plus a
``rollout.swap`` span parented onto the trainer's push trace (the
manifest carries the trace context). Rows in docs/observability.md;
runbook in docs/operations.md "Live weight rollout".
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..data_store import commands as ds
from ..data_store import netpool
from ..exceptions import RolloutError

_ROLLOUT_SECONDS = telemetry.histogram(
    "kt_rollout_seconds",
    "Live weight rollout wall-clock per phase "
    "(fetch: delta over the broadcast tree; stage: host staging + "
    "fingerprint gate; swap: batch-boundary donation swap; verify: "
    "post-swap ledger/fingerprint check)",
    labels=("phase",))
_ROLLOUT_BYTES = telemetry.counter(
    "kt_rollout_bytes_total",
    "Rollout delta bytes moved, by serving source (origin: the store "
    "ring; peer: the P2P broadcast tree / pod cache)",
    labels=("source",))
_ROLLBACKS = telemetry.counter(
    "kt_rollout_rollbacks_total",
    "Weight rollbacks applied, by reason",
    labels=("reason",))
_ROLLOUT_VERSION = telemetry.gauge(
    "kt_rollout_version",
    "Rollout manifest version this process's engine is serving")

# live WeightRollout instances in this process — the /rollout/status and
# `kt rollout status` surface
_LOCAL: "weakref.WeakSet[WeightRollout]" = weakref.WeakSet()


def manifest_key(service: str) -> str:
    return f"rollout/{service}/manifest"


def weights_key(service: str) -> str:
    return f"rollout/{service}/weights"


def read_manifest(service: str,
                  store_url: Optional[str] = None) -> Optional[Dict]:
    """The fleet's current rollout manifest, read at QUORUM (every member
    of its replica set answers; newest ``stored_at`` wins) — a store-node
    loss mid-rollout can never resurrect a stale version. None when no
    rollout has ever been published for ``service``."""
    m = ds.get_json(manifest_key(service), store_url=store_url, quorum=True)
    return m if isinstance(m, dict) else None


def publish_manifest(service: str, *, key: str, step: Optional[int] = None,
                     fingerprint: Optional[str] = None,
                     phase: str = "fleet", canary: Optional[str] = None,
                     reason: Optional[str] = None,
                     store_url: Optional[str] = None,
                     version: Optional[int] = None,
                     index_blake2b: Optional[str] = None) -> Dict:
    """Write the rollout manifest through the ring's write-quorum
    ``put_json`` path (the PUT is the commit point — replicas act only on
    what this publishes). ``version`` auto-increments over the previous
    manifest; the active trace context rides along so every replica's
    ``rollout.swap`` span parents onto the trainer's push trace."""
    if phase not in ("canary", "fleet", "rollback"):
        raise ValueError(f"unknown rollout phase {phase!r}")
    prev = read_manifest(service, store_url=store_url)
    if version is None:
        version = (int(prev.get("version", 0)) + 1) if prev else 1
    manifest = {
        "service": service,
        "version": int(version),
        "key": key,
        "step": None if step is None else int(step),
        "fingerprint": fingerprint,
        "phase": phase,
        "canary": canary,
        "reason": reason,
        # content address of this version's pytree index: what lets
        # replicas fetch a re-put-in-place key over the broadcast tree
        # content-addressed (stale pod caches miss cleanly)
        "index_blake2b": index_blake2b,
        "published_at": round(time.time(), 6),
        "trace": telemetry.current_header(),
    }
    ds.put_json(manifest_key(service), manifest, store_url=store_url)
    return manifest


def local_status() -> List[Dict]:
    """Status of every live rollout coordinator in THIS process (the pod
    ``/rollout/status`` payload)."""
    return [w.status() for w in list(_LOCAL)]


# ---------------------------------------------------------------------------
# pytree path helpers (paths are commands._flatten's "a/b/0/c" shape)
# ---------------------------------------------------------------------------


def _get_leaf(tree: Any, path: str) -> Any:
    node = tree
    for part in path.split("/"):
        if isinstance(node, dict):
            node = node[part]
        elif isinstance(node, (list, tuple)):
            node = node[int(part)]
        else:
            raise KeyError(path)
    return node


def _set_leaf(tree: Any, path: str, value: Any) -> None:
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        if isinstance(node, dict):
            node = node[part]
        elif isinstance(node, list):
            node = node[int(part)]
        else:
            raise RolloutError(
                f"cannot swap into immutable container at {path!r}",
                reason="immutable_container")
    last = parts[-1]
    if isinstance(node, dict):
        node[last] = value
    elif isinstance(node, list):
        node[int(last)] = value
    else:
        raise RolloutError(
            f"cannot swap into immutable container at {path!r}",
            reason="immutable_container")


def _host_leaf(arr: Any) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(arr))


def _is_device_array(arr: Any) -> bool:
    # jax.Array has .delete()/.sharding; numpy has neither
    return hasattr(arr, "delete") and hasattr(arr, "sharding")


# ---------------------------------------------------------------------------
# the per-replica coordinator
# ---------------------------------------------------------------------------


class WeightRollout:
    """One engine's live-rollout coordinator.

    ``engine`` is any object with a mutable ``params`` pytree and the
    ``at_batch_boundary(fn, timeout=)`` contract —
    :class:`~kubetorch_tpu.serve.engine.GenerationEngine` in production,
    :class:`HostEngine` as the CPU proxy in benches/tests. ``replica_id``
    is how canary manifests name this replica (defaults to ``POD_IP``,
    falling back to the hostname).

    Drive it with :meth:`poll_once` (deterministic — what the tests call)
    or :meth:`start` the background manifest-poll thread. All swap state
    transitions are serialized by an internal lock: one apply at a time,
    and ``status()`` is safe from any thread.
    """

    def __init__(self, engine: Any, service: str, *,
                 replica_id: Optional[str] = None,
                 store_url: Optional[str] = None,
                 poll_s: float = 2.0, peer: Optional[bool] = None,
                 swap_timeout_s: float = 120.0):
        import socket

        self.engine = engine
        self.service = service
        self.replica_id = (replica_id or os.environ.get("POD_IP")
                           or socket.gethostname())
        self.store_url = store_url
        self.poll_s = float(poll_s)
        self.peer = peer
        self.swap_timeout_s = float(swap_timeout_s)
        self.version = 0
        self.step: Optional[int] = None
        self.phase: Optional[str] = None
        self.fingerprint: Optional[str] = None
        self.applied_at: Optional[float] = None
        self.last_error: Optional[str] = None
        self.bytes_moved = {"origin": 0, "peer": 0}
        self.swaps = 0
        self.rollbacks = 0
        self._leaf_hashes: Optional[Dict[str, str]] = None
        # pre-swap stash of the LAST swap's replaced leaves (host, delta-
        # sized): what makes rollback a local batch-boundary swap
        self._undo: Optional[Dict[str, Any]] = None
        self._apply_lock = threading.Lock()
        self._swapping = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _LOCAL.add(self)

    # -- ledger --------------------------------------------------------------

    def _ensure_ledger(self) -> None:
        """Per-leaf content hashes of the engine's CURRENT params. Computed
        once (full host pull + hash — the price of joining the verified
        world from unverified initial weights); every later apply updates
        it incrementally from already-verified index hashes."""
        if self._leaf_hashes is not None:
            return
        leaves: Dict[str, Any] = {}
        ds._flatten(self.engine.params, "", leaves)
        self._leaf_hashes = {p: ds._leaf_hash(_host_leaf(a))
                             for p, a in leaves.items()}
        self.fingerprint = ds.tree_fingerprint_of_hashes(self._leaf_hashes)

    # -- polling -------------------------------------------------------------

    def poll_once(self) -> Optional[Dict]:
        """Read the manifest and converge toward it. Returns the apply/
        rollback summary when something changed, None otherwise. Never
        raises on transport problems (the poll loop must survive a store
        blip); RolloutError from a bad manifest is recorded on
        ``last_error`` and re-raised for deterministic callers."""
        manifest = read_manifest(self.service, store_url=self.store_url)
        if manifest is None:
            return None
        try:
            version = int(manifest.get("version", 0))
        except (TypeError, ValueError):
            return None
        if version <= self.version:
            return None
        phase = manifest.get("phase", "fleet")
        if phase == "canary" and manifest.get("canary") != self.replica_id:
            # non-canary replicas NEVER swap on a canary manifest — they
            # wait for the fleet promotion (or absorb the rollback bump)
            return None
        try:
            if phase == "rollback":
                return self._apply_rollback(manifest)
            return self.apply(manifest)
        except RolloutError as e:
            self.last_error = str(e)
            raise

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except RolloutError:
                pass                     # recorded on last_error
            except Exception as e:       # noqa: BLE001 — poll must survive
                self.last_error = str(e)
            self._stop.wait(self.poll_s)

    def start(self) -> "WeightRollout":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="kt-weight-rollout")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- apply ---------------------------------------------------------------

    def apply(self, manifest: Dict) -> Dict:
        with self._apply_lock:
            return self._apply_locked(manifest)

    def _span_parent(self, manifest: Dict):
        tr = manifest.get("trace")
        if not tr:
            return None
        return telemetry.extract({telemetry.TRACE_HEADER: tr})

    def _apply_locked(self, manifest: Dict) -> Dict:
        version = int(manifest["version"])
        key = manifest.get("key") or weights_key(self.service)
        m_fp = manifest.get("fingerprint")
        url = ds._store_url(self.store_url)

        # ---- fetch: delta over the broadcast tree, off the decode thread.
        # content_alias keys the peer exchange by subkey@hash — mutable
        # rollout keys ride the tree without stale-cache hazards
        t0 = time.monotonic()
        fetcher = ds._RoutedFetcher(url, key, self.peer, content_alias=True)
        index_hash = manifest.get("index_blake2b")
        r = fetcher.fetch(f"{key}{ds._INDEX_SUFFIX}", timeout=60,
                          expect_hash=index_hash)
        if r.status_code != 200:
            raise RolloutError(
                f"rollout v{version}: weights index {key!r} not in the "
                "store", reason="missing_index", version=version)
        index = json.loads(r.content)
        target = {p: m["blake2b"] for p, m in index["leaves"].items()}
        want_fp = ds.tree_fingerprint_of_hashes(target)
        if m_fp is not None and want_fp != m_fp and index_hash is None:
            # legacy manifest without the index content address: a pod
            # cache may have served the PREVIOUS version's index — evict
            # it and retry once straight from the origin
            try:
                from ..data_store.peer_cache import cache_evict
                cache_evict(f"{key}{ds._INDEX_SUFFIX}")
            except Exception:     # noqa: BLE001 — cache-less environment
                pass
            r = ds._RoutedFetcher(url, key, False).fetch(
                f"{key}{ds._INDEX_SUFFIX}", timeout=60)
            if r.status_code == 200:
                index = json.loads(r.content)
                target = {p: m["blake2b"]
                          for p, m in index["leaves"].items()}
                want_fp = ds.tree_fingerprint_of_hashes(target)
        if m_fp is not None and want_fp != m_fp:
            # the index does not add up to what the trainer committed —
            # refuse BEFORE moving bulk bytes or touching the engine
            raise RolloutError(
                f"rollout v{version}: index fingerprint {want_fp} != "
                f"manifest {m_fp}", reason="fingerprint_mismatch",
                version=version, expected=m_fp, actual=want_fp)
        self._ensure_ledger()
        if set(target) != set(self._leaf_hashes):
            raise RolloutError(
                f"rollout v{version}: weight tree structure changed "
                f"({len(target)} leaves vs engine's "
                f"{len(self._leaf_hashes)}) — a live engine cannot change "
                "compiled shapes; redeploy instead",
                reason="structure_mismatch", version=version)
        changed = [p for p in target if target[p] != self._leaf_hashes[p]]

        def _one(path):
            meta = index["leaves"][path]
            rr = fetcher.fetch(f"{key}/{path}",
                               expect_hash=meta.get("blake2b"))
            if rr.status_code != 200:
                raise RolloutError(
                    f"rollout v{version}: missing leaf {key}/{path}",
                    reason="missing_leaf", version=version)
            return path, ds._decode_array(rr.content, meta, None)

        staged = dict(netpool.map_concurrent(_one, changed))
        fetcher.complete()      # become a broadcast parent for later joiners
        for src, n in fetcher.bytes_by_source.items():
            bucket = "origin" if src == "store" else "peer"
            self.bytes_moved[bucket] += n
            _ROLLOUT_BYTES.inc(n, source=bucket)
        _ROLLOUT_SECONDS.observe(time.monotonic() - t0, phase="fetch")

        with telemetry.span("rollout.swap", parent=self._span_parent(manifest),
                            service=self.service, version=version,
                            leaves=len(changed)) as sp:
            with telemetry.stage("rollout_apply"):
                # ---- stage: shape/dtype gate against the compiled step
                t0 = time.monotonic()
                for path, arr in staged.items():
                    cur = _get_leaf(self.engine.params, path)
                    if (tuple(arr.shape) != tuple(cur.shape)
                            or str(arr.dtype) != str(cur.dtype)):
                        raise RolloutError(
                            f"rollout v{version}: leaf {path!r} is "
                            f"{arr.dtype}{tuple(arr.shape)}, engine holds "
                            f"{cur.dtype}{tuple(cur.shape)} — the compiled "
                            "step's shapes are frozen",
                            reason="shape_mismatch", version=version)
                _ROLLOUT_SECONDS.observe(time.monotonic() - t0,
                                         phase="stage")

                # ---- swap: between decode batches, donated leaf-by-leaf
                t0 = time.monotonic()
                self._swapping = True
                try:
                    undo = self.engine.at_batch_boundary(
                        lambda: self._swap_leaves(staged),
                        timeout=self.swap_timeout_s)
                finally:
                    self._swapping = False
                _ROLLOUT_SECONDS.observe(time.monotonic() - t0, phase="swap")

                # ---- verify: ledger + composed fingerprint bit-equality
                t0 = time.monotonic()
                old_hashes = {p: self._leaf_hashes[p] for p in changed}
                self._undo = {"version": self.version,
                              "fingerprint": self.fingerprint,
                              "leaves": undo, "hashes": old_hashes}
                self._leaf_hashes.update({p: target[p] for p in changed})
                got_fp = ds.tree_fingerprint_of_hashes(self._leaf_hashes)
                if m_fp is not None and got_fp != m_fp:
                    raise RolloutError(
                        f"rollout v{version}: post-swap fingerprint "
                        f"{got_fp} != manifest {m_fp}",
                        reason="verify_failed", version=version,
                        expected=m_fp, actual=got_fp)
                self.fingerprint = got_fp
                self.version = version
                self.step = manifest.get("step")
                self.phase = manifest.get("phase", "fleet")
                self.applied_at = time.time()
                self.swaps += 1
                self.last_error = None
                _ROLLOUT_VERSION.set(version)
                _ROLLOUT_SECONDS.observe(time.monotonic() - t0,
                                         phase="verify")
            if sp:
                sp.set_attr("fingerprint", got_fp)
                sp.set_attr("bytes", sum(fetcher.bytes_by_source.values()))
        return {"version": version, "leaves_changed": len(changed),
                "fingerprint": got_fp,
                "bytes": dict(fetcher.bytes_by_source)}

    def _swap_leaves(self, staged: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """The donation swap (stepping thread, between batches): per leaf,
        stash the old bytes host-side for rollback, FREE the old device
        buffer, then land the replacement with the old sharding — peak
        extra HBM is one leaf, never a second model."""
        params = self.engine.params
        undo: Dict[str, Any] = {}
        for path, host_new in staged.items():
            cur = _get_leaf(params, path)
            undo[path] = np.array(_host_leaf(cur), copy=True)
            on_device = _is_device_array(cur)
            sharding = cur.sharding if on_device else None
            _set_leaf(params, path, None)   # drop the tree's reference
            if on_device:
                try:
                    cur.delete()            # donation: free BEFORE landing
                except Exception:           # noqa: BLE001 — already freed
                    pass
            del cur
            if on_device:
                import jax
                new_leaf = jax.device_put(host_new, sharding)
            else:
                new_leaf = host_new
            _set_leaf(params, path, new_leaf)
        return undo

    # -- rollback ------------------------------------------------------------

    def _apply_rollback(self, manifest: Dict) -> Dict:
        with self._apply_lock:
            version = int(manifest["version"])
            reason = manifest.get("reason") or "manifest"
            target_fp = manifest.get("fingerprint")
            self._ensure_ledger()
            if target_fp is not None and target_fp == self.fingerprint:
                # never swapped to the bad version (non-canary replica, or
                # a replica that already rolled back): adopt the version
                # number, touch nothing
                self.version = version
                self.phase = "rollback"
                _ROLLOUT_VERSION.set(version)
                return {"version": version, "rolled_back": False,
                        "fingerprint": self.fingerprint}
            undo = self._undo
            if undo is None or (target_fp is not None
                                and undo["fingerprint"] != target_fp):
                if manifest.get("key") and target_fp is not None:
                    # no matching local stash (e.g. replica restarted):
                    # converge by an ordinary verified apply toward the
                    # good version the manifest names
                    out = self._apply_locked(manifest)
                    self.rollbacks += 1
                    _ROLLBACKS.inc(reason=reason)
                    return out
                raise RolloutError(
                    f"rollback v{version}: no pre-swap stash and no "
                    "target weights to refetch", reason="no_undo",
                    version=version)
            t0 = time.monotonic()
            self._swapping = True
            try:
                self.engine.at_batch_boundary(
                    lambda: self._swap_leaves(undo["leaves"]),
                    timeout=self.swap_timeout_s)
            finally:
                self._swapping = False
            _ROLLOUT_SECONDS.observe(time.monotonic() - t0, phase="swap")
            self._leaf_hashes.update(undo["hashes"])
            self.fingerprint = ds.tree_fingerprint_of_hashes(
                self._leaf_hashes)
            self.version = version
            self.step = manifest.get("step")
            self.phase = "rollback"
            self.applied_at = time.time()
            self.rollbacks += 1
            self._undo = None
            _ROLLBACKS.inc(reason=reason)
            _ROLLOUT_VERSION.set(version)
            telemetry.add_event("rollout.rollback", reason=reason,
                                version=version)
            return {"version": version, "rolled_back": True,
                    "fingerprint": self.fingerprint}

    # -- introspection -------------------------------------------------------

    def status(self) -> Dict:
        return {
            "service": self.service,
            "replica": self.replica_id,
            "version": self.version,
            "step": self.step,
            "phase": self.phase,
            "fingerprint": self.fingerprint,
            "applied_at": self.applied_at,
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "swapping": self._swapping,
            "bytes": dict(self.bytes_moved),
            "last_error": self.last_error,
            "polling": self._thread is not None and self._thread.is_alive(),
        }


# ---------------------------------------------------------------------------
# canary-first control
# ---------------------------------------------------------------------------


class CanaryRollout:
    """Fleet-level canary-first driver.

    Publishes the new version as a canary manifest (only the named replica
    swaps), pins a slice of router traffic to it
    (``Router.set_canary``), and bakes: a regression verdict — error rate
    or latency blown out against the router's pre-swap EWMA — publishes a
    typed rollback manifest; a clean bake promotes to ``phase="fleet"``.
    Non-canary replicas swap only on the promotion, by construction of
    :meth:`WeightRollout.poll_once`.
    """

    def __init__(self, service: str, router: Any, *,
                 store_url: Optional[str] = None,
                 slice_fraction: float = 0.1, bake_s: float = 10.0,
                 min_requests: int = 20, ttft_factor: float = 2.0,
                 err_threshold: float = 0.05, poll_s: float = 0.25):
        self.service = service
        self.router = router
        self.store_url = store_url
        self.slice_fraction = slice_fraction
        self.bake_s = bake_s
        self.min_requests = min_requests
        self.ttft_factor = ttft_factor
        self.err_threshold = err_threshold
        self.poll_s = poll_s

    def run(self, publish, canary_replica: str) -> str:
        """Drive one canary-first rollout. ``publish(phase=..., canary=...)``
        is the trainer-side publisher (typically a partial of
        ``train.checkpoint.publish_rollout`` over the new tree) — called
        once for the canary manifest and, on a clean bake, once more for
        the fleet promotion. Returns ``"promoted"`` or ``"rolled_back"``.

        A first-ever rollout (no previous manifest) promotes directly:
        there is no good version to regress against or roll back to."""
        prev = read_manifest(self.service, store_url=self.store_url)
        if prev is None or not prev.get("fingerprint"):
            publish(phase="fleet")
            return "promoted"
        canary_m = publish(phase="canary", canary=canary_replica)
        self.router.set_canary(canary_replica,
                               fraction=self.slice_fraction)
        verdict = "warming"
        deadline = time.monotonic() + self.bake_s
        try:
            while time.monotonic() < deadline:
                verdict = self.router.canary_verdict(
                    min_requests=self.min_requests,
                    ttft_factor=self.ttft_factor,
                    err_threshold=self.err_threshold)
                if verdict == "regressed":
                    break
                time.sleep(self.poll_s)
        finally:
            self.router.clear_canary()
        if verdict == "regressed":
            publish_manifest(
                self.service, key=prev["key"], step=prev.get("step"),
                fingerprint=prev["fingerprint"], phase="rollback",
                reason="canary_regression", store_url=self.store_url)
            telemetry.add_event("rollout.canary_regressed",
                                canary=canary_replica,
                                version=canary_m.get("version"))
            return "rolled_back"
        publish(phase="fleet")
        return "promoted"


# ---------------------------------------------------------------------------
# CPU-proxy engine (benches / tests)
# ---------------------------------------------------------------------------


class HostEngine:
    """Host-side engine stand-in with the exact swap contract
    ``WeightRollout`` needs — a mutable ``params`` pytree, a stepping
    loop, and ``at_batch_boundary`` — so the rollout path (fetch, stage,
    fingerprint gate, boundary swap, rollback) is drivable on a 1-core CI
    box and in ``scripts/bench_rollout.py``'s subprocess replicas without
    compiling a model. Each "decode batch" advances every in-flight
    request one token and touches a param leaf, so a torn swap would be
    observable as an exception or a dropped request."""

    def __init__(self, params: Dict[str, Any], step_s: float = 0.001):
        self.params = params
        self.step_s = float(step_s)
        self.steps = 0
        # same continuous-learning tap as GenerationEngine.feedback_sink,
        # so bench_serve's host-mode replicas can feed the flywheel ledger
        self.feedback_sink = None
        self._req_seq = 0
        self._reqs: List[Dict[str, Any]] = []
        self._hooks: "deque[tuple]" = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def submit(self, n_tokens: int = 4) -> Dict[str, Any]:
        req = {"left": int(n_tokens), "done": threading.Event(),
               "error": None}
        with self._lock:
            # unique per-engine id: the flywheel ledger dedups feedback
            # records by content hash, so two requests retiring in the
            # same step with equal token counts must not hash alike
            self._req_seq += 1
            req["id"] = self._req_seq
            self._reqs.append(req)
        self._work.set()
        return req

    def at_batch_boundary(self, fn, timeout: Optional[float] = None):
        thread = self._thread
        if (thread is None or not thread.is_alive()
                or threading.current_thread() is thread):
            return fn()
        box: Dict[str, Any] = {"done": threading.Event()}
        self._hooks.append((fn, box))
        self._work.set()
        if not box["done"].wait(timeout):
            raise TimeoutError("HostEngine batch boundary not reached")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _step_once(self) -> int:
        while self._hooks:
            fn, box = self._hooks.popleft()
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            finally:
                box["done"].set()
        with self._lock:
            active = list(self._reqs)
        for req in active:
            try:
                # touch a leaf: a half-swapped tree (missing leaf, None
                # placeholder) would throw here and fail the request
                leaves: Dict[str, Any] = {}
                ds._flatten(self.params, "", leaves)
                next(iter(leaves.values())).ravel()[0]
                req["left"] -= 1
            except Exception as e:      # noqa: BLE001
                req["error"] = e
                req["left"] = 0
            if req["left"] <= 0:
                with self._lock:
                    if req in self._reqs:
                        self._reqs.remove(req)
                if self.feedback_sink is not None:
                    try:
                        self.feedback_sink({
                            "request_id": req.get("id"),
                            "generated": int(req.get("tokens", 0)),
                            "error": (str(req["error"])[:120]
                                      if req.get("error") else None),
                            "step": self.steps})
                    except Exception:  # noqa: BLE001 — never stall stepping
                        pass
                req["done"].set()
        self.steps += 1
        if self.step_s:
            time.sleep(self.step_s)
        with self._lock:
            return len(self._reqs)

    def _run(self) -> None:
        while not self._stop.is_set():
            n = self._step_once()
            if n == 0 and not self._hooks:
                self._work.clear()
                self._work.wait(timeout=0.1)

    def start(self) -> "HostEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="kt-host-engine")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
