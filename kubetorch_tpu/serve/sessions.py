"""Session → prefix-cache glue for the serving front door (ISSUE 9).

The engine-side half of affinity routing. The router
(``serving/router.py``) keeps a session sticky to one replica; this module
makes that stickiness *worth something* on the replica: a returning
session's conversation header is already resident as registered prefix
K/V, so only the new turn's suffix is prefilled.

Deliberately free of jax/engine imports at module level — the engine is
passed in — so the pod HTTP server process can import the session types
without pulling device runtimes into the wrong process.

Two pieces:

- :class:`SessionStats` / :func:`session_key` — the shared vocabulary
  (header name, key derivation) both halves agree on.
- :class:`EngineSessionBinder` — binds sessions to registered prefixes on
  a :class:`~kubetorch_tpu.serve.engine.GenerationEngine`, LRU-capped so
  resident prefixes (each pins ~2·L·P·NKV·Hd device bytes) can't grow
  without bound. Turn 1 pays one extra prefill to register the prompt;
  every later turn of the session prefills only its suffix.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

# The wire name both halves key on (re-exported for callers that only
# deal with the engine side). The router reads it off the incoming
# request; keyless calls fall back to well-known kwargs — see
# ``serving.router.affinity_key``, this function's routing-side twin.
from ..constants import SESSION_HEADER  # noqa: E402  (shared wire name)


def session_key(headers: Optional[Dict[str, str]] = None,
                kwargs: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Derive the affinity key for one call: the explicit session header
    wins; else well-known body kwargs (``session_id``, ``prefix_id``,
    ``adapter_id``) in that order — a request pinned to a cached prefix or
    a LoRA adapter benefits from landing where that state is resident even
    when the caller never named a session."""
    if headers:
        for name in (SESSION_HEADER, SESSION_HEADER.lower()):
            val = headers.get(name)
            if val:
                return str(val)
    if kwargs:
        for field_name in ("session_id", "session", "prefix_id",
                           "adapter_id"):
            val = kwargs.get(field_name)
            if val is not None:
                return f"{field_name}:{val}"
    return None


@dataclass
class SessionStats:
    sessions: int
    hits: int
    misses: int
    evictions: int

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class EngineSessionBinder:
    """Per-engine session residency: session id → registered prefix.

    ``submit(session_id, prompt, ...)`` strips the session's resident
    prefix off the prompt (suffix-only prefill — the prefix-cache win the
    router's affinity routing exists to compound) and, on first sight of a
    session, registers its prompt as the resident prefix for the next
    turn. ``advance=True`` rolls the resident prefix forward to each
    turn's full prompt (next turn's suffix is just the new text) at the
    cost of one extra registration prefill per turn; the default keeps the
    turn-1 header resident, which already covers the dominant
    system-prompt + few-shot share of multi-turn traffic.

    LRU-capped: the coldest session's prefix is unregistered (freeing its
    device K/V) when ``capacity`` is exceeded. Thread-safe — engines are
    driven from server executors and the engine loop concurrently.
    """

    def __init__(self, engine, capacity: int = 64, *,
                 advance: bool = False, min_prefix_tokens: int = 8):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.engine = engine
        self.capacity = int(capacity)
        self.advance = bool(advance)
        # below this length a registration costs more than it saves
        self.min_prefix_tokens = int(min_prefix_tokens)
        # session id → (prefix_id, tokens tuple, adapter_id)
        self._resident: "OrderedDict[str, Tuple[int, tuple, Any]]" = \
            OrderedDict()
        self._lock = threading.Lock()
        self._hits = self._misses = self._evictions = 0
        self.created_at = time.monotonic()

    # -- residency ----------------------------------------------------------

    def lookup(self, session_id: str, prompt: Sequence[int],
               adapter_id: Optional[int] = None):
        """(prefix_id, suffix) when the session's resident prefix is a
        proper prefix of ``prompt`` under the same adapter; (None, prompt)
        otherwise. Bumps LRU recency on hit."""
        prompt = list(prompt)
        with self._lock:
            entry = self._resident.get(session_id)
            if entry is None:
                return None, prompt
            pid, toks, aid = entry
            n = len(toks)
            if (aid == adapter_id and n < len(prompt)
                    and list(toks) == prompt[:n]):
                self._resident.move_to_end(session_id)
                return pid, prompt[n:]
            return None, prompt

    def _register(self, session_id: str, prompt: List[int],
                  adapter_id: Optional[int]) -> None:
        if len(prompt) < self.min_prefix_tokens:
            return
        try:
            pid = self.engine.register_prefix(prompt, adapter_id=adapter_id)
        except Exception:  # noqa: BLE001 — residency is an optimization;
            # a prompt the engine refuses (too long for max_len headroom)
            # must never fail the request that carried it
            return
        with self._lock:
            old = self._resident.pop(session_id, None)
            self._resident[session_id] = (pid, tuple(prompt), adapter_id)
            evict = []
            while len(self._resident) > self.capacity:
                _sid, (opid, _t, _a) = self._resident.popitem(last=False)
                evict.append(opid)
                self._evictions += 1
        if old is not None:
            self.engine.unregister_prefix(old[0])
        for opid in evict:
            self.engine.unregister_prefix(opid)

    def release(self, session_id: str) -> bool:
        """Drop a session's resident prefix (client disconnect, TTL)."""
        with self._lock:
            entry = self._resident.pop(session_id, None)
        if entry is None:
            return False
        self.engine.unregister_prefix(entry[0])
        return True

    # -- the submit path ----------------------------------------------------

    def submit(self, session_id: Optional[str], prompt: Sequence[int],
               adapter_id: Optional[int] = None, **kwargs):
        """``engine.submit`` with session-aware prefix reuse. A keyless
        call passes straight through. Returns the engine's handle."""
        if session_id is None:
            return self.engine.submit(prompt, adapter_id=adapter_id,
                                      **kwargs)
        prompt = [int(t) for t in prompt]
        pid, suffix = self.lookup(session_id, prompt, adapter_id)
        if pid is not None:
            with self._lock:
                self._hits += 1
            handle = self.engine.submit(suffix, prefix_id=pid,
                                        adapter_id=adapter_id, **kwargs)
            if self.advance:
                self._register(session_id, prompt, adapter_id)
            return handle
        with self._lock:
            self._misses += 1
            known = session_id in self._resident
        handle = self.engine.submit(prompt, adapter_id=adapter_id, **kwargs)
        # first sight (or a prompt that diverged from the resident prefix):
        # make THIS prompt resident so the session's next turn hits
        if not known or self.advance:
            self._register(session_id, prompt, adapter_id)
        return handle

    # -- introspection ------------------------------------------------------

    def resident_sessions(self) -> List[str]:
        with self._lock:
            return list(self._resident)

    def stats(self) -> SessionStats:
        with self._lock:
            return SessionStats(sessions=len(self._resident),
                                hits=self._hits, misses=self._misses,
                                evictions=self._evictions)

    def __kt_metrics__(self) -> Dict[str, float]:
        """Pod-scrape hook merge (same contract as the engine's): session
        residency and hit rate on ``/metrics`` under ``kt_user_``."""
        s = self.stats()
        out = {"sessions_resident": float(s.sessions),
               "session_prefix_hits": float(s.hits),
               "session_prefix_misses": float(s.misses),
               "session_prefix_hit_rate": float(s.hit_rate),
               "session_evictions": float(s.evictions)}
        inner = getattr(self.engine, "__kt_metrics__", None)
        if inner is not None:
            out.update(inner())
        return out
