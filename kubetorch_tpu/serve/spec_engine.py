"""Speculative continuous batching: the slot-grid engine with a draft.

``speculative_generate`` (serve/speculative.py) speculates ONE request;
``GenerationEngine`` batches many requests but decodes one token per slot
per step. This engine does both at once: every round, a draft model
proposes ``k`` tokens for EVERY active slot, and one target forward scores
all slots' pending+proposal windows together — so each target
weight-stream yields 1..k+1 tokens per slot, across the whole grid.

The shapes stay static (the engine's contract): the draft ingests a
(SLOTS, k+1) block of per-slot pending tokens, proposes via k-1 grid
decode steps, and the target verifies a (SLOTS, 2k+1) block — per-slot
true lengths ride as traced vectors, so mixed progress (a slot that
accepted everything beside one that accepted nothing, idle slots at
length 0) shares one compile. Rows past a slot's frontier hold stale
garbage by design: every round writes its rows BEFORE attending and the
per-slot causal mask never admits an unwritten row — the same position
ledger the standalone implementation proves (speculative.py docstring).

Greedy verification is EXACT per slot: each request's emitted stream is
bit-identical to the target's own greedy decode of that prompt, whatever
the draft proposes and whatever the neighbors do — the oracle
``tests/test_spec_engine.py`` asserts, for dense AND MoE targets (MoE
windows route drop-free like the standalone; the prefill mirrors the
oracle's real-length capacity).

Reference analog: none — beyond-parity serving, docs/serving.md.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import telemetry

from ..models.generate import KVCache, ffn_block, init_cache, rope_freqs
from ..models.llama import rmsnorm
from ..models.quant import dequant_layer, lm_head_dot, wdot
from .engine import (GenerationEngine, _decode_block, _prefill,
                     _prefill_suffix, _splice_slot)
from .speculative import SpecStats

NEG_INF = -1e30


def _rope_grid(x: jax.Array, freqs: jax.Array) -> jax.Array:
    """RoPE with per-(slot, offset) rotations: x (B, W, N, Hd), freqs
    (B, W, Hd/2) complex — the grid generalization of ``_rope_slot``."""
    b, w, n, hd = x.shape
    xf = x.astype(jnp.float32).reshape(b, w, n, hd // 2, 2)
    xc = lax.complex(xf[..., 0], xf[..., 1])
    rotated = xc * freqs[:, :, None, :]
    out = jnp.stack([jnp.real(rotated), jnp.imag(rotated)], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


@partial(jax.jit, static_argnames=("cfg", "s_eff", "lora_scale"),
         donate_argnums=(1,))
def _grid_ingest(params, cache, blocks, start, true_len, cfg,
                 s_eff: Optional[int] = None, banks=None, aidx=None,
                 lora_scale: float = 1.0):
    """Run a (B, W) token window through the model, each slot at its own
    absolute positions ``start[b] + i``, writing cache rows and returning
    fp32 logits for EVERY window position (B, W, V).

    ``true_len`` (B,) marks each slot's real tokens: padding (and wholly
    idle slots at true_len 0) writes garbage rows past the frontier that a
    later round overwrites before the mask can admit them, and never
    claims MoE expert capacity (token_mask + no_drop routing — each real
    token routes exactly as it would alone, the T=1 oracle).

    ``s_eff`` (static) bounds the attended cache rows: the causal mask
    never admits a row past ``max(start) + W``, so the caller passes that
    frontier rounded up to a power-of-two bucket and the attention einsums
    stream ``s_eff`` rows instead of all ``S_max`` — the frontier-skip the
    flash-decode kernel gives the T=1 path, as a static slice here (one
    compile per bucket, a handful over a request's lifetime).

    The layer body is deliberately specialized (three position shapes live
    in this codebase: (T,) scanned generate, (B,) slot decode, (B, W)
    here) — divergence from ``generate``'s semantics is pinned by the
    bit-exactness oracles in tests/test_spec_engine.py, which fail on ANY
    drift in norm/RoPE/cache/MoE behavior.

    ``cache`` may be a fp ``KVCache`` or an int8 ``QuantKVCache``
    (``serve.kv_quant``) — the pytree structure keys the jit. The quant
    branch quantizes new rows before writing and folds the row scales
    into the attention f32 einsums (logits columns ·ks, probs ·vs) — the
    same reference math as ``engine._decode_layer_quant``, so the verify
    window attends bit-compatibly with the T=1 decode it must match."""
    from .kv_quant import QuantKVCache, quantize_rows
    quant = isinstance(cache, QuantKVCache)
    b, w = blocks.shape
    s_max = cache.kq.shape[2] if quant else cache.k.shape[2]
    if s_eff is None:
        s_eff = s_max
    x = params["embed"][blocks].astype(cfg.dtype)
    posm = start[:, None] + jnp.arange(w)[None, :]          # (B, W)
    freqs_full = rope_freqs(cfg, s_max)
    freqs = freqs_full[posm]                                 # (B, W, Hd/2)
    token_mask = jnp.arange(w)[None, :] < true_len[:, None]  # (B, W)
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    group = nh // nkv
    bi = jnp.arange(b)[:, None]

    from ..models.lora import gather_slot_adapters, lora_proj

    def make_lora(bank_l):
        # the SAME gather the plain decode step uses (shared helper — the
        # bank layout / zero-adapter convention cannot drift)
        return gather_slot_adapters(bank_l, aidx, lora_scale, banks)

    def proj_qkv(lw, h, lora):
        hn = rmsnorm(h, lw["attn_norm"], cfg.norm_eps)
        q = lora_proj(hn, lw["wq"], lora, "wq").reshape(b, w, nh, hd)
        k = lora_proj(hn, lw["wk"], lora, "wk").reshape(b, w, nkv, hd)
        v = lora_proj(hn, lw["wv"], lora, "wv").reshape(b, w, nkv, hd)
        return _rope_grid(q, freqs), _rope_grid(k, freqs), v

    def finish(lw, h, attn, lora):
        h = h + lora_proj(attn, lw["wo"], lora, "wo")
        hn = rmsnorm(h, lw["ffn_norm"], cfg.norm_eps)
        return h + ffn_block(cfg, hn, lw, token_mask=token_mask,
                             moe_no_drop=True)

    def win_mask():
        return (jnp.arange(s_eff)[None, None, :]
                <= posm[:, :, None])                        # (B, W, S_eff)

    if quant:
        def body(carry, layer):
            lw, kq, ks, vq, vs, bank_l = layer
            lw = dequant_layer(lw, cfg.dtype)
            lora = make_lora(bank_l)
            h = carry
            q, k, v = proj_qkv(lw, h, lora)
            k_row, ks_row = quantize_rows(k)
            v_row, vs_row = quantize_rows(v)
            kq = kq.at[bi, posm].set(k_row)
            ks = ks.at[bi, posm].set(ks_row)
            vq = vq.at[bi, posm].set(v_row)
            vs = vs.at[bi, posm].set(vs_row)
            kq_a = lax.slice_in_dim(kq, 0, s_eff, axis=1)
            ks_a = lax.slice_in_dim(ks, 0, s_eff, axis=1)
            vq_a = lax.slice_in_dim(vq, 0, s_eff, axis=1)
            vs_a = lax.slice_in_dim(vs, 0, s_eff, axis=1)
            qg = q.reshape(b, w, nkv, group, hd).astype(jnp.float32)
            logits = jnp.einsum("bwkgh,bskh->bkgws", qg,
                                kq_a.astype(jnp.float32)) * (hd ** -0.5)
            # fold the K row scales over the S axis: ks_a (B, S, NKV)
            logits = logits * ks_a.transpose(0, 2, 1)[:, :, None, None, :]
            logits = jnp.where(win_mask()[:, None, None], logits, NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1)
            probs = probs * vs_a.transpose(0, 2, 1)[:, :, None, None, :]
            attn = jnp.einsum("bkgws,bskh->bwkgh", probs,
                              vq_a.astype(jnp.float32)).reshape(
                                  b, w, nh * hd).astype(h.dtype)
            return finish(lw, h, attn, lora), (kq, ks, vq, vs)

        x, leaves = lax.scan(body, x, (params["layers"], cache.kq,
                                       cache.ks, cache.vq, cache.vs,
                                       banks or {}))
        new_cache = QuantKVCache(*leaves)
    else:
        def body(carry, layer):
            lw, ck, cv, bank_l = layer
            lw = dequant_layer(lw, cfg.dtype)
            lora = make_lora(bank_l)
            h = carry
            q, k, v = proj_qkv(lw, h, lora)
            ck = ck.at[bi, posm].set(k.astype(ck.dtype))
            cv = cv.at[bi, posm].set(v.astype(cv.dtype))
            ck_a = lax.slice_in_dim(ck, 0, s_eff, axis=1)
            cv_a = lax.slice_in_dim(cv, 0, s_eff, axis=1)
            qg = q.reshape(b, w, nkv, group, hd)
            logits = jnp.einsum("bwkgh,bskh->bkgws", qg,
                                ck_a).astype(jnp.float32) * (hd ** -0.5)
            logits = jnp.where(win_mask()[:, None, None], logits, NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1).astype(cv.dtype)
            attn = jnp.einsum("bkgws,bskh->bwkgh", probs,
                              cv_a).reshape(b, w, nh * hd)
            return finish(lw, h, attn, lora), (ck, cv)

        x, (nk, nv) = lax.scan(body, x, (params["layers"], cache.k,
                                         cache.v, banks or {}))
        new_cache = KVCache(nk, nv)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = lm_head_dot(x, params, cfg.dtype)
    return logits, new_cache


class SpeculativeEngine(GenerationEngine):
    """Continuous batching with per-slot speculative decoding (module
    docstring has the design). Greedy-only — the exactness proof is the
    argmax acceptance rule; sampled speculation needs rejection sampling
    and is out of scope. int8 KV composes (``quantize_kv=True`` — the
    TARGET cache quantizes; the draft stays fp, its cache is small), and
    so does multi-LoRA (per-request ``adapter_id``: the target's window
    forwards gather each slot's adapter while the draft proposes from
    base weights — proposal quality only, never tokens), and so does
    prefix caching (``register_prefix`` prefills BOTH models' prefixes;
    admission splices each into its own grid), and chunked prefill
    (``prefill_chunk`` — both accumulators advance one chunk per step).
    Tensor/data meshes work GSPMD-sharded like the plain engine; a CONTEXT axis is also correct here but the window forwards
    have no per-shard combine yet, so the cache won't stay
    sequence-sharded — context-sharded serving is the plain engine's
    feature (``sp_decode_attention``)."""

    def __init__(self, params: Dict[str, Any], cfg,
                 draft_params: Dict[str, Any], draft_cfg, *, spec_k: int = 4,
                 spec_k_min: Optional[int] = None,
                 spec_k_max: Optional[int] = None,
                 spec_adapt_every: int = 4, **kwargs):
        if kwargs.get("temperature", 0.0) != 0.0:
            raise ValueError("SpeculativeEngine is greedy-only "
                             "(temperature=0); use GenerationEngine for "
                             "sampled serving")
        if kwargs.get("top_p") is not None:
            raise ValueError("top_p requires sampling — SpeculativeEngine "
                             "is greedy-only; use GenerationEngine")
        if kwargs.get("decode_block", 1) != 1:
            raise ValueError("decode_block tunes GenerationEngine's plain "
                             "decode loop; a speculation round already "
                             "batches its device work — use spec_k")
        if kwargs.get("auto_prefix"):
            # the verify-window headroom check runs in submit() BEFORE the
            # base engine would auto-match a prefix — an auto-matched
            # bucket could push the speculation window past max_len
            raise ValueError("auto_prefix is not supported with "
                             "speculation — pass prefix_id explicitly")
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        super().__init__(params, cfg, **kwargs)
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.k = int(spec_k)
        # Adaptive draft length (ISSUE 12 satellite): `k` is a *bet* on the
        # draft's acceptance rate, and a static bet is wrong in both
        # directions — a well-aligned draft wastes target weight-streams on
        # too-short windows, a misaligned one burns k draft decodes per
        # emitted token. An acceptance-rate EWMA shrinks/grows k within
        # [k_min, k_max] (env KT_SPEC_K_MIN/KT_SPEC_K_MAX or kwargs; both
        # default to spec_k, i.e. adaptation off unless bounds are widened).
        # Each distinct k is its own compile of the window forwards — the
        # bounds cap that to a handful, like the s_eff buckets.
        env_min = os.environ.get("KT_SPEC_K_MIN")
        env_max = os.environ.get("KT_SPEC_K_MAX")
        self.k_min = int(spec_k_min if spec_k_min is not None
                         else (env_min or self.k))
        self.k_max = int(spec_k_max if spec_k_max is not None
                         else (env_max or self.k))
        if not (1 <= self.k_min <= self.k <= self.k_max):
            raise ValueError(
                f"need 1 <= k_min ({self.k_min}) <= spec_k ({self.k}) <= "
                f"k_max ({self.k_max})")
        self._adapt_every = max(1, int(spec_adapt_every))
        self._rounds_since_adapt = 0
        self._accept_ewma: Optional[float] = None
        self._draft_cache = init_cache(draft_cfg, self.slots, self.max_len)
        # per-slot ledgers: rows both caches validly cover, and the tokens
        # emitted but not yet ingested (1..k+1 long while active).
        # NB: self._pending is the BASE class's request queue — the token
        # ledger gets its own name
        self._spec_valid = np.zeros(self.slots, np.int32)
        self._slot_pending: List[List[int]] = [[] for _ in range(self.slots)]
        # pid → (draft prefix K, V) — the target's tuples live in the base
        # self._prefixes; widths are trimmed to match
        self._draft_prefixes: Dict[int, tuple] = {}
        self.spec_stats = SpecStats()

    # -- unsupported registrations refused at REGISTRATION time, before
    # they commit device memory no request could ever use ------------------

    # register_adapter/unregister_adapter: the BASE implementations — the
    # bank/aidx machinery is shared; the target's window forwards gather
    # per-slot adapters exactly like the plain decode step

    def register_prefix(self, tokens: Sequence[int],
                        adapter_id: Optional[int] = None) -> int:
        """Prefix caching under speculation: the TARGET's prefix K/V comes
        from the base machinery; the DRAFT (its own model) prefills the
        same tokens through its own weights — both caches splice their
        prefix at admission, at the same bucket widths (shared bucket
        table), so the position ledgers stay aligned."""
        pid = super().register_prefix(tokens, adapter_id)   # validates
        with self._mesh_scope():
            pk = self._prefixes[pid][0]
            t = len(tokens)
            # pad straight to the TARGET's stored width: one source of
            # truth for the bucket/trim policy (the base), and the two
            # models' prefix widths cannot desynchronize
            padded = np.zeros((1, pk.shape[2]), np.int32)
            padded[0, :t] = [int(x) for x in tokens]
            _f, dk, dv, _lp = _prefill(
                self.draft_params, jnp.asarray(padded), jnp.int32(t),
                self._next_key(), jnp.zeros((1,), jnp.float32),
                self.draft_cfg)
            self._draft_prefixes[pid] = (dk, dv)
        return pid

    def unregister_prefix(self, prefix_id: int) -> bool:
        self._draft_prefixes.pop(prefix_id, None)
        return super().unregister_prefix(prefix_id)

    # -- submission ---------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 64,
               temperature: Optional[float] = None,
               prefix_id: Optional[int] = None,
               adapter_id: Optional[int] = None,
               top_p: Optional[float] = None,
               frequency_penalty: float = 0.0,
               presence_penalty: float = 0.0,
               stop: Optional[Sequence] = None,
               logit_bias=None, seed=None):
        if temperature not in (None, 0.0):
            raise ValueError("SpeculativeEngine is greedy-only")
        if top_p is not None:
            raise ValueError("top_p requires sampling — SpeculativeEngine "
                             "is greedy-only; use GenerationEngine")
        if frequency_penalty or presence_penalty:
            # penalties change even the greedy argmax, which would break
            # the exact-verification acceptance rule (target argmax is
            # computed penalty-free in the verify window)
            raise ValueError("repetition penalties are not supported with "
                             "speculation — use GenerationEngine")
        if logit_bias:
            # same argmax-steering problem as penalties
            raise ValueError("logit_bias is not supported with "
                             "speculation — use GenerationEngine")
        if seed is not None:
            raise ValueError("seed is meaningless for greedy speculation "
                             "(deterministic already) — use "
                             "GenerationEngine for sampled serving")
        prompt = [int(t) for t in prompt]
        p_bucket = 0
        if prefix_id is not None:
            pref = self._prefixes.get(prefix_id)
            if pref is None:
                raise KeyError(f"unknown prefix_id {prefix_id}")
            p_bucket = pref[0].shape[2]
        # the verify window writes up to 2k+1 rows past the last emitted
        # token — reserve headroom for the LARGEST k adaptation may pick,
        # so a later grow can never push a seated request out of bounds
        if (prompt and max_new_tokens >= 1
                and p_bucket + len(prompt) + max_new_tokens
                + 2 * self.k_max + 1 > self.max_len):
            raise ValueError(
                f"prefix bucket ({p_bucket}) + prompt ({len(prompt)}) + "
                f"max_new_tokens ({max_new_tokens}) + verify window "
                f"({2 * self.k_max + 1}) exceeds max_len ({self.max_len})")
        # stop sequences work unchanged: emission goes through the shared
        # _emit suffix check, and speculation is exact-greedy so stopping
        # early never changes the tokens that were already emitted
        return super().submit(prompt, max_new_tokens, stop=stop,
                              adapter_id=adapter_id, prefix_id=prefix_id)

    # -- admission ----------------------------------------------------------

    def _admit_one(self, req, slot: int) -> None:
        pref = self._resolve_prefix(req)
        t = len(req.prompt)
        temps = jnp.zeros((1,), jnp.float32)
        adapter, aidx = self._resolve_adapter(req.adapter_id)
        lkw = ({"adapter": adapter, "lora_scale": self._lora_cfg.scale}
               if adapter is not None else {})
        if req.prefix_id is not None:
            # both models continue behind their OWN cached prefix, at the
            # same widths (registration pads the draft to the target's).
            # Fetch the draft half ONCE: an unregister racing admission
            # must fail this request cleanly, not half-resolve
            pk, pv, p_real, _toks, _pad = pref
            dpref = self._draft_prefixes.get(req.prefix_id)
            if dpref is None:
                raise KeyError(f"unknown prefix_id {req.prefix_id}")
            dk_p, dv_p = dpref
            p_bucket = pk.shape[2]
            bucket = next((b for b in self._buckets if b >= t
                           and p_bucket + b <= self.max_len), None)
            if bucket is None:
                bucket = self.max_len - p_bucket
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :t] = req.prompt
            block = jnp.asarray(padded)
            first, k_new, v_new, _flp = _prefill_suffix(
                self.params, block, jnp.int32(t), pk, pv,
                jnp.int32(p_real), self._next_key(), temps, self.cfg,
                **lkw)
            _f2, dk, dv, _dlp = _prefill_suffix(
                self.draft_params, block, jnp.int32(t), dk_p, dv_p,
                jnp.int32(p_real), self._next_key(), temps,
                self.draft_cfg)
            start = int(p_real) + t
            self._prefix_hits += 1
        else:
            bucket = next(b for b in self._buckets if b >= t)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :t] = req.prompt
            block = jnp.asarray(padded)
            first, k_new, v_new, _flp = _prefill(
                self.params, block, jnp.int32(t), self._next_key(), temps,
                self.cfg, **lkw)
            # the draft prefills the same prompt into ITS grid (its
            # first-token sample is discarded — the target owns every
            # emitted token)
            _f2, dk, dv, _dlp = _prefill(
                self.draft_params, block, jnp.int32(t), self._next_key(),
                temps, self.draft_cfg)
            start = t
        self._seat(req, slot, first, k_new, v_new, dk, dv, start, aidx)

    def _seat(self, req, slot, first, k_new, v_new, dk, dv, start,
              aidx) -> None:
        """Post-prefill seating shared by one-shot and chunked admission:
        splice BOTH caches, set the speculation ledgers, re-check the
        adapter mapping, emit the first (target-sampled) token."""
        self._cache = _splice_slot(self._cache, jnp.int32(slot),
                                   k_new, v_new)
        self._draft_cache = _splice_slot(self._draft_cache, jnp.int32(slot),
                                         dk, dv)
        first_tok = int(first[0])
        self._slot_req[slot] = req
        with self._lock:
            # the base engine's stale-index re-check: an adapter evicted
            # during the prefill must fall back to base, never to a
            # reused bank index
            if (req.adapter_id is not None
                    and self._adapter_slots.get(req.adapter_id) != aidx):
                aidx = 0
            self._aidx[slot] = aidx
        self._spec_valid[slot] = start
        self._slot_pending[slot] = [first_tok]
        self._admitted += 1
        # a retirement on this first token clears the ledgers through the
        # shared _retire_slot → _free_slot_ledgers path
        self._emit(slot, first_tok)

    # -- chunked prefill (both models) --------------------------------------

    def _start_chunking(self, req, slot: int) -> None:
        """First chunk of a long admission, for BOTH models: two
        max_len-capacity accumulators advance in lockstep (the base
        engine's single-accumulator scheme, doubled)."""
        pref = self._resolve_prefix(req)
        adapter, aidx = self._resolve_adapter(req.adapter_id)
        lkw = ({"adapter": adapter, "lora_scale": self._lora_cfg.scale}
               if adapter is not None else {})
        c = self.prefill_chunk
        zero_t = jnp.zeros((1,), jnp.float32)
        if req.prefix_id is not None:
            pk, pv, p_real, _toks, _pad = pref
            dpref = self._draft_prefixes.get(req.prefix_id)
            if dpref is None:
                raise KeyError(f"unknown prefix_id {req.prefix_id}")
            tk, tv = pk, pv
            dk, dv = dpref
            self._prefix_hits += 1
            consumed, frontier = 0, int(p_real)
        else:
            toks = req.prompt[:c]
            padded = np.zeros((1, c), np.int32)
            padded[0, :] = toks
            block = jnp.asarray(padded)
            _f, tk, tv, _lp = _prefill(
                self.params, block, jnp.int32(c), self._dummy_key, zero_t,
                self.cfg, **lkw)
            _f2, dk, dv, _lp2 = _prefill(
                self.draft_params, block, jnp.int32(c), self._dummy_key,
                zero_t, self.draft_cfg)
            consumed = frontier = c

        def widen(arr):
            pad_w = self.max_len - arr.shape[2]
            spec = [(0, 0)] * arr.ndim
            spec[2] = (0, pad_w)
            return jnp.pad(arr, spec)

        self._chunking = (req, slot, widen(tk), widen(tv), widen(dk),
                          widen(dv), consumed, frontier, lkw, aidx)

    def _chunk_step(self) -> None:
        (req, slot, tk, tv, dk, dv, consumed, frontier,
         lkw, aidx) = self._chunking
        if req.cancelled:
            self._chunking = None
            req.out.put(None)
            return
        c = self.prefill_chunk
        rest = len(req.prompt) - consumed
        take = min(c, rest)
        padded = np.zeros((1, c), np.int32)
        padded[0, :take] = req.prompt[consumed:consumed + take]
        block = jnp.asarray(padded)
        zero_t = jnp.zeros((1,), jnp.float32)
        last = take == rest
        try:
            key = (self._next_key() if last else self._dummy_key)
            first, tk, tv, _lp = _prefill_suffix(
                self.params, block, jnp.int32(take), tk, tv,
                jnp.int32(frontier), key, zero_t, self.cfg, **lkw)
            _f2, dk, dv, _lp2 = _prefill_suffix(
                self.draft_params, block, jnp.int32(take), dk, dv,
                jnp.int32(frontier), self._dummy_key, zero_t,
                self.draft_cfg)
            if not last:
                self._chunking = (req, slot, tk[:, :, :self.max_len],
                                  tv[:, :, :self.max_len],
                                  dk[:, :, :self.max_len],
                                  dv[:, :, :self.max_len],
                                  consumed + take, frontier + take,
                                  lkw, aidx)
                return
            self._chunking = None
            self._seat(req, slot, first, tk[:, :, :self.max_len],
                       tv[:, :, :self.max_len], dk[:, :, :self.max_len],
                       dv[:, :, :self.max_len], frontier + take, aidx)
        except Exception as e:   # noqa: BLE001 — fail THIS request only
            self._chunking = None
            req.error = e
            req.out.put(None)

    # -- the speculative round ----------------------------------------------

    def _free_slot_ledgers(self, slot: int) -> None:
        self._slot_pending[slot] = []
        self._spec_valid[slot] = 0

    def step(self) -> int:
        with self._mesh_scope():
            self._reap_cancelled()
            self._admit()
            active = [i for i, r in enumerate(self._slot_req)
                      if r is not None]
            if active:
                self._round(active)
        with self._lock:
            queued = len(self._pending)
        # a mid-chunked-admission request is neither seated nor pending —
        # count it so drive loops don't stop with work in flight (the
        # base _step_once has the same term)
        return (sum(r is not None for r in self._slot_req) + queued
                + (1 if self._chunking is not None else 0))

    def _round(self, active: List[int]) -> None:
        b, k = self.slots, self.k
        wd, wt = k + 1, 2 * k + 1
        c = np.zeros(b, np.int32)
        for i in active:
            c[i] = len(self._slot_pending[i])
        start = self._spec_valid.astype(np.int32).copy()
        # static frontier bucket: no slot attends a row past its own
        # start + W, so both window forwards stream s_eff rows, not S_max
        # (a power-of-two bucket bounds compiles to a handful)
        need = int(start[active].max()) + wt
        s_eff = self.max_len
        while s_eff // 2 >= need and s_eff > 1:
            s_eff //= 2

        # draft: ingest each slot's pending block, then propose greedily
        # (temps 0 ⇒ argmax) — the first proposal from the ingest logits,
        # the remaining k-1 from one scanned decode block below
        dblock = np.zeros((b, wd), np.int32)
        for i in active:
            dblock[i, :c[i]] = self._slot_pending[i]
        dlog, self._draft_cache = _grid_ingest(
            self.draft_params, self._draft_cache, jnp.asarray(dblock),
            jnp.asarray(start), jnp.asarray(c), self.draft_cfg,
            s_eff=s_eff)
        last = np.clip(c - 1, 0, wd - 1)
        tok = jnp.argmax(dlog[jnp.arange(b), last],
                         axis=-1).astype(jnp.int32)
        zeros = jnp.zeros(b, jnp.float32)
        if k > 1:
            # all k-1 remaining proposals in ONE dispatch: the scanned
            # decode block returns the stacked per-step tokens, so the
            # whole draft phase costs two device round-trips (ingest +
            # block) instead of k. Greedy (temps 0) ⇒ the key is unused.
            self._draft_cache, _fp, _ft, toks_k, _lps, _cnt = _decode_block(
                self.draft_params, self._draft_cache,
                jnp.asarray(start + c), tok, self._dummy_key, zeros,
                self.draft_cfg, n_steps=k - 1)
            # (B, k) = first proposal + the block's (k-1, B) transposed
            proposals = np.concatenate(
                [np.asarray(tok)[:, None], np.asarray(toks_k).T], axis=1)
        else:
            proposals = np.asarray(tok)[:, None]          # (B, 1)

        # target: one forward over pending+proposals for every slot
        tblock = np.zeros((b, wt), np.int32)
        tl = np.zeros(b, np.int32)
        for i in active:
            tblock[i, :c[i]] = self._slot_pending[i]
            tblock[i, c[i]:c[i] + k] = proposals[i]
            tl[i] = c[i] + k
        with self._lock:
            banks = self._banks
        lkw = ({"banks": banks, "aidx": jnp.asarray(self._aidx),
                "lora_scale": self._lora_cfg.scale} if banks else {})
        tlog, self._cache = _grid_ingest(
            self.params, self._cache, jnp.asarray(tblock),
            jnp.asarray(start), jnp.asarray(tl), self.cfg, s_eff=s_eff,
            **lkw)
        greedy = np.asarray(jnp.argmax(tlog, axis=-1))   # (B, WT)
        self._steps += 1

        round_accepted = 0
        for i in active:
            ci = int(c[i])
            accepted = 0
            while (accepted < k
                   and proposals[i, accepted] == greedy[i, ci - 1 + accepted]):
                accepted += 1
            correction = int(greedy[i, ci - 1 + accepted])
            emitted = [int(t) for t in proposals[i, :accepted]] + [correction]
            sent = 0
            for t in emitted:
                self._emit(i, t)
                sent += 1
                if self._slot_req[i] is None:
                    break
            self.spec_stats.rounds += 1
            self.spec_stats.proposed += k
            # count only acceptances that were EMITTED: matches past a
            # retirement point (budget/eos) are comparisons against the
            # target's post-stream continuation, and counting them would
            # flatter acceptance_rate for exactly the requests that end
            self.spec_stats.accepted += min(accepted, sent)
            round_accepted += min(accepted, sent)
            # a slot retired during emission had its ledgers cleared by
            # _retire_slot → _free_slot_ledgers; only live slots advance
            if self._slot_req[i] is not None:
                self._spec_valid[i] = start[i] + ci
                self._slot_pending[i] = emitted
        self._note_round(round_accepted, len(active) * k)

    def _note_round(self, accepted: int, proposed: int) -> None:
        """Acceptance-rate EWMA → draft-length adaptation (ISSUE 12
        satellite). Grows ``k`` while the draft keeps earning its windows
        (EWMA ≥ 0.8), shrinks it when more than half the proposals are
        wasted draft decodes (EWMA ≤ 0.5); the 0.5–0.8 band is hysteresis.
        At most one ±1 move per ``spec_adapt_every`` rounds, bounded by
        [k_min, k_max] — the bounds also cap how many window-shape
        compiles adaptation can ever trigger."""
        if not proposed:
            return
        rate = accepted / proposed
        self._accept_ewma = (rate if self._accept_ewma is None
                             else 0.8 * self._accept_ewma + 0.2 * rate)
        gauges = telemetry.spec_metrics()
        gauges["accept_rate"].set(self._accept_ewma)
        gauges["draft_len"].set(self.k)
        if self.k_min == self.k_max:
            return
        self._rounds_since_adapt += 1
        if self._rounds_since_adapt < self._adapt_every:
            return
        self._rounds_since_adapt = 0
        if self._accept_ewma >= 0.8 and self.k < self.k_max:
            self.k += 1
        elif self._accept_ewma <= 0.5 and self.k > self.k_min:
            self.k -= 1
        gauges["draft_len"].set(self.k)
