"""Speculative decoding: a small draft model proposes, the target verifies.

Decode spends one full weight-stream per token; a draft model K× smaller
proposes ``k`` tokens autoregressively and the target scores all of them in
ONE forward — so each target weight-stream yields 1..k+1 tokens. Greedy
verification is EXACT: a proposal is accepted only while it equals the
target's own argmax, so the emitted stream is bit-identical to plain greedy
decode of the target (the oracle the tests assert). The win is the
acceptance rate; the worst case costs one extra draft pass per token.

TPU-first shapes: both models keep fixed ``max_len`` caches; every round
runs two static-width jits — the draft ingests the previous round's
accepted block (padded to ``k+1``) then proposes ``k`` single steps inside
a ``lax.scan``; the target ingests block+proposals (padded to ``2k+1``)
and returns per-position logits. Rows past the valid frontier hold stale
garbage by design: every forward writes its rows BEFORE attending, and the
causal mask never admits a row at a position not yet written — the same
invariant the slot-grid engine relies on.

MoE decoders keep the same bit-exactness: the oracle decodes T=1, where a
token's K chosen experts can never overflow a capacity slot — so draft and
verify windows route with ``no_drop`` expert buffers (capacity = window
width, ``models.moe.moe_ffn``), making every window token route exactly as
it would alone. The prompt prefill instead mirrors the oracle's own
prefill: real-length capacity threshold over the padded bucket (the same
``keep_capacity`` contract bucketed engine prefill uses).

Reference analog: none (serving optimization is user code there) — part of
the beyond-parity serving stack, docs/serving.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.generate import (KVCache, _layer_step, init_cache, rope_freqs)
from ..models.llama import rmsnorm
from ..models.quant import lm_head_dot


@partial(jax.jit, static_argnames=("cfg", "logits", "no_drop"),
         donate_argnums=(1,))
def _ingest(params, cache: KVCache, block, start, true_len, cfg,
            logits: str = "all", keep_capacity=None, no_drop: bool = False):
    """Run ``block`` (1, W) of tokens at absolute positions ``start + i``
    through the model, writing their K/V rows (cache donated — the caller
    never reuses the old one). ``logits`` picks what the head computes:
    "all" → fp32 (1, W, V) for every position (the verify round needs
    them; W ≤ 2k+1 so it's cheap), "last" → (1, V) at ``true_len - 1``
    only (prompt prefill: a W×V tensor for a long prompt would be GBs),
    "none" → None (the draft's prompt ingest only needs the cache).
    Positions at and past ``true_len`` are padding — their logits are
    garbage the caller must ignore, and their rows are either overwritten
    by a later round before they can be attended, or masked off.

    MoE routing semantics per window kind: mid-stream windows pass
    ``no_drop=True`` (each token routes as if decoded alone — the T=1
    oracle); the prompt window passes the real length's overflow
    threshold as ``keep_capacity`` (the oracle's own prefill pressure).
    Both are no-ops for dense configs."""
    b, w = block.shape
    x = params["embed"][block].astype(cfg.dtype)
    freqs_full = rope_freqs(cfg, cache.k.shape[2])
    q_pos = start + jnp.arange(w)
    token_mask = (jnp.arange(w) < true_len)[None, :]

    def body(carry, layer):
        lw, ck, cv = layer
        h, ck, cv = _layer_step(cfg, carry, lw, ck, cv, q_pos, freqs_full,
                                token_mask=token_mask,
                                keep_capacity=keep_capacity,
                                moe_no_drop=no_drop)
        return h, (ck, cv)

    x, (nk, nv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    if logits == "none":
        return None, KVCache(nk, nv)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    if logits == "last":
        h_last = x[jnp.arange(b), true_len - 1]
        return lm_head_dot(h_last, params, cfg.dtype), KVCache(nk, nv)
    out = lm_head_dot(x, params, cfg.dtype)
    return out, KVCache(nk, nv)


@partial(jax.jit, static_argnames=("cfg", "k"), donate_argnums=(1,))
def _draft_propose(params, cache: KVCache, block, start, true_len, cfg,
                   k: int):
    """Draft round: ingest the accepted block, then greedily propose ``k``
    tokens with single-step decodes inside a scan. Returns (proposals (k,),
    cache'). The proposal steps write rows ``start+true_len …
    start+true_len+k-2`` (the k-th proposal is never ingested — the next
    round's block carries whatever survives verification)."""
    logits, cache = _ingest(params, cache, block, start, true_len, cfg,
                            no_drop=True)
    first = jnp.argmax(logits[0, true_len - 1]).astype(jnp.int32)

    def step(carry, i):
        cache, tok = carry
        lg, cache = _ingest(params, cache, tok[None, None],
                            start + true_len + i, jnp.int32(1), cfg,
                            no_drop=True)
        nxt = jnp.argmax(lg[0, 0]).astype(jnp.int32)
        return (cache, nxt), nxt

    (cache, _), rest = lax.scan(step, (cache, first), jnp.arange(k - 1))
    return jnp.concatenate([first[None], rest]), cache


@dataclass
class SpecStats:
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


def speculative_generate(target_params, target_cfg, draft_params, draft_cfg,
                         prompt, max_new_tokens: int = 64, k: int = 4,
                         max_len: Optional[int] = None,
                         prompt_buckets: Sequence[int] = (64, 256, 1024,
                                                         4096),
                         stats: Optional[SpecStats] = None) -> List[int]:
    """Greedy speculative decoding; returns the generated tokens (prompt
    excluded) — bit-identical to ``generate(target_params, …)`` greedy.

    ``draft_cfg``/``target_cfg`` must share the vocabulary; ``k`` proposals
    per round. Pass a ``SpecStats`` to read the acceptance rate (the
    realized speedup is roughly ``(1 + accepted/rounds)`` target streams
    amortized per token).

    Compile behavior: each jit is keyed on the CACHE length and block
    widths. A server should pin ``max_len`` (one compile set per model
    pair) — the default derives it from the request and recompiles per
    distinct prompt/new-token budget. Prompts pad to ``prompt_buckets``
    so prompt-length variety alone never recompiles."""
    prompt = [int(t) for t in prompt]
    if not prompt:
        raise ValueError("empty prompt")
    p = len(prompt)
    p_bucket = next((b for b in sorted(prompt_buckets) if b >= p), p)
    # The cache must hold the FULL padded windows past the last valid row:
    # dynamic_update_slice CLAMPS an out-of-bounds start, which would
    # silently shift padding writes onto history rows and corrupt them —
    # reserve the padded prompt AND prompt + new + (2k+1) verify rows.
    total_cap = max(p_bucket, p + max_new_tokens + 2 * k + 1)
    if max_len is None:
        max_len = total_cap
    if max_len < total_cap:
        raise ValueError(
            f"max_len {max_len} < max(prompt bucket, prompt + "
            f"max_new_tokens + 2k+1) ({total_cap}) — the padded windows "
            "must fit")

    t_cache = init_cache(target_cfg, 1, max_len)
    d_cache = init_cache(draft_cfg, 1, max_len)

    # bucketed prompt prefill on both models; the draft skips the lm_head
    # entirely and the target computes logits at the last position only
    from ..models.moe import moe_prefill_keep_capacity
    block = np.zeros((1, p_bucket), np.int32)
    block[0, :p] = prompt
    block = jnp.asarray(block)
    t_last, t_cache = _ingest(
        target_params, t_cache, block, jnp.int32(0), jnp.int32(p),
        target_cfg, logits="last",
        keep_capacity=moe_prefill_keep_capacity(target_cfg, p))
    _, d_cache = _ingest(
        draft_params, d_cache, block, jnp.int32(0), jnp.int32(p),
        draft_cfg, logits="none",
        keep_capacity=moe_prefill_keep_capacity(draft_cfg, p))
    first = int(jnp.argmax(t_last[0]))

    out: List[int] = [first]
    # pending = emitted tokens neither model has validly ingested yet;
    # always 1..k+1 long, so the draft ingest width is statically k+1
    pending: List[int] = [first]
    n_valid = p                      # tokens both caches validly cover
    W_D, W_T = k + 1, 2 * k + 1

    while len(out) < max_new_tokens:
        c = len(pending)
        dblock = np.zeros((1, W_D), np.int32)
        dblock[0, :c] = pending
        proposals, d_cache = _draft_propose(
            draft_params, d_cache, jnp.asarray(dblock), jnp.int32(n_valid),
            jnp.int32(c), draft_cfg, k)
        proposals = [int(t) for t in np.asarray(proposals)]

        tblock = np.zeros((1, W_T), np.int32)
        tblock[0, :c] = pending
        tblock[0, c:c + k] = proposals
        t_logits, t_cache = _ingest(
            target_params, t_cache, jnp.asarray(tblock), jnp.int32(n_valid),
            jnp.int32(c + k), target_cfg, no_drop=True)
        greedy = np.asarray(jnp.argmax(t_logits[0], axis=-1))

        # greedy[c-1+i] is the target's own choice after pending+proposals
        # [:i]; accept while the draft matched it
        accepted = 0
        while accepted < k and proposals[accepted] == int(greedy[c - 1 + accepted]):
            accepted += 1
        correction = int(greedy[c - 1 + accepted])

        emitted = proposals[:accepted] + [correction]
        out.extend(emitted)
        n_valid += c                 # the old pending is now verified rows
        pending = emitted
        if stats is not None:
            stats.rounds += 1
            stats.proposed += k
            stats.accepted += accepted

    return out[:max_new_tokens]
