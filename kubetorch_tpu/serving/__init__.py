"""Pod runtime: HTTP server, execution supervisors, process pool, observability.

The in-pod half of the fabric (reference layer L2, SURVEY §1): an aiohttp
server that loads the user's callable from synced code, executes it in rank
subprocesses via a supervisor hierarchy, fans out to peer pods for SPMD, and
streams logs/metrics/exceptions back.
"""
