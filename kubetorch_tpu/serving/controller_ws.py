"""Persistent WebSocket from pod to controller.

Reference (``serving/http_server.py:206-501``): on startup the pod dials
``/controller/ws/pods``, registers {pod_name, pod_ip, namespace,
service_name}, receives workload metadata (applied as env), and thereafter
handles push messages — ``reload`` (hot code swap, ack'd with
``reload_ack``) and ``waiting`` (BYO pods registered before a workload
exists). Auto-reconnects with exponential backoff.
"""

from __future__ import annotations

import asyncio
import json
import os
import uuid
from typing import Optional

import aiohttp

from ..constants import server_port
from .discovery import my_pod_ip
from .env_contract import KT_SERVICE_NAME, apply_metadata

RECONNECT_BASE_S = 0.5
RECONNECT_MAX_S = 30.0


class ControllerWebSocket:
    def __init__(self, url: str, state):
        self.url = url
        self.state = state
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._stopping = False
        self.metadata_received = asyncio.Event()

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        self._stopping = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
        if self._session:
            await self._session.close()

    async def wait_for_metadata(self, timeout: float = 60.0) -> bool:
        try:
            await asyncio.wait_for(self.metadata_received.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def _run(self) -> None:
        # Parse the port OUTSIDE the reconnect try: a malformed value must
        # warn once (shared tolerant parse), not turn into a silent
        # retry-forever loop that never registers.
        port = server_port()
        delay = RECONNECT_BASE_S
        while not self._stopping:
            try:
                async with self._session.ws_connect(self.url, heartbeat=20) as ws:
                    delay = RECONNECT_BASE_S
                    await ws.send_json({
                        "action": "register",
                        "pod_name": self.state.pod_name,
                        "pod_ip": my_pod_ip(),
                        "namespace": self.state.namespace,
                        "service_name": os.environ.get(KT_SERVICE_NAME, ""),
                        "launch_id": self.state.launch_id,
                        # lets the controller derive a routable service_url for
                        # BYO pods, where no manifest ever declared one
                        "server_port": port,
                    })
                    async for msg in ws:
                        if msg.type != aiohttp.WSMsgType.TEXT:
                            break
                        await self._handle(ws, json.loads(msg.data))
            except asyncio.CancelledError:
                return
            except Exception:
                pass
            if self._stopping:
                return
            await asyncio.sleep(delay)
            delay = min(delay * 2, RECONNECT_MAX_S)

    async def _handle(self, ws, msg: dict) -> None:
        action = msg.get("action")
        if action == "metadata":
            apply_metadata(msg.get("metadata", {}))
            if msg.get("launch_id"):
                self.state.launch_id = msg["launch_id"]
                __import__("os").environ["KT_LAUNCH_ID"] = msg["launch_id"]
            self.metadata_received.set()
            await ws.send_json({"action": "metadata_ack",
                                "pod_name": self.state.pod_name})
        elif action == "reload":
            launch_id = msg.get("launch_id", uuid.uuid4().hex)
            try:
                await self.state.reload(msg.get("metadata", {}), launch_id)
                await ws.send_json({"action": "reload_ack", "ok": True,
                                    "launch_id": launch_id,
                                    "pod_name": self.state.pod_name})
            except BaseException as e:  # noqa: BLE001
                await ws.send_json({"action": "reload_ack", "ok": False,
                                    "error": str(e), "launch_id": launch_id,
                                    "pod_name": self.state.pod_name})
        elif action == "waiting":
            # BYO pod: registered before any workload is deployed to it
            self.metadata_received.set()
