"""Worker membership discovery.

Reference (``serving/distributed_supervisor.py:90-174``): pod IPs come from
the headless-service DNS record ``{svc}-headless.{ns}.svc.cluster.local``,
with quorum wait (exponential backoff 100ms→2s) and a ``LOCAL_IPS`` env fake
for running outside Kubernetes — the single hook that makes all distributed
logic unit-testable with local processes (SURVEY §4).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Callable, List, Optional


def discover_ips(service_name: str, namespace: str = "default") -> List[str]:
    """Current worker IPs, sorted for stable rank assignment."""
    fake = os.environ.get("LOCAL_IPS")
    if fake:
        return sorted(ip.strip() for ip in fake.split(",") if ip.strip())
    host = f"{service_name}-headless.{namespace}.svc.cluster.local"
    try:
        infos = socket.getaddrinfo(host, None, family=socket.AF_INET,
                                   type=socket.SOCK_STREAM)
        return sorted({info[4][0] for info in infos})
    except socket.gaierror:
        return []


def wait_for_quorum(service_name: str, namespace: str, expected: int,
                    timeout: float = 300.0,
                    discover: Optional[Callable[[], List[str]]] = None) -> List[str]:
    """Block until ``expected`` workers are resolvable (backoff 100ms→2s)."""
    discover = discover or (lambda: discover_ips(service_name, namespace))
    deadline = time.monotonic() + timeout
    delay = 0.1
    ips = discover()
    while len(ips) < expected:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"Quorum timeout: {len(ips)}/{expected} workers for "
                f"{service_name!r} after {timeout}s (have: {ips})")
        time.sleep(delay)
        delay = min(delay * 2, 2.0)
        ips = discover()
    return ips


def my_pod_ip() -> str:
    if os.environ.get("POD_IP"):
        return os.environ["POD_IP"]
    try:
        return socket.gethostbyname(socket.gethostname())
    except socket.gaierror:
        return "127.0.0.1"
