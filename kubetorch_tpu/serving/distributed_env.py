"""User-facing distributed introspection inside pods: ``kt.distributed``.

Reference analog: ``kt.distributed.pod_ips`` (SURVEY §2.1). User code running
in a rank subprocess reads its identity from the env contract; these helpers
decode it, and ``initialize_jax`` is the one-liner that brings up
``jax.distributed`` from the injected coordinates (usually automatic — jax
reads the same env vars — but explicit init lets users pass options).
"""

from __future__ import annotations

import os
from typing import List, Optional


class distributed:
    @staticmethod
    def pod_ips() -> List[str]:
        raw = os.environ.get("POD_IPS", "")
        return [ip for ip in raw.split(",") if ip]

    @staticmethod
    def rank() -> int:
        return int(os.environ.get("RANK", 0))

    @staticmethod
    def world_size() -> int:
        return int(os.environ.get("WORLD_SIZE", 1))

    @staticmethod
    def local_rank() -> int:
        return int(os.environ.get("LOCAL_RANK", 0))

    @staticmethod
    def node_rank() -> int:
        return int(os.environ.get("NODE_RANK", 0))

    @staticmethod
    def mesh_spec() -> Optional[dict]:
        import json
        raw = os.environ.get("KT_MESH")
        return json.loads(raw) if raw else None

    @staticmethod
    def initialize_jax(**kwargs) -> None:
        """Explicit ``jax.distributed.initialize`` from the env contract."""
        import jax
        jax.distributed.initialize(
            coordinator_address=os.environ.get("JAX_COORDINATOR_ADDRESS"),
            num_processes=int(os.environ.get("JAX_NUM_PROCESSES", 1)),
            process_id=int(os.environ.get("JAX_PROCESS_ID", 0)), **kwargs)

    @staticmethod
    def mesh(devices=None):
        """Build the mesh declared in ``.distribute(mesh=...)`` on this host's
        view of the global device set."""
        from ..parallel.mesh import build_mesh

        spec = distributed.mesh_spec()
        return build_mesh(spec, devices=devices)
