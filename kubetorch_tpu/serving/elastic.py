"""Elastic SPMD policy engine: survive rank loss without losing the job.

PRs 2-5 built a fail-fast substrate: a dead rank is detected within one
watchdog interval, classified into a typed cause, and healed by a
budget-bounded full-pool respawn — but the respawn is a restart-from-zero
that throws away every step since launch, exactly the failure amplification
Nonuniform-Tensor-Parallelism (arXiv:2504.06095) shows dominates scaled-up
training cost. This module is the degraded-but-alive alternative (ROADMAP
item 4), the Singularity (arXiv:2202.07848) checkpoint/preempt/resume loop:

- **Policy** — :class:`ElasticPolicy` maps the watchdog's typed causes to
  actions: ``Preempted``/``Evicted`` get the cooperative drain-and-checkpoint
  path *before* death (the SIGTERM grace window); ``OOMKilled`` restarts
  with a scaled-down per-rank batch (the job was too big for the host, not
  broken); ``Crashed``/``Killed``/``Exited`` resume from the last committed
  checkpoint — on the surviving N-1 ranks when survivors remain (re-mesh),
  at full size otherwise.
- **Budget split** — elastic resumes draw from their *own* sliding-window
  :class:`~..resilience.RestartBudget`, never the watchdog's hard-restart
  budget: a healthy elastic job riding out routine preemptions can't
  exhaust the budget that guards against genuine crash loops (and vice
  versa). ``kt_restarts_total{kind=...}`` keeps the two series distinct.
- **Drain flag** — the cooperative half of the loop. The rank worker
  installs a SIGTERM handler that flips a process-local drain event; a
  training step polls :func:`drain_requested` each iteration and flushes a
  committed checkpoint (``train/checkpoint.py``'s commit-marker protocol)
  inside the grace window, so a graceful preemption loses **zero** steps.
- **State** — checkpoint/restore itself lives in ``train/checkpoint.py``
  (async sharded saves to the data store, commit marker written last, delta
  sync making per-step cost ~bytes-changed); the re-mesh lives in
  ``ProcessPool.restart_all(num_procs=...)`` + ``MeshSpec.shrink_to``; this
  module only decides *what to do* and accounts for it.

Deterministic proof: the ``kill-rank`` chaos verb (hard loss → N-1 resume)
and the ``term-rank`` verb (SIGTERM + grace → drain-and-checkpoint), see
``tests/test_elastic.py`` / ``make test-elastic``.
"""

from __future__ import annotations

import os
import signal as signal_mod
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional

from .. import telemetry
from ..resilience import RestartBudget

# the elastic ledger (ISSUE 6): every resume decision is a counter by typed
# cause, so "how often does this job lose ranks, and to what" is a scrape,
# not a log grep
_RESUMES = telemetry.counter(
    "kt_elastic_resumes_total",
    "Elastic resumes (re-mesh / checkpoint-resume / batch-scaled restart) "
    "by typed death cause",
    labels=("cause",))
_DRAINS = telemetry.counter(
    "kt_elastic_drains_total",
    "Cooperative drain requests observed (SIGTERM grace-window path)")

ELASTIC_MAX_RESUMES_ENV = "KT_ELASTIC_MAX_RESUMES"
ELASTIC_RESUME_WINDOW_ENV = "KT_ELASTIC_RESUME_WINDOW_S"
BATCH_SCALE_ENV = "KT_ELASTIC_BATCH_SCALE"
# shared with the controller scheduler (controller/scheduler.py): the
# SIGTERM→eviction window a preempted pod gets. Policy and scheduler
# resolving the same knob keeps "how long do I have to checkpoint" and
# "how long do I wait before evicting" the same number.
DRAIN_GRACE_ENV = "KT_SCHED_DRAIN_GRACE_S"

# Actions a policy can decide for an observed rank death.
RESUME = "resume"                          # re-mesh + resume from checkpoint
RESTART_SMALLER_BATCH = "restart-smaller-batch"   # OOM: same mesh, scaled batch
FAIL = "fail"                              # budget/min-ranks verdict: hard-fail


def _env_or_cfg(env_key: str, cfg_field: str, default: float, cast=float):
    """Env wins over the layered config (same precedence as the watchdog:
    the config singleton may predate a runtime env mutation)."""
    raw = os.environ.get(env_key)
    if raw is not None:
        try:
            return cast(raw)
        except (TypeError, ValueError):
            pass
    try:
        from ..config import config
        return cast(config().get(cfg_field, default))
    except Exception:
        return default


def _default_max_resumes() -> int:
    return max(0, _env_or_cfg(ELASTIC_MAX_RESUMES_ENV,
                              "elastic_max_resumes", 8, int))


def _default_resume_window() -> float:
    return max(1.0, _env_or_cfg(ELASTIC_RESUME_WINDOW_ENV,
                                "elastic_resume_window_s", 3600.0))


@dataclass
class ElasticPolicy:
    """Knobs for the cause→action mapping. Travels controller→pod inside
    ``DistributedConfig.elastic`` (a plain dict), so ``.distribute(...,
    elastic={...})`` turns a fail-fast deployment into an elastic one."""

    min_ranks: int = 1              # below this, shrink is refused → FAIL
    max_resumes: int = -1           # elastic budget; -1 → env/config default
    resume_window_s: float = -1.0   # sliding window; -1 → env/config default
    oom_batch_scale: float = 0.5    # per-OOM multiplier on the batch scale
    min_batch_scale: float = 0.125  # floor: below this an OOM is a hard fail
    checkpoint_every: int = 50      # advisory cadence for Checkpointer users
    drain_grace_s: float = -1.0     # SIGTERM→KILL window; -1 → env/config

    def __post_init__(self):
        if self.max_resumes < 0:
            self.max_resumes = _default_max_resumes()
        if self.resume_window_s < 0:
            self.resume_window_s = _default_resume_window()
        if self.drain_grace_s < 0:
            self.drain_grace_s = max(0.0, _env_or_cfg(
                DRAIN_GRACE_ENV, "sched_drain_grace_s", 20.0))

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ElasticPolicy":
        d = d or {}
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        return cls(**{k: v for k, v in d.items() if k in known})

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    def action_for(self, cause: Optional[str]) -> str:
        """Typed death cause → elastic action. ``Preempted``/``Evicted``
        deaths land here only when the drain window was missed (the
        cooperative path checkpoints *before* death) — the remedy is the
        same resume-from-last-commit as any other loss.

        The pipeline supervisor (ISSUE 17) feeds the same taxonomy plus
        the straggler cause ``Slow`` (``watchdog.CAUSE_SLOW`` — alive but
        stalled, so there is no exitcode to classify): for a pipelined
        job every RESUME-class cause maps to a stage RE-GROUP under the
        same resume budget/window, so "how often may this job degrade"
        stays one knob for both distribution shapes."""
        if cause == "OOMKilled":
            return RESTART_SMALLER_BATCH
        return RESUME


class ElasticCoordinator:
    """Decision + accounting state for one supervisor's elastic loop.

    Owned by the supervisor, consulted by the pool's watchdog on every
    observed death (``Watchdog._maybe_restart``). Thread-safety: decisions
    run only on the watchdog thread; ``state_dict`` reads are snapshots.
    """

    def __init__(self, policy: Optional[ElasticPolicy] = None):
        self.policy = policy or ElasticPolicy()
        # the SPLIT budget: elastic resumes never touch the watchdog's
        # hard-restart budget, so routine preemptions can't eat the guard
        # against genuine crash loops
        self.budget = RestartBudget(self.policy.max_resumes,
                                    self.policy.resume_window_s)
        self.batch_scale = 1.0
        self.resumes = 0
        self.events: List[Dict[str, Any]] = []

    def decide(self, cause: Optional[str], surviving: int,
               num_procs: int) -> Dict[str, Any]:
        """One death → the verdict the watchdog executes.

        Returns ``{"action", "num_procs", "env"}``: the respawn size (the
        surviving N-1 ranks when enough survive — the re-mesh — else the
        original size, a plain resume-from-checkpoint) and the env overrides
        the fresh ranks must see (the batch scale). ``action == FAIL`` means
        the elastic budget is spent or the floor was hit; the watchdog turns
        that into the permanent typed failure.
        """
        action = self.policy.action_for(cause)
        if action == RESTART_SMALLER_BATCH:
            next_scale = self.batch_scale * self.policy.oom_batch_scale
            if next_scale < self.policy.min_batch_scale:
                return self._verdict(FAIL, cause, num_procs,
                                     reason="batch scale floor reached")
        if not self.budget.try_acquire():
            return self._verdict(FAIL, cause, num_procs,
                                 reason="elastic resume budget exhausted")
        if action == RESTART_SMALLER_BATCH:
            self.batch_scale *= self.policy.oom_batch_scale
            new_procs = num_procs          # same mesh, smaller per-rank batch
        elif surviving >= max(1, self.policy.min_ranks):
            new_procs = surviving          # re-mesh to the N-1 survivors
        else:
            new_procs = num_procs          # whole pool lost: resume full-size
        self.resumes += 1
        _RESUMES.inc(cause=cause or "Unknown")
        return self._verdict(action, cause, new_procs)

    def _verdict(self, action: str, cause: Optional[str], num_procs: int,
                 reason: Optional[str] = None) -> Dict[str, Any]:
        verdict = {"action": action, "num_procs": max(1, num_procs),
                   "env": self.env(), "cause": cause}
        if reason:
            verdict["reason"] = reason
        self.events.append({**verdict, "at": time.time()})
        del self.events[:-8]
        return verdict

    def env(self) -> Dict[str, str]:
        """Env overrides for respawned ranks: the batch scale a training
        loop reads via :func:`batch_scale` (halved per OOM)."""
        return {BATCH_SCALE_ENV: f"{self.batch_scale:g}"}

    def state_dict(self) -> Dict[str, Any]:
        """Surfaced under ``/health``'s ``workers.elastic``."""
        out = {"resumes": self.resumes, "batch_scale": self.batch_scale,
               **{f"budget_{k}": v for k, v in self.budget.state().items()}}
        if self.events:
            out["recent"] = self.events[-3:]
        return out


# ---------------------------------------------------------------------------
# Cooperative drain (the process-local half of the preemption grace window)
# ---------------------------------------------------------------------------

# Process-local by design: the pod server and each rank subprocess own one
# flag each. The server's SIGTERM path flips the pod-level watchdog drain
# flag (watchdog.set_draining) for death *classification*; this event is the
# rank-local signal a training step polls to flush-and-exit cooperatively.
_drain = threading.Event()
_drain_reason: Optional[str] = None


def request_drain(reason: Optional[str] = None) -> None:
    """Mark this process as draining: the step loop should checkpoint and
    return at the next opportunity. Idempotent."""
    global _drain_reason
    if not _drain.is_set():
        _drain_reason = reason
        _DRAINS.inc()
        telemetry.add_event("elastic.drain", reason=reason or "")
    _drain.set()


def drain_requested() -> bool:
    """Poll this from inside a training step loop (cheap: one Event read).
    True → flush a committed checkpoint and return; the pod/rank is going
    away inside a grace window."""
    return _drain.is_set()


def drain_reason() -> Optional[str]:
    return _drain_reason


def clear_drain() -> None:
    global _drain_reason
    _drain_reason = None
    _drain.clear()


def install_sigterm_drain() -> None:
    """Install the cooperative SIGTERM handler (rank subprocesses call this
    before user code loads). SIGTERM no longer kills the rank instantly —
    it flips the drain flag so the in-flight step can flush a checkpoint;
    the sender's grace-window SIGKILL (kubelet, or the ``term-rank`` chaos
    verb) remains the backstop for loops that never poll the flag. Only
    effective on the main thread; elsewhere it is a recorded no-op."""
    def _handler(signum, frame):  # noqa: ARG001 — signal signature
        request_drain("SIGTERM")

    try:
        signal_mod.signal(signal_mod.SIGTERM, _handler)
    except (ValueError, OSError):   # not the main thread / unsupported
        pass


def batch_scale(default: float = 1.0) -> float:
    """The per-rank batch scale the elastic layer asked for (1.0 → full
    batch; halved on each OOM-driven restart). Training loops multiply
    their per-rank batch size by this."""
    try:
        return float(os.environ.get(BATCH_SCALE_ENV, default))
    except (TypeError, ValueError):
        return default
