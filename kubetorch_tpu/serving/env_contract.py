"""The pod environment contract: how controller metadata and rank identity
reach user code.

Reference contract (``serving/design.md:266-278`` + ``_apply_metadata``
``http_server.py:254``): controller pushes workload metadata over WS, the
server exports it as ``KT_*`` env vars, and each rank subprocess additionally
gets framework-specific distributed env vars (``spmd/{pytorch,jax,
tensorflow}_process.py``).

TPU-first deltas:
- JAX is the primary framework: ``JaxEnv`` wires
  ``jax.distributed.initialize`` coordinates and — critically on TPU — the
  per-host TPU visibility vars. One process per TPU *host* (megacore), not
  per chip.
- TPU runtime vars (``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``) are set so
  libtpu agrees with the mesh about host ordering.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

# Metadata env keys (pushed controller → pod, applied by the server)
KT_MODULE_NAME = "KT_MODULE_NAME"
KT_CLS_OR_FN_NAME = "KT_CLS_OR_FN_NAME"
KT_FILE_PATH = "KT_FILE_PATH"
KT_PROJECT_ROOT = "KT_PROJECT_ROOT"
KT_INIT_ARGS = "KT_INIT_ARGS"
KT_CALLABLE_TYPE = "KT_CALLABLE_TYPE"          # fn | cls | app | cmd
KT_DISTRIBUTED_CONFIG = "KT_DISTRIBUTED_CONFIG"
KT_LAUNCH_ID = "KT_LAUNCH_ID"
KT_SERVICE_NAME = "KT_SERVICE_NAME"
KT_NAMESPACE = "KT_NAMESPACE"
KT_ALLOWED_SERIALIZATION = "KT_ALLOWED_SERIALIZATION"
KT_RUNTIME_CONFIG = "KT_RUNTIME_CONFIG"

METADATA_KEYS = [
    KT_MODULE_NAME, KT_CLS_OR_FN_NAME, KT_FILE_PATH, KT_PROJECT_ROOT,
    KT_INIT_ARGS, KT_CALLABLE_TYPE, KT_DISTRIBUTED_CONFIG, KT_LAUNCH_ID,
    KT_SERVICE_NAME, KT_NAMESPACE, KT_ALLOWED_SERIALIZATION, KT_RUNTIME_CONFIG,
]


def apply_metadata(metadata: Dict[str, object]) -> None:
    """Export workload metadata as env vars (values json-encoded if not str)."""
    for key, value in metadata.items():
        env_key = key if key.startswith("KT_") else f"KT_{key.upper()}"
        if value is None:
            os.environ.pop(env_key, None)
        elif isinstance(value, str):
            os.environ[env_key] = value
        else:
            os.environ[env_key] = json.dumps(value)


def read_metadata() -> Dict[str, str]:
    return {k: os.environ[k] for k in METADATA_KEYS if k in os.environ}


@dataclass
class RankInfo:
    """Identity of one rank subprocess in the global job."""

    node_rank: int
    local_rank: int
    nproc_per_node: int
    num_nodes: int
    pod_ips: List[str]

    @property
    def world_size(self) -> int:
        return self.num_nodes * self.nproc_per_node

    @property
    def rank(self) -> int:
        return self.node_rank * self.nproc_per_node + self.local_rank

    @property
    def master_ip(self) -> str:
        return self.pod_ips[0] if self.pod_ips else "127.0.0.1"


class FrameworkEnv:
    """Base: generic SPMD env contract (reference process_worker.py:75-102)."""

    name = "spmd"
    needs_restart_between_calls = False
    # Whether rank identity may be rebound per request (worker-subset calls,
    # reference spmd_supervisor.py:345-364 assembles env per call). True for
    # frameworks whose collectives initialize inside the request (pytorch
    # gloo/NCCL process groups, TF strategies, generic SPMD). False when
    # identity is physically fixed at process spawn.
    per_call_identity = True

    def env(self, info: RankInfo) -> Dict[str, str]:
        return {
            "WORLD_SIZE": str(info.world_size),
            "RANK": str(info.rank),
            "LOCAL_RANK": str(info.local_rank),
            "NODE_RANK": str(info.node_rank),
            "POD_IPS": ",".join(info.pod_ips),
        }

    def auto_nproc(self) -> int:
        """Processes per node when the user didn't specify."""
        return 1

    def worker_cleanup(self) -> None:
        """Called in the rank subprocess on reload/teardown."""


class JaxEnv(FrameworkEnv):
    """JAX on TPU: one process per host, chips exclusively owned.

    Coordinator = rank-0 pod IP. ``jax.distributed.initialize`` picks these
    up from env (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID)
    so user code needs zero boilerplate.
    """

    name = "jax"
    coordinator_port = 1234
    default_cache_dir = "/tmp/kt_jax_cache"
    # TPU chips are exclusively owned from spawn and jax.distributed
    # initializes once per process — the compiled mesh's identity cannot be
    # rebound per request. Worker-subset calls keep deployment-wide identity
    # (use shard_map sub-meshes inside the program to address chip subsets).
    per_call_identity = False

    def env(self, info: RankInfo) -> Dict[str, str]:
        e = super().env(info)
        e.update({
            "JAX_COORDINATOR_ADDRESS": f"{info.master_ip}:{self.coordinator_port}",
            "JAX_NUM_PROCESSES": str(info.world_size),
            "JAX_PROCESS_ID": str(info.rank),
            # libtpu host ordering must agree with the JAX process ids
            "TPU_WORKER_ID": str(info.rank),
            "TPU_WORKER_HOSTNAMES": ",".join(info.pod_ips),
        })
        # Persistent XLA compilation cache: rank subprocesses are recreated on
        # every hot reload / restart_procs, and without this each respawn pays
        # the full jit compile again (tens of seconds for real models). The
        # cache dir outlives subprocesses (same pod) and, when KT_JAX_CACHE_DIR
        # points at a mounted volume, even pod restarts. Empty value disables;
        # an explicit JAX_COMPILATION_CACHE_DIR in the pod env wins.
        if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
            cache_dir = os.environ.get("KT_JAX_CACHE_DIR", self.default_cache_dir)
            if cache_dir:
                e["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        return e

    def auto_nproc(self) -> int:
        # one process per TPU host (it owns all local chips / megacore)
        return 1

    def worker_cleanup(self) -> None:
        # Release the TPU: libtpu holds chips per-process, so a clean reload
        # must shut the distributed client down before respawn (SURVEY §7
        # hard-part 3).
        try:
            import jax
            jax.distributed.shutdown()
        except Exception:
            pass


class PyTorchEnv(FrameworkEnv):
    name = "pytorch"
    master_port = 12355

    def env(self, info: RankInfo) -> Dict[str, str]:
        e = super().env(info)
        e.update({
            "MASTER_ADDR": info.master_ip,
            "MASTER_PORT": str(self.master_port),
        })
        return e

    def auto_nproc(self) -> int:
        try:
            import torch
            if torch.cuda.is_available():
                return torch.cuda.device_count()
        except Exception:
            pass
        return 1

    def worker_cleanup(self) -> None:
        try:
            import torch.distributed as dist
            if dist.is_initialized():
                dist.destroy_process_group()
        except Exception:
            pass


class TensorflowEnv(FrameworkEnv):
    name = "tensorflow"
    port = 2222

    def env(self, info: RankInfo) -> Dict[str, str]:
        e = super().env(info)
        cluster = {
            "cluster": {"worker": [f"{ip}:{self.port}" for ip in info.pod_ips]},
            "task": {"type": "worker", "index": info.node_rank},
        }
        e["TF_CONFIG"] = json.dumps(cluster)
        return e


FRAMEWORKS: Dict[str, type] = {
    "spmd": FrameworkEnv,
    "jax": JaxEnv,
    "pytorch": PyTorchEnv,
    "torch": PyTorchEnv,
    "tensorflow": TensorflowEnv,
    "tf": TensorflowEnv,
}


def framework_for(name: Optional[str]) -> FrameworkEnv:
    cls = FRAMEWORKS.get((name or "spmd").lower(), FrameworkEnv)
    return cls()


def sync_jax_runtime_config() -> None:
    """Re-apply env-derived jax config that jax froze at import time.

    jax reads ``JAX_COMPILATION_CACHE_DIR`` (and the persistent-cache knobs)
    once, at import. A rank subprocess applies its env contract *after*
    interpreter startup, and jax may already be imported by then (spawn
    re-imports the parent's modules; some images preload jax site-wide). If
    so, push the values into ``jax.config`` explicitly — a no-op when jax
    isn't loaded yet, since import will pick the env vars up itself.
    """
    import sys

    if "jax" not in sys.modules:
        return
    import jax

    mapping = {
        "JAX_COMPILATION_CACHE_DIR": ("jax_compilation_cache_dir", str),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": (
            "jax_persistent_cache_min_compile_time_secs", float),
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": (
            "jax_persistent_cache_min_entry_size_bytes", int),
    }
    for env_key, (config_key, cast) in mapping.items():
        value = os.environ.get(env_key)
        if value:
            try:
                jax.config.update(config_key, cast(value))
            except Exception as e:
                # visible, not fatal: a failed sync means the worker falls
                # back to cold compiles, which must not go unnoticed
                import logging
                logging.getLogger(__name__).warning(
                    "failed to sync %s=%r into jax.config (%s): %s",
                    env_key, value, config_key, e)
