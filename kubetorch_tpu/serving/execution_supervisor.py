"""Supervisor hierarchy: owns rank subprocesses and routes calls.

Reference (``serving/execution_supervisor.py`` + ``distributed_supervisor.py``):
the base supervisor owns a ProcessPool and routes to subprocess 0; the
distributed supervisor adds membership discovery, a monitor thread diffing
pod-IP sets every few seconds, and ``WorkerMembershipChanged`` propagation
into in-flight calls.
"""

from __future__ import annotations

import asyncio
import contextlib
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..exceptions import WorkerDiedError, WorkerMembershipChanged
from ..parallel.mesh import DistributedConfig
from ..resources.pointers import Pointers
from .discovery import discover_ips, my_pod_ip, wait_for_quorum
from .env_contract import framework_for
from .process_pool import ProcessPool

MEMBERSHIP_POLL_S = 3.0


class ExecutionSupervisor:
    """Single-pod execution: one ProcessPool, calls go to rank 0."""

    def __init__(self, pointers: Optional[Pointers], init_args: Optional[Dict],
                 config: Optional[DistributedConfig] = None,
                 service_name: str = "", namespace: str = "default"):
        self.pointers = pointers
        self.init_args = init_args
        self.config = config or DistributedConfig(distribution_type="local")
        self.service_name = service_name
        self.namespace = namespace
        self.pool: Optional[ProcessPool] = None
        self._served_calls = 0
        self._restart_lock: Optional[asyncio.Lock] = None
        # elastic policy (ISSUE 6): when the distributed config carries an
        # `elastic` dict, rank loss resolves to checkpoint-resume / N-1
        # re-mesh instead of cancel-the-fan-out + same-size respawn
        self.elastic = None
        if getattr(self.config, "elastic", None) is not None:
            from .elastic import ElasticCoordinator, ElasticPolicy
            self.elastic = ElasticCoordinator(
                ElasticPolicy.from_dict(self.config.elastic))

    def attach_elastic(self, policy_or_coordinator) -> None:
        """Attach an elastic policy after construction (tests, embedders).
        Wires the live pool's watchdog too when one already exists."""
        from .elastic import ElasticCoordinator, ElasticPolicy
        if isinstance(policy_or_coordinator, ElasticPolicy):
            self.elastic = ElasticCoordinator(policy_or_coordinator)
        else:
            self.elastic = policy_or_coordinator
        if self.pool is not None:
            self._wire_elastic()

    def _wire_elastic(self) -> None:
        if self.elastic is None or self.pool is None:
            return
        self.pool.watchdog.attach_elastic(self.elastic)
        self.pool.remesh_env = self._remesh_env

    def _remesh_env(self, world_size: int) -> Dict[str, str]:
        """Env overrides for a resized pool: a KT_MESH shrunk to the new
        world (model-parallel axes keep their sizes, data-like axes absorb
        the loss — see :meth:`~..parallel.mesh.MeshSpec.shrink_to`)."""
        if not self.config.mesh:
            return {}
        import json
        from ..parallel.mesh import MeshSpec
        spec = MeshSpec.from_dict(self.config.mesh)
        old_total = max(1, math.prod(spec.shape))
        old_world = max(1, self.config.workers *
                        (self.config.procs_per_worker or 1))
        new_total = max(1, old_total * world_size // old_world)
        try:
            shrunk = spec.shrink_to(new_total)
        except ValueError:
            from ..parallel.mesh import best_mesh_for
            shrunk = best_mesh_for(new_total)
        return {"KT_MESH": json.dumps(
            {a: s for a, s in shrunk.axis_sizes().items() if s > 1})}

    # -- lifecycle ----------------------------------------------------------

    def num_procs(self) -> int:
        if self.config.procs_per_worker:
            return self.config.procs_per_worker
        return framework_for(self.config.distribution_type).auto_nproc()

    def setup(self) -> None:
        self.pool = ProcessPool(
            num_procs=self.num_procs(),
            framework_name=self.config.distribution_type,
            pointers=self.pointers, init_args=self.init_args,
            node_rank=0, num_nodes=1, pod_ips=[my_pod_ip()],
            base_env=self._base_env(),
        )
        self._wire_elastic()
        self.pool.start()

    def _base_env(self) -> Dict[str, str]:
        env = {}
        if self.config.mesh:
            import json
            env["KT_MESH"] = json.dumps(self.config.mesh)
        return env

    def cleanup(self) -> None:
        if self.pool is not None:
            self.pool.shutdown()
            self.pool = None

    @property
    def healthy(self) -> bool:
        return self.pool is not None and self.pool.healthy

    @property
    def warming(self) -> bool:
        """True while rank workers are inside their load+warmup window —
        gates /ready so pods don't join the endpoint pool mid-compile."""
        return self.pool is not None and self.pool.warming

    @property
    def recovering(self) -> bool:
        """True while the watchdog is respawning dead ranks — /ready flips
        unhealthy for exactly this window so the endpoint pool routes
        around a pod that is mid-self-heal."""
        return self.pool is not None and self.pool.recovering

    def restart_state(self) -> Dict[str, Any]:
        """Watchdog restart/budget state, reported in ``/health``."""
        if self.pool is None:
            return {}
        return self.pool.watchdog.state_dict()

    # -- calls ---------------------------------------------------------------

    async def call(self, method: Optional[str], args: list, kwargs: dict,
                   timeout: Optional[float] = None, **_ignored) -> Any:
        async with self.restart_guard():
            assert self.pool is not None, "supervisor not set up"
            while True:
                try:
                    return await self.pool.call(0, method, args, kwargs,
                                                timeout)
                except (WorkerDiedError, WorkerMembershipChanged) as e:
                    if not await self.elastic_recover(e):
                        raise

    async def elastic_recover(self, exc: BaseException) -> bool:
        """The resume half of the elastic loop (ISSUE 6): when a call died
        to rank loss and an elastic policy is attached, wait (bounded) for
        the watchdog's elastic respawn — re-meshed to the survivors, user
        state restored from the last committed checkpoint by the reloaded
        callable — then tell the caller to retry instead of cancelling the
        whole fan-out. False → not elastic / not resumable / pool failed
        permanently: surface the typed error as before."""
        if self.elastic is None or self.pool is None:
            return False
        if isinstance(exc, WorkerMembershipChanged) and \
                not getattr(exc, "resumable", False):
            return False
        from .. import telemetry
        # generous bound: watchdog interval + respawn backoff + worker spawn
        deadline = time.monotonic() + max(
            60.0, self.pool.watchdog.interval_s * 10)
        while time.monotonic() < deadline:
            if self.pool.watchdog.failed:
                return False        # budget verdict: permanent, typed
            if self.pool.healthy and not self.pool.recovering \
                    and not self.pool.warming:
                telemetry.add_event("elastic.call_retry",
                                    num_procs=self.pool.num_procs)
                return True
            await asyncio.sleep(0.05)
        return False

    def restart_guard(self):
        """Context manager for ``.distribute(restart_procs=True)``: fresh
        rank subprocesses for every call (reference spmd_supervisor.py:265)
        — the hammer for user code that can't re-init in-process (singleton
        frameworks, leaked device state).

        Calls are SERIALIZED in this mode (fresh-proc-per-call implies it):
        the lock prevents one request's cleanup() from killing the pool under
        another's in-flight call. Restart-before-call runs before any pool
        assertion, so a transient setup() failure is retried on the next call
        instead of bricking the supervisor. NOTE: ranks (and the TPU chips
        they hold) stay alive between calls — pair with ``inactivity_ttl`` to
        release hosts when idle.
        """
        if not (self.config and self.config.restart_procs):
            return contextlib.nullcontext()
        return self._serialized_restart()

    @contextlib.asynccontextmanager
    async def _serialized_restart(self):
        if self._restart_lock is None:
            self._restart_lock = asyncio.Lock()
        async with self._restart_lock:
            if self._served_calls > 0 or self.pool is None:
                await asyncio.to_thread(self.cleanup)
                await asyncio.to_thread(self.setup)
            self._served_calls += 1
            yield


class DistributedSupervisor(ExecutionSupervisor):
    """Adds worker membership: discovery, quorum, monitor, change events."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._known_ips: List[str] = []
        self._monitor: Optional[threading.Thread] = None
        self._stop_monitor = threading.Event()
        self._membership_events: List[WorkerMembershipChanged] = []
        self._events_lock = threading.Lock()

    def discover(self) -> List[str]:
        return discover_ips(self.service_name, self.namespace)

    def setup(self) -> None:
        expected = max(self.config.workers, 1)
        ips = wait_for_quorum(self.service_name, self.namespace, expected,
                              discover=self.discover)
        self._known_ips = ips
        my_ip = my_pod_ip()
        node_rank = ips.index(my_ip) if my_ip in ips else 0
        self.pool = ProcessPool(
            num_procs=self.num_procs(),
            framework_name=self.config.distribution_type,
            pointers=self.pointers, init_args=self.init_args,
            node_rank=node_rank, num_nodes=len(ips), pod_ips=ips,
            base_env=self._base_env(),
        )
        # a coordinator-observed local rank death must cancel the whole
        # distributed fan-out, typed — not just the local branch
        self.pool.watchdog.on_death.append(self._on_worker_death)
        self.pool.watchdog.on_restart.append(self._on_worker_restart)
        self._wire_elastic()
        self.pool.start()
        self._start_monitor()

    def cleanup(self) -> None:
        self._stop_monitor.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2)
            self._monitor = None
        super().cleanup()

    # -- membership monitoring (reference :236-339) ---------------------------

    def _start_monitor(self) -> None:
        self._stop_monitor.clear()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop_monitor.wait(MEMBERSHIP_POLL_S):
            current = self.discover()
            if not current:
                continue
            previous = self._known_ips
            if set(current) != set(previous):
                event = WorkerMembershipChanged(
                    added=sorted(set(current) - set(previous)),
                    removed=sorted(set(previous) - set(current)),
                    previous=previous, current=current,
                )
                if self.elastic is not None and event.removed:
                    # elastic jobs treat a shrunken pod set as resumable:
                    # the fan-out coordinator re-meshes to the survivors
                    # and resumes instead of cancelling the job
                    event.resumable = True
                self._known_ips = current
                with self._events_lock:
                    self._membership_events.append(event)
                if self.pool is not None and event.is_critical:
                    # fast-fail in-flight local work; the coordinator
                    # propagates the typed error to the client for resize
                    self.pool.cancel_pending(event)

    # -- worker-death translation (watchdog hooks, ISSUE 3) -------------------

    def _on_worker_death(self, local_rank: int, exc) -> None:
        """Translate a rank-subprocess death into the membership taxonomy:
        a critical ``WorkerMembershipChanged`` with the concrete typed cause
        (``WorkerDiedError``) chained on, queued for the next call AND
        fanned out into every in-flight future so remote branches of a
        distributed call cancel now instead of riding out their timeouts."""
        my_ip = my_pod_ip()
        event = WorkerMembershipChanged(
            f"local rank {local_rank} died mid-call "
            f"(cause={exc.cause}); mesh invalidated",
            removed=[my_ip], previous=list(self._known_ips),
            current=[ip for ip in self._known_ips if ip != my_ip])
        if self.elastic is not None:
            # downgraded from fan-out-fatal to resumable (ISSUE 6): the
            # elastic call loop waits out the re-mesh and retries on the
            # surviving ranks instead of cancelling the whole job
            event.resumable = True
        event.__cause__ = exc
        with self._events_lock:
            self._membership_events.append(event)
        if self.pool is not None:
            self.pool.cancel_pending(event)

    def _on_worker_restart(self) -> None:
        """The respawned pool restores the collective: drop queued
        death-caused events so the next call runs instead of tripping over
        a cancellation for a mesh that no longer exists. Real membership
        changes (pod-IP diffs) are kept — those still require a resize."""
        from ..exceptions import WorkerDiedError
        with self._events_lock:
            self._membership_events = [
                e for e in self._membership_events
                if not isinstance(e.__cause__, WorkerDiedError)]

    def pop_membership_event(self) -> Optional[WorkerMembershipChanged]:
        with self._events_lock:
            return self._membership_events.pop(0) if self._membership_events else None

    def check_membership(self) -> None:
        event = self.pop_membership_event()
        if event is not None and event.is_critical:
            raise event

    def pod_ips(self) -> List[str]:
        return list(self._known_ips)
