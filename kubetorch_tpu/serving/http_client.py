"""Client-side HTTP caller for deployed services.

Reference (``serving/http_client.py``, 1132 LoC): request preparation with
serialization headers, sync/async call paths, WS log streaming filtered by
X-Request-ID, and exception rehydration that reconstructs the remote error
type on the caller's side.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Optional

import requests as _requests

from .. import serialization as ser
from ..config import config
from ..exceptions import ControllerRequestError, rehydrate_exception


class CustomResponse:
    """Wraps a response; raise_for_status rehydrates remote exceptions
    (reference http_client.py:87-194)."""

    def __init__(self, status: int, body: bytes, headers: Dict[str, str]):
        self.status = status
        self.body = body
        self.headers = headers

    def raise_for_status(self) -> None:
        if self.status < 400:
            return
        try:
            data = json.loads(self.body.decode())
        except (ValueError, UnicodeDecodeError):
            raise ControllerRequestError(
                f"HTTP {self.status}: {self.body[:500]!r}", status_code=self.status)
        if "error_type" in data:
            raise rehydrate_exception(data)
        raise ControllerRequestError(f"HTTP {self.status}: {data}",
                                     status_code=self.status)

    def result(self) -> Any:
        self.raise_for_status()
        fmt = self.headers.get("X-Serialization", ser.JSON)
        return ser.deserialize(self.body, fmt)


# Live log-stream pump threads: daemon threads die with the interpreter, so
# a one-shot script exiting right after its call would lose the trailing log
# lines the grace drain exists to deliver — the atexit hook joins them first.
_LIVE_PUMPS: list = []


def _drain_pumps_at_exit() -> None:
    grace = float(os.environ.get("KT_LOG_STREAM_GRACE", "3.0"))
    deadline = time.monotonic() + max(6.0, grace + 2.0)
    for t in list(_LIVE_PUMPS):
        t.join(max(0.0, deadline - time.monotonic()))


atexit.register(_drain_pumps_at_exit)


class HTTPClient:
    """Caller for one deployed service."""

    def __init__(self, base_url: str, serialization: Optional[str] = None,
                 stream_logs: Optional[bool] = None,
                 proxy_url: Optional[str] = None,
                 service: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.serialization = serialization or config().serialization
        self.stream_logs = (config().stream_logs if stream_logs is None
                            else stream_logs)
        # Controller-proxy fallback: a scaled-to-zero service has no pod
        # listening at base_url; the proxy cold-starts it (the Knative
        # activator role) and forwards the held request.
        self.proxy_url = proxy_url.rstrip("/") if proxy_url else None
        self.service = service       # labels resource-scope PromQL queries
        self._resource_scope_dead = False   # controller said: no stack
        self._resource_scope_fails = 0      # consecutive-failure backoff
        self._session = _requests.Session()

    # -- calls ----------------------------------------------------------------

    def call_method(self, fn_name: str, method: Optional[str] = None,
                    args: tuple = (), kwargs: Optional[dict] = None,
                    workers=None, timeout: Optional[float] = None,
                    debugger=None,
                    stream_logs: Optional[bool] = None,
                    metrics=None, logging=None) -> Any:
        """``debugger``/``metrics``/``logging`` accept the typed config
        objects (``kt.DebugConfig`` / ``kt.MetricsConfig`` /
        ``kt.LoggingConfig``, reference globals.py:40-127) or plain dicts
        with the same fields."""
        from ..config import LoggingConfig, MetricsConfig
        if isinstance(metrics, dict):
            metrics = MetricsConfig(**metrics)
        if isinstance(logging, dict):
            logging = LoggingConfig(**logging)
        if logging is not None and stream_logs is None:
            stream_logs = logging.stream_logs
        if hasattr(debugger, "to_dict"):
            debugger = debugger.to_dict()
        body: Dict[str, Any] = {"args": list(args), "kwargs": kwargs or {}}
        if workers is not None:
            body["_kt_workers"] = workers
        if debugger:
            debugger = dict(debugger)
            if "token" not in debugger:
                # one-shot session token: the pod-side breakpoint refuses
                # connections that don't present it
                debugger["token"] = uuid.uuid4().hex[:16]
                print(f"[debug] breakpoint armed — attach with: kt debug "
                      f"<service> --port {debugger.get('port', 5678)} "
                      f"--token {debugger['token']}", flush=True)
            body["debugger"] = debugger
        request_id = uuid.uuid4().hex[:16]
        url = f"{self.base_url}/{fn_name}" + (f"/{method}" if method else "")

        stop_streaming = None
        stop_metrics = None
        if (self.stream_logs if stream_logs is None else stream_logs):
            stop_streaming = self._start_log_stream(
                request_id,
                include_name=(logging.include_name if logging else True),
                grace=(logging.grace_period if logging else None))
        if metrics is not None or config().stream_metrics:
            stop_metrics = self._start_metric_stream(
                interval=(metrics.interval if metrics else None),
                scope=(metrics.scope if metrics else "pod"))
        try:
            data = ser.serialize(body, self.serialization)
            headers = {"X-Serialization": self.serialization,
                       "X-Request-ID": request_id}
            try:
                resp = self._session.post(url, data=data, headers=headers,
                                          timeout=timeout)
            except _requests.exceptions.ConnectionError as e:
                # Fall back ONLY when the connection was never established
                # (scaled to zero / pod churn): the proxy cold-starts the
                # service and holds the request until a pod is ready. A
                # reset MID-request must not re-POST — the call may already
                # be executing on the pod, and running it twice is worse
                # than surfacing the error.
                established = not any(
                    marker in str(e) for marker in
                    ("NewConnectionError", "Connection refused",
                     "Name or service not known", "No route to host"))
                if self.proxy_url is None or established:
                    raise
                resp = self._session.post(
                    f"{self.proxy_url}/{fn_name}" +
                    (f"/{method}" if method else ""),
                    data=data, headers=headers, timeout=timeout)
        finally:
            if stop_streaming:
                stop_streaming()
            if stop_metrics:
                stop_metrics()
        return CustomResponse(resp.status_code, resp.content,
                              dict(resp.headers)).result()

    async def call_method_async(self, fn_name: str, method: Optional[str] = None,
                                args: tuple = (), kwargs: Optional[dict] = None,
                                workers=None, timeout: Optional[float] = None) -> Any:
        import aiohttp

        body: Dict[str, Any] = {"args": list(args), "kwargs": kwargs or {}}
        if workers is not None:
            body["_kt_workers"] = workers
        url = f"{self.base_url}/{fn_name}" + (f"/{method}" if method else "")
        async with aiohttp.ClientSession() as sess:
            async with sess.post(
                url, data=ser.serialize(body, self.serialization),
                headers={"X-Serialization": self.serialization,
                         "X-Request-ID": uuid.uuid4().hex[:16]},
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                return CustomResponse(resp.status, await resp.read(),
                                      dict(resp.headers)).result()

    # -- health ---------------------------------------------------------------

    def is_ready(self, launch_id: Optional[str] = None,
                 timeout: float = 2.0) -> bool:
        try:
            params = {"launch_id": launch_id} if launch_id else {}
            r = self._session.get(f"{self.base_url}/ready", params=params,
                                  timeout=timeout)
            return r.status_code == 200
        except _requests.RequestException:
            return False

    # -- metric streaming -----------------------------------------------------

    @staticmethod
    def _format_metrics(text: str) -> str:
        """Compact one-liner from a pod's /metrics exposition: summed HBM
        across devices, in-flight count, request counter."""
        hbm_use = hbm_lim = 0.0
        inflight = reqs = None
        for ln in text.splitlines():
            if not ln.startswith(("kt_", "kubetorch_")):
                continue
            try:
                name, val = ln.rsplit(" ", 1)
                v = float(val)
            except ValueError:
                continue
            if name.startswith("kt_tpu_hbm_bytes_in_use"):
                hbm_use += v
            elif name.startswith("kt_tpu_hbm_bytes_limit"):
                hbm_lim += v
            elif name == "kt_inflight_requests":
                inflight = int(v)
            elif name == "kt_http_requests_total":
                reqs = int(v)
        parts = []
        if hbm_lim:
            parts.append(f"hbm={hbm_use / 2**30:.2f}/{hbm_lim / 2**30:.2f}GiB"
                         f" ({100 * hbm_use / hbm_lim:.0f}%)")
        if inflight is not None:
            parts.append(f"inflight={inflight}")
        if reqs is not None:
            parts.append(f"reqs={reqs}")
        return "  ".join(parts)

    def _resource_scope_line(self) -> Optional[str]:
        """Service-aggregate gauges via PromQL through the controller
        (reference ``scope="resource"`` queries, http_client.py:758-795).
        Needs deploy/metrics.yaml; any failure returns None and the pump
        falls back to pod scope."""
        api = config().api_url
        if not api or not self.service:
            return None
        parts = []
        queries = {
            "hbm_used": f'sum(kt_tpu_hbm_bytes_in_use{{service="{self.service}"}})',
            "inflight": f'sum(kt_inflight_requests{{service="{self.service}"}})',
        }
        for label, q in queries.items():
            try:
                r = _requests.get(f"{api}/controller/metrics/query",
                                  params={"query": q}, timeout=5)
                if r.status_code == 503:
                    # Latch ONLY the controller's own "no metrics stack
                    # configured" sentinel (dedicated header; body match for
                    # older controllers). The query route relays upstream
                    # status codes, so a 503 from a transiently-overloaded
                    # Prometheus must stay retryable — latching it would
                    # disable resource-scope metrics for the client's
                    # lifetime over a blip.
                    if (r.headers.get("X-KT-Unconfigured") == "metrics"
                            or "no metrics stack configured"
                            in r.text[:200]):
                        self._resource_scope_dead = True
                    return None
                results = r.json().get("data", {}).get("result", [])
                if r.status_code == 200 and results:
                    val = float(results[0]["value"][1])
                    parts.append(
                        f"{label}={val / 2**30:.2f}GiB"
                        if label.startswith("hbm") else
                        f"{label}={val:.0f}")
            except (_requests.RequestException, ValueError, KeyError,
                    IndexError):
                return None
        return "  ".join(parts) if parts else None

    def _start_metric_stream(self, interval: Optional[float] = None,
                             scope: str = "pod"):
        """Poll metrics during a call and echo compact lines alongside the
        streamed logs (reference streams DCGM GPU util via PromQL,
        ``http_client.py:758-795``). ``scope="pod"``: the service's own
        /metrics (TPU HBM gauges), via the controller proxy when the pod
        isn't directly reachable. ``scope="resource"``: PromQL aggregates
        across the service's pods, degrading to pod scope when no metrics
        stack answers."""
        stop = threading.Event()
        if interval is None:
            interval = float(os.environ.get("KT_METRIC_STREAM_INTERVAL", "3"))

        def pump():
            # module-level requests, NOT self._session: Session isn't
            # thread-safe and the main thread's POST is in flight
            tick = 0
            while not stop.wait(interval):
                tick += 1
                if scope == "resource" and not self._resource_scope_dead:
                    # exponential backoff on consecutive failures: a fresh
                    # deploy's not-yet-scraped window recovers (unlike a
                    # permanent latch), but a dead/stale controller can't
                    # charge every tick two 5s query timeouts. The explicit
                    # "no stack configured" 503 still latches immediately
                    # (inside _resource_scope_line).
                    if self._resource_scope_fails and (
                            tick % min(2 ** self._resource_scope_fails, 32)):
                        pass
                    else:
                        line = self._resource_scope_line()
                        if line:
                            self._resource_scope_fails = 0
                            print(f"[metrics] {line}", flush=True)
                            continue
                        self._resource_scope_fails += 1
                for url in (self.base_url, self.proxy_url):
                    if not url:
                        continue
                    try:
                        r = _requests.get(f"{url}/metrics", timeout=3)
                    except _requests.RequestException:
                        continue
                    if r.status_code != 200:
                        continue
                    line = self._format_metrics(r.text)
                    if line:
                        print(f"[metrics] {line}", flush=True)
                    break

        threading.Thread(target=pump, daemon=True).start()
        return stop.set

    # -- log streaming --------------------------------------------------------

    def _start_log_stream(self, request_id: str, include_name: bool = True,
                          grace: Optional[float] = None):
        """Poll the controller's log buffer for this request's lines and echo
        them locally (reference streams from Loki over WS; our controller
        exposes the same data over HTTP long-poll)."""
        api = config().api_url
        if not api:
            return None
        stop = threading.Event()
        # Keep draining after the call returns: the pod batches log pushes
        # (~1s) and the controller ingest adds latency, so the lines printed
        # at the end of a request land AFTER its response (the reference's
        # LoggingConfig grace-period behavior, globals.py:61-102).
        if grace is None:
            grace = float(os.environ.get("KT_LOG_STREAM_GRACE", "3.0"))

        def pump():
            seen = 0
            stopped_at = None
            while True:
                if stop.is_set() and stopped_at is None:
                    stopped_at = time.monotonic()
                got = 0
                try:
                    r = _requests.get(
                        f"{api}/controller/logs",
                        params={"request_id": request_id, "offset": seen},
                        timeout=5)
                    if r.status_code == 200:
                        data = r.json()
                        for entry in data.get("entries", []):
                            tag = (entry.get("pod") or "remote"
                                   if include_name else "remote")
                            print(f"[{tag}] {entry['line']}")
                            got += 1
                        seen = data.get("offset", seen)
                except _requests.RequestException:
                    pass
                if stopped_at is not None:
                    elapsed = time.monotonic() - stopped_at
                    # drain until quiet: once the pod's ~1s flush interval has
                    # passed and a fetch comes back empty, everything the
                    # request produced has been echoed; grace bounds it
                    if elapsed >= grace or (got == 0 and elapsed >= 1.25):
                        return
                    time.sleep(0.25)    # Event.wait would return instantly now
                else:
                    stop.wait(0.5)

        def run_pump():
            try:
                pump()
            finally:
                try:
                    _LIVE_PUMPS.remove(t)
                except ValueError:
                    pass

        t = threading.Thread(target=run_pump, daemon=True)
        _LIVE_PUMPS.append(t)
        t.start()

        def stopper():
            # no join here: that would charge every streamed call the ~1.25s
            # quiet-drain minimum. The pump drains in the background; the
            # atexit hook below joins survivors so a one-shot script still
            # sees the trailing lines (batched ~1s in the pod) before exit.
            stop.set()

        return stopper
