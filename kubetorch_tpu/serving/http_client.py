"""Client-side HTTP caller for deployed services.

Reference (``serving/http_client.py``, 1132 LoC): request preparation with
serialization headers, sync/async call paths, WS log streaming filtered by
X-Request-ID, and exception rehydration that reconstructs the remote error
type on the caller's side.
"""

from __future__ import annotations

import asyncio
import atexit
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Optional

import requests as _requests

from .. import serialization as ser
from .. import telemetry
from ..config import config
from ..exceptions import ControllerRequestError, rehydrate_exception
from ..resilience import (DEADLINE_HEADER, ESTABLISHED_TRANSIENT_EXCS,
                          RETRYABLE_STATUSES, Deadline, RetryPolicy,
                          connection_never_established, http_policy,
                          retry_after_seconds)


class CustomResponse:
    """Wraps a response; raise_for_status rehydrates remote exceptions
    (reference http_client.py:87-194)."""

    def __init__(self, status: int, body: bytes, headers: Dict[str, str]):
        self.status = status
        self.body = body
        self.headers = headers

    def raise_for_status(self) -> None:
        if self.status < 400:
            return
        try:
            data = json.loads(self.body.decode())
        except (ValueError, UnicodeDecodeError):
            raise ControllerRequestError(
                f"HTTP {self.status}: {self.body[:500]!r}", status_code=self.status)
        if "error_type" in data:
            exc = rehydrate_exception(data)
            # keep the transport facts alongside the rehydrated type: the
            # HTTP status and the request id the server logs are labelled
            # with, so `except kt.PodTerminatedError as e` can actually
            # find the failing request in the pod logs
            if getattr(exc, "status_code", None) is None:
                exc.status_code = self.status  # type: ignore[attr-defined]
            rid = self.headers.get("X-Request-ID")
            if rid and getattr(exc, "request_id", None) is None:
                exc.request_id = rid  # type: ignore[attr-defined]
            raise exc
        raise ControllerRequestError(f"HTTP {self.status}: {data}",
                                     status_code=self.status)

    def result(self) -> Any:
        self.raise_for_status()
        fmt = self.headers.get("X-Serialization", ser.JSON)
        return ser.deserialize(self.body, fmt)


# Live log-stream pump threads: daemon threads die with the interpreter, so
# a one-shot script exiting right after its call would lose the trailing log
# lines the grace drain exists to deliver — the atexit hook joins them first.
_LIVE_PUMPS: list = []


def _drain_pumps_at_exit() -> None:
    grace = float(os.environ.get("KT_LOG_STREAM_GRACE", "3.0"))
    deadline = time.monotonic() + max(6.0, grace + 2.0)
    for t in list(_LIVE_PUMPS):
        t.join(max(0.0, deadline - time.monotonic()))


atexit.register(_drain_pumps_at_exit)


def _clamp_timeout(explicit: Optional[float],
                   policy_timeout: Optional[float]) -> Optional[float]:
    """Per-attempt I/O timeout: the caller's explicit value bounded by the
    policy's deadline-clamped attempt timeout (whichever is tighter)."""
    if explicit is None:
        return policy_timeout
    if policy_timeout is None:
        return explicit
    return min(explicit, policy_timeout)


def _retryable_exc(e: BaseException, idempotency_key: Optional[str]) -> bool:
    """The safe-retry rule for user calls: never-established is always
    retryable (the server can't have seen the request); established
    transport failures only when the server dedupes our idempotency key."""
    if connection_never_established(e):
        return True
    return bool(idempotency_key) and isinstance(e, ESTABLISHED_TRANSIENT_EXCS)


def _response_retry(status: int, body: bytes, resp: Any,
                    idempotency_key: Optional[str]):
    """Response verdict for RetryPolicy.run/arun: retry transient 5xx only
    under an idempotency key, honoring Retry-After; a DeadlineExceededError
    body is terminal — the budget is gone whatever we do."""
    if status not in RETRYABLE_STATUSES or not idempotency_key:
        return None
    if b"DeadlineExceededError" in body[:2048]:
        return None
    ra = retry_after_seconds(resp)
    return ra if ra is not None else True


class HTTPClient:
    """Caller for one deployed service."""

    def __init__(self, base_url: str, serialization: Optional[str] = None,
                 stream_logs: Optional[bool] = None,
                 proxy_url: Optional[str] = None,
                 service: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None):
        self.base_url = base_url.rstrip("/")
        self.serialization = serialization or config().serialization
        self.stream_logs = (config().stream_logs if stream_logs is None
                            else stream_logs)
        # Controller-proxy fallback: a scaled-to-zero service has no pod
        # listening at base_url; the proxy cold-starts it (the Knative
        # activator role) and forwards the held request.
        self.proxy_url = proxy_url.rstrip("/") if proxy_url else None
        self.service = service       # labels resource-scope PromQL queries
        self._resource_scope_dead = False   # controller said: no stack
        self._resource_scope_fails = 0      # consecutive-failure backoff
        self._session = _requests.Session()
        self.retry = retry           # per-client default; None → http_policy()
        self.last_retry_delays: list = []   # backoff actually slept (tests)
        self._aio_session = None
        self._aio_loop = None

    # -- calls ----------------------------------------------------------------

    def call_method(self, fn_name: str, method: Optional[str] = None,
                    args: tuple = (), kwargs: Optional[dict] = None,
                    workers=None, timeout: Optional[float] = None,
                    debugger=None,
                    stream_logs: Optional[bool] = None,
                    metrics=None, logging=None,
                    idempotency_key: Optional[str] = None,
                    deadline: Optional[float] = None,
                    retry: Optional[RetryPolicy] = None) -> Any:
        """``debugger``/``metrics``/``logging`` accept the typed config
        objects (``kt.DebugConfig`` / ``kt.MetricsConfig`` /
        ``kt.LoggingConfig``, reference globals.py:40-127) or plain dicts
        with the same fields.

        Resilience (see :mod:`kubetorch_tpu.resilience`): a connection that
        was never established is always retried (the request can't have
        executed); anything after the connection was established — resets,
        timeouts, 5xx — is retried ONLY when ``idempotency_key`` is given,
        because the server dedupes that key and a retry can never run the
        function twice. ``deadline`` (seconds) rides ``X-KT-Deadline`` so
        the pod refuses work the client has already abandoned."""
        from ..config import LoggingConfig, MetricsConfig
        if isinstance(metrics, dict):
            metrics = MetricsConfig(**metrics)
        if isinstance(logging, dict):
            logging = LoggingConfig(**logging)
        if logging is not None and stream_logs is None:
            stream_logs = logging.stream_logs
        if hasattr(debugger, "to_dict"):
            debugger = debugger.to_dict()
        body: Dict[str, Any] = {"args": list(args), "kwargs": kwargs or {}}
        if workers is not None:
            body["_kt_workers"] = workers
        if debugger:
            debugger = dict(debugger)
            if "token" not in debugger:
                # one-shot session token: the pod-side breakpoint refuses
                # connections that don't present it
                debugger["token"] = uuid.uuid4().hex[:16]
                print(f"[debug] breakpoint armed — attach with: kt debug "
                      f"<service> --port {debugger.get('port', 5678)} "
                      f"--token {debugger['token']}", flush=True)
            body["debugger"] = debugger
        request_id = uuid.uuid4().hex[:16]
        url = f"{self.base_url}/{fn_name}" + (f"/{method}" if method else "")

        stop_streaming = None
        stop_metrics = None
        if (self.stream_logs if stream_logs is None else stream_logs):
            stop_streaming = self._start_log_stream(
                request_id,
                include_name=(logging.include_name if logging else True),
                grace=(logging.grace_period if logging else None))
        if metrics is not None or config().stream_metrics:
            stop_metrics = self._start_metric_stream(
                interval=(metrics.interval if metrics else None),
                scope=(metrics.scope if metrics else "pod"))
        try:
            data = ser.serialize(body, self.serialization)
            headers = {"X-Serialization": self.serialization,
                       "X-Request-ID": request_id}
            policy = retry or self.retry or http_policy()
            dl = None
            if deadline is not None:
                dl = Deadline.after(deadline)
            elif policy.deadline is not None:
                dl = Deadline.after(policy.deadline)
            if dl is not None:
                headers[DEADLINE_HEADER] = dl.header_value()
            if idempotency_key:
                headers["X-KT-Idempotency-Key"] = idempotency_key

            # the client-side root of the request's trace: the span context
            # rides X-KT-Trace so the pod server (and everything behind it)
            # parents onto it, and the retry loop's attempt/backoff events
            # land on it (resilience.py emits into the active span)
            client_span = telemetry.span(
                "client.call", fn=fn_name, method=method or "",
                request_id=request_id, url=self.base_url)

            def _attempt(info):
                t = _clamp_timeout(timeout, info.timeout)
                try:
                    return self._session.post(url, data=data,
                                              headers=headers, timeout=t)
                except _requests.exceptions.ConnectionError as e:
                    # Fall back ONLY when the connection was never
                    # established (scaled to zero / pod churn): the proxy
                    # cold-starts the service and holds the request until a
                    # pod is ready. A reset MID-request must not re-POST —
                    # the call may already be executing on the pod, and
                    # running it twice is worse than surfacing the error.
                    if (self.proxy_url is None
                            or not connection_never_established(e)):
                        raise
                    return self._session.post(
                        f"{self.proxy_url}/{fn_name}" +
                        (f"/{method}" if method else ""),
                        data=data, headers=headers, timeout=t)

            self.last_retry_delays = []
            with client_span as sp:
                telemetry.inject(headers)
                resp = policy.run(
                    _attempt,
                    retryable_exc=lambda e: _retryable_exc(e, idempotency_key),
                    response_retry_delay=lambda r: _response_retry(
                        r.status_code, r.content, r, idempotency_key),
                    deadline=dl,
                    record=self.last_retry_delays)
                sp.set_attr("status", resp.status_code)
        finally:
            if stop_streaming:
                stop_streaming()
            if stop_metrics:
                stop_metrics()
        return CustomResponse(resp.status_code, resp.content,
                              dict(resp.headers)).result()

    def _async_session(self):
        """One shared ``aiohttp.ClientSession`` per client per event loop
        (connection keep-alive parity with the sync path's Session). A
        session from a finished loop can't be awaited closed — it is
        abandoned and replaced."""
        import aiohttp

        loop = asyncio.get_running_loop()
        if (self._aio_session is None or self._aio_session.closed
                or self._aio_loop is not loop):
            self._aio_session = aiohttp.ClientSession()
            self._aio_loop = loop
        return self._aio_session

    async def aclose(self) -> None:
        if self._aio_session is not None and not self._aio_session.closed \
                and self._aio_loop is asyncio.get_running_loop():
            await self._aio_session.close()
        self._aio_session = None
        self._aio_loop = None

    async def call_method_async(self, fn_name: str, method: Optional[str] = None,
                                args: tuple = (), kwargs: Optional[dict] = None,
                                workers=None, timeout: Optional[float] = None,
                                idempotency_key: Optional[str] = None,
                                deadline: Optional[float] = None,
                                retry: Optional[RetryPolicy] = None) -> Any:
        """Async twin of :meth:`call_method`: same shared-session reuse,
        same scaled-to-zero proxy fallback, and the same
        never-re-POST-after-established rule (retries past an established
        connection require ``idempotency_key``)."""
        import aiohttp

        body: Dict[str, Any] = {"args": list(args), "kwargs": kwargs or {}}
        if workers is not None:
            body["_kt_workers"] = workers
        url = f"{self.base_url}/{fn_name}" + (f"/{method}" if method else "")
        data = ser.serialize(body, self.serialization)
        request_id = uuid.uuid4().hex[:16]
        headers = {"X-Serialization": self.serialization,
                   "X-Request-ID": request_id}
        policy = retry or self.retry or http_policy()
        dl = None
        if deadline is not None:
            dl = Deadline.after(deadline)
        elif policy.deadline is not None:
            dl = Deadline.after(policy.deadline)
        if dl is not None:
            headers[DEADLINE_HEADER] = dl.header_value()
        if idempotency_key:
            headers["X-KT-Idempotency-Key"] = idempotency_key
        sess = self._async_session()

        async def _read(resp) -> CustomResponse:
            return CustomResponse(resp.status, await resp.read(),
                                  dict(resp.headers))

        async def _attempt(info) -> CustomResponse:
            t = aiohttp.ClientTimeout(total=_clamp_timeout(timeout,
                                                           info.timeout))
            try:
                async with sess.post(url, data=data, headers=headers,
                                     timeout=t) as resp:
                    return await _read(resp)
            except aiohttp.ClientConnectorError:
                # connector errors = never established → the proxy fallback
                # (and retry) are safe, exactly like the sync path
                if self.proxy_url is None:
                    raise
                async with sess.post(
                        f"{self.proxy_url}/{fn_name}" +
                        (f"/{method}" if method else ""),
                        data=data, headers=headers, timeout=t) as resp:
                    return await _read(resp)

        def _aio_retryable(e: BaseException) -> bool:
            if isinstance(e, aiohttp.ClientConnectorError):
                return True          # never established
            return bool(idempotency_key) and isinstance(
                e, (aiohttp.ServerDisconnectedError,
                    aiohttp.ClientPayloadError, aiohttp.ClientOSError,
                    asyncio.TimeoutError))

        self.last_retry_delays = []
        with telemetry.span("client.call", fn=fn_name, method=method or "",
                            request_id=request_id, url=self.base_url) as sp:
            telemetry.inject(headers)
            cr = await policy.arun(
                _attempt,
                retryable_exc=_aio_retryable,
                response_retry_delay=lambda r: _response_retry(
                    r.status, r.body, r, idempotency_key),
                deadline=dl,
                record=self.last_retry_delays)
            sp.set_attr("status", cr.status)
        return cr.result()

    # -- health ---------------------------------------------------------------

    def is_ready(self, launch_id: Optional[str] = None,
                 timeout: float = 2.0) -> bool:
        try:
            params = {"launch_id": launch_id} if launch_id else {}
            r = self._session.get(f"{self.base_url}/ready", params=params,
                                  timeout=timeout)
            return r.status_code == 200
        except _requests.RequestException:
            return False

    # -- metric streaming -----------------------------------------------------

    @staticmethod
    def _format_metrics(text: str) -> str:
        """Compact one-liner from a pod's /metrics exposition: summed HBM
        across devices, in-flight count, request counter."""
        hbm_use = hbm_lim = 0.0
        inflight = reqs = None
        for ln in text.splitlines():
            if not ln.startswith(("kt_", "kubetorch_")):
                continue
            try:
                name, val = ln.rsplit(" ", 1)
                v = float(val)
            except ValueError:
                continue
            if name.startswith("kt_tpu_hbm_bytes_in_use"):
                hbm_use += v
            elif name.startswith("kt_tpu_hbm_bytes_limit"):
                hbm_lim += v
            elif name == "kt_inflight_requests":
                inflight = int(v)
            elif name == "kt_http_requests_total":
                reqs = int(v)
        parts = []
        if hbm_lim:
            parts.append(f"hbm={hbm_use / 2**30:.2f}/{hbm_lim / 2**30:.2f}GiB"
                         f" ({100 * hbm_use / hbm_lim:.0f}%)")
        if inflight is not None:
            parts.append(f"inflight={inflight}")
        if reqs is not None:
            parts.append(f"reqs={reqs}")
        return "  ".join(parts)

    def _resource_scope_line(self) -> Optional[str]:
        """Service-aggregate gauges via PromQL through the controller
        (reference ``scope="resource"`` queries, http_client.py:758-795).
        Needs deploy/metrics.yaml; any failure returns None and the pump
        falls back to pod scope."""
        api = config().api_url
        if not api or not self.service:
            return None
        parts = []
        queries = {
            "hbm_used": f'sum(kt_tpu_hbm_bytes_in_use{{service="{self.service}"}})',
            "inflight": f'sum(kt_inflight_requests{{service="{self.service}"}})',
        }
        for label, q in queries.items():
            try:
                r = _requests.get(f"{api}/controller/metrics/query",
                                  params={"query": q}, timeout=5)
                if r.status_code == 503:
                    # Latch ONLY the controller's own "no metrics stack
                    # configured" sentinel (dedicated header; body match for
                    # older controllers). The query route relays upstream
                    # status codes, so a 503 from a transiently-overloaded
                    # Prometheus must stay retryable — latching it would
                    # disable resource-scope metrics for the client's
                    # lifetime over a blip.
                    if (r.headers.get("X-KT-Unconfigured") == "metrics"
                            or "no metrics stack configured"
                            in r.text[:200]):
                        self._resource_scope_dead = True
                    return None
                results = r.json().get("data", {}).get("result", [])
                if r.status_code == 200 and results:
                    val = float(results[0]["value"][1])
                    parts.append(
                        f"{label}={val / 2**30:.2f}GiB"
                        if label.startswith("hbm") else
                        f"{label}={val:.0f}")
            except (_requests.RequestException, ValueError, KeyError,
                    IndexError):
                return None
        return "  ".join(parts) if parts else None

    def _start_metric_stream(self, interval: Optional[float] = None,
                             scope: str = "pod"):
        """Poll metrics during a call and echo compact lines alongside the
        streamed logs (reference streams DCGM GPU util via PromQL,
        ``http_client.py:758-795``). ``scope="pod"``: the service's own
        /metrics (TPU HBM gauges), via the controller proxy when the pod
        isn't directly reachable. ``scope="resource"``: PromQL aggregates
        across the service's pods, degrading to pod scope when no metrics
        stack answers."""
        stop = threading.Event()
        if interval is None:
            interval = float(os.environ.get("KT_METRIC_STREAM_INTERVAL", "3"))

        def pump():
            # module-level requests, NOT self._session: Session isn't
            # thread-safe and the main thread's POST is in flight
            tick = 0
            while not stop.wait(interval):
                tick += 1
                if scope == "resource" and not self._resource_scope_dead:
                    # exponential backoff on consecutive failures: a fresh
                    # deploy's not-yet-scraped window recovers (unlike a
                    # permanent latch), but a dead/stale controller can't
                    # charge every tick two 5s query timeouts. The explicit
                    # "no stack configured" 503 still latches immediately
                    # (inside _resource_scope_line).
                    if self._resource_scope_fails and (
                            tick % min(2 ** self._resource_scope_fails, 32)):
                        pass
                    else:
                        line = self._resource_scope_line()
                        if line:
                            self._resource_scope_fails = 0
                            print(f"[metrics] {line}", flush=True)
                            continue
                        self._resource_scope_fails += 1
                for url in (self.base_url, self.proxy_url):
                    if not url:
                        continue
                    try:
                        r = _requests.get(f"{url}/metrics", timeout=3)
                    except _requests.RequestException:
                        continue
                    if r.status_code != 200:
                        continue
                    line = self._format_metrics(r.text)
                    if line:
                        print(f"[metrics] {line}", flush=True)
                    break

        threading.Thread(target=pump, daemon=True).start()
        return stop.set

    # -- log streaming --------------------------------------------------------

    def _start_log_stream(self, request_id: str, include_name: bool = True,
                          grace: Optional[float] = None):
        """Poll the controller's log buffer for this request's lines and echo
        them locally (reference streams from Loki over WS; our controller
        exposes the same data over HTTP long-poll)."""
        api = config().api_url
        if not api:
            return None
        stop = threading.Event()
        # Keep draining after the call returns: the pod batches log pushes
        # (~1s) and the controller ingest adds latency, so the lines printed
        # at the end of a request land AFTER its response (the reference's
        # LoggingConfig grace-period behavior, globals.py:61-102).
        if grace is None:
            grace = float(os.environ.get("KT_LOG_STREAM_GRACE", "3.0"))

        def pump():
            seen = 0
            stopped_at = None
            while True:
                if stop.is_set() and stopped_at is None:
                    stopped_at = time.monotonic()
                got = 0
                try:
                    r = _requests.get(
                        f"{api}/controller/logs",
                        params={"request_id": request_id, "offset": seen},
                        timeout=5)
                    if r.status_code == 200:
                        data = r.json()
                        for entry in data.get("entries", []):
                            tag = (entry.get("pod") or "remote"
                                   if include_name else "remote")
                            print(f"[{tag}] {entry['line']}")
                            got += 1
                        seen = data.get("offset", seen)
                except _requests.RequestException:
                    pass
                if stopped_at is not None:
                    elapsed = time.monotonic() - stopped_at
                    # drain until quiet: once the pod's ~1s flush interval has
                    # passed and a fetch comes back empty, everything the
                    # request produced has been echoed; grace bounds it
                    if elapsed >= grace or (got == 0 and elapsed >= 1.25):
                        return
                    time.sleep(0.25)    # Event.wait would return instantly now
                else:
                    stop.wait(0.5)

        def run_pump():
            try:
                pump()
            finally:
                try:
                    _LIVE_PUMPS.remove(t)
                except ValueError:
                    pass

        t = threading.Thread(target=run_pump, daemon=True)
        _LIVE_PUMPS.append(t)
        t.start()

        def stopper():
            # no join here: that would charge every streamed call the ~1.25s
            # quiet-drain minimum. The pump drains in the background; the
            # atexit hook below joins survivors so a one-shot script still
            # sees the trailing lines (batched ~1s in the pod) before exit.
            stop.set()

        return stopper
