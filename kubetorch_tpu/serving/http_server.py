"""The in-pod HTTP server (aiohttp).

Re-design of the reference pod runtime (``serving/http_server.py``, 1971 LoC,
FastAPI/uvicorn — neither exists in this image, and aiohttp's single-loop
model suits the fan-out design anyway). Feature parity map:

- pod identity from env/hostname (reference :146-204)
- metadata application → env contract (reference :254)
- callable/supervisor loading, config-hash keyed, lock-guarded (:878-1134)
- ``TerminationCheckMiddleware`` racing requests vs SIGTERM, with typed
  ``PodTerminatedError`` carrying OOMKilled/Evicted/**TPU-preemption** reasons
  (:1184-1235 + serving/utils.py:111-191)
- ``X-Request-ID`` propagation (:1237-1249)
- routes: /health, /ready?launch_id, /metrics, /app/status,
  POST /{fn}[/{method}] (:1645-1946)
- serialization negotiation via ``X-Serialization`` with server-side
  allowlist (:1768-1891)
- exception packaging (:1478-1530)
- hot reload: re-apply metadata → re-sync code → recreate supervisor → new
  launch_id, no process restart (:352-410)

Run: ``python -m kubetorch_tpu.serving.http_server --port 32300``.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import hashlib
import json
import os
import re
import signal
import socket
import sys
import time
import uuid
from typing import Any, Dict, Optional

from aiohttp import web

from .. import serialization as ser
from .. import telemetry
from ..exceptions import (AdmissionShedError, DeadlineExceededError,
                          KubetorchError, PodTerminatedError,
                          SerializationError, WorkerDiedError,
                          package_exception)
from ..resilience import DEADLINE_HEADER, Deadline, IdempotencyCache
from ..parallel.mesh import DistributedConfig
from ..resources.pointers import Pointers
from .env_contract import (KT_ALLOWED_SERIALIZATION, KT_CALLABLE_TYPE,
                           KT_CLS_OR_FN_NAME, KT_DISTRIBUTED_CONFIG,
                           KT_FILE_PATH, KT_INIT_ARGS, KT_LAUNCH_ID,
                           KT_MODULE_NAME, KT_NAMESPACE, KT_PROJECT_ROOT,
                           KT_SERVICE_NAME, apply_metadata)
from .supervisor_factory import supervisor_for

from ..constants import server_port
request_id_var: contextvars.ContextVar[str] = contextvars.ContextVar(
    "kt_request_id", default="")

RESERVED_ROUTES = {"health", "ready", "metrics", "app", "_kt", "debug"}

# probes and the observability surface itself are never spanned: a 3s
# scrape cadence would churn the whole trace ring in minutes (they still
# get X-Request-ID — the header contract covers every response)
TRACE_EXEMPT_PATHS = ("/health", "/ready", "/metrics", "/debug/traces")


class ServerState:
    """All mutable pod-runtime state, attachable to a fresh app per test."""

    def __init__(self):
        self.pod_name = os.environ.get("POD_NAME", socket.gethostname())
        self.namespace = os.environ.get(KT_NAMESPACE, "default")
        self.launch_id: Optional[str] = os.environ.get(KT_LAUNCH_ID)
        self.termination = asyncio.Event()
        self.termination_reason: Optional[str] = None
        self.supervisor = None
        self._supervisor_key: Optional[str] = None
        self._prewarm_task: Optional[asyncio.Task] = None
        self._prewarm_error: Optional[str] = None
        self._load_lock = asyncio.Lock()
        self.started_at = time.time()
        self.request_count = 0
        self.inflight = 0          # concurrency signal for the autoscaler
        self.last_activity = time.time()
        self.log_capture = None
        self.metrics_pusher = None
        self.controller_ws = None
        self.app_process = None
        self.blobd_proc = None
        # retried-POST dedupe (see resilience.IdempotencyCache): a client
        # that retries with X-KT-Idempotency-Key must never execute twice
        self.idempotency = IdempotencyCache(
            ttl_s=float(os.environ.get("KT_IDEMPOTENCY_TTL_S", "600")),
            max_entries=int(os.environ.get("KT_IDEMPOTENCY_MAX", "1024")))

    # -- metadata / supervisor ------------------------------------------------

    def allowed_serialization(self):
        raw = os.environ.get(KT_ALLOWED_SERIALIZATION)
        if raw:
            return [s.strip() for s in raw.split(",") if s.strip()]
        return list(ser.DEFAULT_ALLOWED)

    def pointers(self) -> Optional[Pointers]:
        if not os.environ.get(KT_CLS_OR_FN_NAME):
            return None
        return Pointers(
            project_root=os.environ.get(KT_PROJECT_ROOT, os.getcwd()),
            module_name=os.environ.get(KT_MODULE_NAME, ""),
            file_path=os.environ.get(KT_FILE_PATH, ""),
            cls_or_fn_name=os.environ[KT_CLS_OR_FN_NAME],
        )

    def distributed_config(self) -> Optional[DistributedConfig]:
        raw = os.environ.get(KT_DISTRIBUTED_CONFIG)
        if not raw:
            return None
        return DistributedConfig.from_dict(json.loads(raw))

    def init_args(self) -> Optional[Dict]:
        raw = os.environ.get(KT_INIT_ARGS)
        return json.loads(raw) if raw else None

    def _config_key(self) -> str:
        blob = json.dumps({
            "ptr": os.environ.get(KT_CLS_OR_FN_NAME),
            "mod": os.environ.get(KT_MODULE_NAME),
            "dist": os.environ.get(KT_DISTRIBUTED_CONFIG),
            "init": os.environ.get(KT_INIT_ARGS),
        }, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    async def get_supervisor(self):
        """Config-hash-keyed supervisor (reference load_supervisor :971)."""
        if (self.supervisor is not None
                and self._config_key() == self._supervisor_key):
            return self.supervisor
        async with self._load_lock:
            # recompute INSIDE the lock: a reload may have changed the env
            # while we waited, and building from new env under a stale key
            # would force an immediate tear-down/rebuild of warming workers
            key = self._config_key()
            if self.supervisor is not None and key == self._supervisor_key:
                return self.supervisor
            if self.supervisor is not None:
                await asyncio.to_thread(self.supervisor.cleanup)
            pointers = self.pointers()
            if pointers is None:
                raise KubetorchError(
                    "No callable configured on this pod (missing metadata)")
            sup = supervisor_for(
                self.distributed_config(), pointers, self.init_args(),
                service_name=os.environ.get(KT_SERVICE_NAME, ""),
                namespace=self.namespace,
                server_port=server_port(),
                fn_name=pointers.cls_or_fn_name,
            )
            await asyncio.to_thread(sup.setup)
            self.supervisor = sup
            self._supervisor_key = key
            return sup

    async def reload(self, metadata: Dict[str, Any], launch_id: str) -> None:
        """Hot reload (reference _handle_reload :352): metadata → code sync →
        supervisor recreation → only then flip the launch_id."""
        apply_metadata(metadata)
        await self._sync_code()
        # replay changed dockerfile instructions (reference run_image_setup)
        dockerfile = os.environ.get("KT_DOCKERFILE") or metadata.get("KT_DOCKERFILE")
        if dockerfile:
            from .image_setup import run_image_setup
            await run_image_setup(dockerfile, state=self)
        if os.environ.get("KT_APP_CMD") and not dockerfile:
            from .image_setup import start_app_process
            await start_app_process(self, os.environ["KT_APP_CMD"])
        async with self._load_lock:
            if self.supervisor is not None:
                await asyncio.to_thread(self.supervisor.cleanup)
                self.supervisor = None
                self._supervisor_key = None
            # purge the user's modules under the same lock so a queued call
            # can't rebuild a supervisor from the stale module cache. Never
            # purge the runtime itself or __main__ (mp spawn needs it, and
            # the user's project root may contain this package).
            root = os.environ.get(KT_PROJECT_ROOT)
            if root:
                for name, mod in list(sys.modules.items()):
                    if name == "__main__" or name.split(".")[0] == "kubetorch_tpu":
                        continue
                    f = getattr(mod, "__file__", None)
                    if f and f.startswith(root) and "site-packages" not in f:
                        sys.modules.pop(name, None)
            self.launch_id = launch_id
            os.environ[KT_LAUNCH_ID] = launch_id
        # open the load+warmup window NOW (readiness gates on it) instead of
        # on the first request — otherwise the warmup hook defers to exactly
        # the request it was supposed to pre-pay
        self.prewarm_supervisor()

    def prewarm_supervisor(self) -> None:
        """Fire-and-forget supervisor creation so rank workers start their
        eager load + ``__kt_warmup__`` immediately and ``/ready`` can observe
        the warming window. A failure is recorded for ``/ready`` (a pod that
        cannot build its supervisor must not join the endpoint pool) and the
        same error resurfaces, typed, on the first direct call — which also
        retries the build."""
        # a new config supersedes any previous prewarm outcome — a stale
        # error must not keep /ready at 503 for a config it doesn't describe
        self._prewarm_error = None
        if self.pointers() is None:
            # drop a finished task's handle; an in-flight one stays tracked
            # so cleanup still awaits it
            if self._prewarm_task is not None and self._prewarm_task.done():
                self._prewarm_task = None
            return

        async def _go():
            try:
                await self.get_supervisor()
                self._prewarm_error = None
            except Exception as e:  # noqa: BLE001
                self._prewarm_error = f"{type(e).__name__}: {e}"
                print(f"[kt] supervisor prewarm failed (will retry on first "
                      f"call): {e}")

        self._prewarm_task = asyncio.create_task(_go())

    async def _sync_code(self) -> None:
        """Pull latest code from the data store (reference rsync pull :1140).

        No code tree in the store + a locally-present project root means the
        client shares our filesystem (local backend) and never pushed —
        nothing to sync. A missing tree with a missing root is a real error.
        """
        store_url = os.environ.get("KT_DATA_STORE_URL")
        service = os.environ.get(KT_SERVICE_NAME)
        root = os.environ.get(KT_PROJECT_ROOT)
        if not (store_url and service and root):
            return
        from ..data_store.sync import pull_tree
        from ..exceptions import SyncError
        try:
            await asyncio.to_thread(pull_tree, store_url,
                                    f"__code__/{service}", root)
        except SyncError as e:
            if "No tree" in str(e) and os.path.isdir(root):
                return
            raise

    def terminate(self, reason: str) -> None:
        self.termination_reason = reason
        self.termination.set()
        # the watchdog classifies a rank's SIGTERM during this drain window
        # as Evicted/Preempted rather than an anonymous kill
        from .watchdog import set_draining
        set_draining(reason)


# ---------------------------------------------------------------------------
# Middleware
# ---------------------------------------------------------------------------


@web.middleware
async def request_id_middleware(request: web.Request, handler):
    """Outermost middleware: request-id binding + the server span.

    Every response — success, middleware short-circuit (504 deadline
    rejection, 503 recovering/terminating, idempotent replay), and
    ``HTTPException`` raises — carries ``X-Request-ID`` back, so a client
    holding only the id can always find the failing request in logs and
    traces. The span continues the client's ``X-KT-Trace`` context when
    present (its id is echoed in ``X-KT-Trace-Id``); chaos, deadline, and
    idempotency middlewares all run inside it, so injected faults and
    rejections land on the request's own span."""
    rid = request.headers.get("X-Request-ID") or uuid.uuid4().hex[:16]
    request_id_var.set(rid)
    request["kt_request_id"] = rid
    if request.path.startswith(TRACE_EXEMPT_PATHS):
        sp = telemetry.NOOP_SPAN
    else:
        sp = telemetry.span("server.request",
                            parent=telemetry.extract(request.headers),
                            request_id=rid, path=request.path,
                            method=request.method)
    with sp:
        try:
            resp = await handler(request)
        except web.HTTPException as e:
            # aiohttp exception-responses bypass the normal return path —
            # they must not lose the id
            e.headers["X-Request-ID"] = rid
            sp.set_attr("status", e.status)
            raise
        resp.headers["X-Request-ID"] = rid
        if sp:
            sp.set_attr("status", resp.status)
            resp.headers.setdefault("X-KT-Trace-Id", sp.trace_id)
    return resp


@web.middleware
async def deadline_middleware(request: web.Request, handler):
    """Enforce the client's propagated deadline (``X-KT-Deadline``, absolute
    unix seconds) before AND during dispatch: a request that arrives past
    its deadline — or runs past it — gets a rehydratable
    ``DeadlineExceededError`` instead of burning a TPU slot on work the
    client already abandoned."""
    deadline = Deadline.from_header(request.headers.get(DEADLINE_HEADER))
    if deadline is None:
        return await handler(request)
    if deadline.expired():
        return _error_response(DeadlineExceededError(
            f"request arrived {-deadline.remaining():.3f}s past its "
            f"deadline; not dispatched", deadline=deadline.at), status=504)
    try:
        return await asyncio.wait_for(handler(request),
                                      timeout=deadline.remaining())
    except asyncio.TimeoutError:
        return _error_response(DeadlineExceededError(
            "deadline expired during dispatch; handler cancelled",
            deadline=deadline.at), status=504)


@web.middleware
async def idempotency_middleware(request: web.Request, handler):
    """Dedupe retried POSTs carrying ``X-KT-Idempotency-Key``: the first
    execution's response is recorded in a TTL cache and replayed for any
    retry of the same key, so a client-side retry never runs the user
    function twice. Concurrent duplicates await the original execution
    instead of racing it."""
    key = request.headers.get("X-KT-Idempotency-Key")
    if not key or request.method != "POST":
        return await handler(request)
    state: ServerState = request.app["state"]
    cache = state.idempotency
    entry = cache.lookup(key)
    if entry is None and key in cache.inflight:
        try:
            entry = await asyncio.shield(cache.inflight[key])
        except Exception:
            entry = None            # original died; fall through and execute
    if entry is not None:
        status, body, headers = entry
        return web.Response(status=status, body=body,
                            headers={**headers,
                                     "X-KT-Idempotent-Replay": "1"})
    fut = asyncio.get_running_loop().create_future()
    cache.inflight[key] = fut
    try:
        resp = await handler(request)
        body = resp.body if isinstance(getattr(resp, "body", None), bytes) \
            else None
        if body is not None:
            headers = {k: resp.headers[k]
                       for k in ("Content-Type", "X-Serialization")
                       if k in resp.headers}
            entry = (resp.status, body, headers)
            cache.store(key, entry)
            fut.set_result(entry)
        else:
            # streaming/file response: not replayable — drop the claim so a
            # retry re-executes rather than hanging on a never-set future
            fut.set_exception(KubetorchError("response not replayable"))
        return resp
    except BaseException as e:
        if not fut.done():
            fut.set_exception(
                KubetorchError(f"original execution failed: {e}"))
        raise
    finally:
        cache.inflight.pop(key, None)
        # a consumed exception on an unawaited future is expected noise
        if fut.done() and fut.exception() is not None:
            fut.exception()


@web.middleware
async def termination_middleware(request: web.Request, handler):
    """Race the handler against pod termination (reference :1184-1235)."""
    state: ServerState = request.app["state"]
    if state.termination.is_set():
        return _error_response(PodTerminatedError(
            "Pod is terminating", reason=state.termination_reason,
            pod_name=state.pod_name), status=503)
    handler_task = asyncio.ensure_future(handler(request))
    term_task = asyncio.ensure_future(state.termination.wait())
    try:
        done, _ = await asyncio.wait({handler_task, term_task},
                                     return_when=asyncio.FIRST_COMPLETED)
        if handler_task in done:
            return handler_task.result()
        handler_task.cancel()
        return _error_response(PodTerminatedError(
            "Pod was terminated while handling the request",
            reason=state.termination_reason, pod_name=state.pod_name),
            status=503)
    finally:
        term_task.cancel()


def _error_response(exc: BaseException, status: int = 500) -> web.Response:
    return web.json_response(package_exception(exc), status=status)


# ---------------------------------------------------------------------------
# Routes
# ---------------------------------------------------------------------------


async def health(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    sup = state.supervisor
    body = {
        "status": "ok",
        "pod": state.pod_name,
        "launch_id": state.launch_id,
        "uptime_s": round(time.time() - state.started_at, 1),
        "supervisor_healthy": bool(sup and sup.healthy),
    }
    # watchdog restart state (ISSUE 3): deaths, budget remaining, whether
    # the pool is mid-respawn or permanently failed — the operator's view
    # of worker-level self-healing
    if sup is not None and hasattr(sup, "restart_state"):
        try:
            body["workers"] = sup.restart_state()
        except Exception:  # noqa: BLE001 — health must never 500 over this
            pass
    # serving front door (ISSUE 9): admission/affinity/batching accounting
    # for load_balanced services — the operator's `kt serve status` source
    if sup is not None and hasattr(sup, "router_state"):
        try:
            body["router"] = sup.router_state()
        except Exception:  # noqa: BLE001 — health must never 500 over this
            pass
    # elastic pipeline parallelism (ISSUE 17): stage membership epoch,
    # bubble fraction, and recent re-groups — the operator's view of a
    # pipe that degraded around a lost stage instead of stalling
    if sup is not None and hasattr(sup, "pipeline_state"):
        try:
            body["pipeline"] = sup.pipeline_state()
        except Exception:  # noqa: BLE001 — health must never 500 over this
            pass
    return web.json_response(body)


async def ready(request: web.Request) -> web.Response:
    """Reload-completion barrier (reference :1670): ready only when the pod's
    launch_id matches the client's freshly deployed one AND the rank workers
    have finished their load+warmup window (``__kt_warmup__`` pays jit
    compilation before the pod joins the endpoint pool)."""
    state: ServerState = request.app["state"]
    want = request.query.get("launch_id")
    if want and want != state.launch_id:
        return web.json_response(
            {"ready": False, "launch_id": state.launch_id, "expected": want},
            status=409)
    # the whole load+warmup window: supervisor being built (prewarm task in
    # flight), rank workers still warming, or a rank that DIED during warmup
    # (a pod that can never serve must not report ready)
    task = state._prewarm_task
    if task is not None and not task.done():
        return web.json_response(
            {"ready": False, "launch_id": state.launch_id, "warming": True},
            status=503)
    if state._prewarm_error is not None and state.supervisor is None:
        return web.json_response(
            {"ready": False, "launch_id": state.launch_id,
             "error": state._prewarm_error}, status=503)
    sup = state.supervisor
    if sup is not None and (getattr(sup, "warming", False)
                            or getattr(sup, "recovering", False)
                            or not getattr(sup, "healthy", True)):
        # recovering: the watchdog is respawning dead ranks — readiness
        # flips down for the recovery window and back up once healed
        # (permanent restart-budget exhaustion keeps healthy False forever,
        # so /ready stays down for good)
        return web.json_response(
            {"ready": False, "launch_id": state.launch_id,
             "warming": bool(getattr(sup, "warming", False)),
             "recovering": bool(getattr(sup, "recovering", False)),
             "healthy": bool(getattr(sup, "healthy", True))}, status=503)
    return web.json_response({"ready": True, "launch_id": state.launch_id})

async def metrics(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    try:
        from prometheus_client import generate_latest, REGISTRY
        body = generate_latest(REGISTRY)
    except Exception:
        body = b""
    from .metrics_push import tpu_gauges
    lines = {
        "kubetorch_last_activity_timestamp": state.last_activity,
        "kt_http_requests_total": state.request_count,
        "kt_inflight_requests": state.inflight,
        # HBM gauges on the SCRAPE endpoint too (not just the push loop):
        # Prometheus (deploy/metrics.yaml) and live client streaming read
        # the TPU signal from here. Off-loop: memory_stats() can stall on a
        # busy chip and a 3s-interval scraper must not block /health.
        **(await asyncio.to_thread(tpu_gauges)),
    }
    # user gauges: rank 0's __kt_metrics__ hook (the __kt_warmup__ sibling)
    # — serving state like the generation engine's tokens/s and slot
    # occupancy, merged under kt_user_. Best-effort with a short cap: a
    # stuck rank must not wedge the 3s scraper.
    sup = state.supervisor
    if (sup is not None and getattr(sup, "pool", None) is not None
            and not getattr(sup, "warming", False)):
        # warming gate: the worker loop doesn't poll its queue until the
        # load+warmup window ends — submitting during it would stall every
        # scrape for the full timeout AND backlog one stale op per scrape
        try:
            user = await asyncio.wait_for(sup.pool.user_metrics(0),
                                          timeout=3.0)
        except Exception:  # noqa: BLE001
            user = {}
        for k, v in (user or {}).items():
            safe = re.sub(r"[^a-zA-Z0-9_]", "_", str(k))
            lines[f"kt_user_{safe}"] = v
    # TYPE-headed exposition (ISSUE 5): the registry (stage histograms,
    # retry/death/chaos counters) + the state-derived gauge lines above,
    # label-escaped and grouped — never hand-joined "k v" pairs.
    extra = (telemetry.REGISTRY.render()
             + telemetry.render_untyped_gauges(lines)).encode()
    return web.Response(body=body + extra, content_type="text/plain")


async def debug_traces(request: web.Request) -> web.Response:
    """``GET /debug/traces[?q=<request_id|trace_id>][&limit=N]`` — this
    process's span ring (including rank-worker spans shipped back over the
    response queue). The flight recorder behind ``kt trace``."""
    limit = None
    try:
        if request.query.get("limit"):
            limit = max(1, int(request.query["limit"]))
    except ValueError:
        return web.json_response({"error": "bad limit"}, status=400)
    return web.json_response(telemetry.debug_traces_payload(
        request.query.get("q") or request.query.get("request_id"),
        limit=limit))


async def app_status(request: web.Request) -> web.Response:
    state: ServerState = request.app["state"]
    proc = state.app_process
    if proc is None:
        return web.json_response({"running": False}, status=404)
    running = proc.returncode is None
    return web.json_response({"running": running, "returncode": proc.returncode})


async def rollout_status(request: web.Request) -> web.Response:
    """Live weight-rollout state of every engine coordinator in THIS
    process (ISSUE 11): per-replica manifest version, fingerprint, canary
    phase, bytes moved by source — the rows ``kt rollout status``
    aggregates across the fleet. Engines whose coordinator runs in a rank
    worker surface through the pod's ``/metrics`` (``kt_rollout_*``)
    instead; an empty list here just means no in-process rollout."""
    def _collect():
        try:
            from ..serve.rollout import local_status
            return local_status()
        except Exception:       # noqa: BLE001 — serve/ absent or jax-less
            return []

    rollouts = await asyncio.to_thread(_collect)
    return web.json_response({"rollouts": rollouts})


async def reload_route(request: web.Request) -> web.Response:
    """HTTP reload path (controller WS push calls state.reload directly)."""
    state: ServerState = request.app["state"]
    try:
        body = json.loads(await request.read())
        await state.reload(body.get("metadata", {}),
                           body.get("launch_id", uuid.uuid4().hex))
        return web.json_response({"ok": True, "launch_id": state.launch_id})
    except BaseException as e:  # noqa: BLE001
        return _error_response(e)


async def profile_route(request: web.Request) -> web.Response:
    """POST /_kt/profile {duration_s} → capture a jax.profiler trace in the
    rank-0 subprocess, return it as a tar.gz (TensorBoard-loadable)."""
    state: ServerState = request.app["state"]
    try:
        body = json.loads(await request.read() or b"{}")
        sup = await state.get_supervisor()
        result = await sup.pool.profile(
            duration_s=float(body.get("duration_s", 3.0)))
        import io
        import tarfile

        def _tar() -> bytes:
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                tar.add(result["trace_dir"],
                        arcname=os.path.basename(result["trace_dir"]))
            return buf.getvalue()

        # real traces are tens of MB — never compress on the event loop
        # (stalled /health probes would make this pod look dead mid-profile)
        payload = await asyncio.to_thread(_tar)
        return web.Response(body=payload,
                            content_type="application/gzip",
                            headers={"X-KT-Trace-Dir": result["trace_dir"]})
    except BaseException as e:  # noqa: BLE001
        return _error_response(e)


async def serve_cached_data(request: web.Request) -> web.Response:
    """P2P broadcast parent role (reference PodDataServer TCP serving,
    pod_data_server.py:668-745 — TPU redesign per SURVEY §2.9: host-staged
    bytes over the pod's existing HTTP server instead of a CUDA-IPC daemon):
    serve a data-store key this pod already fetched, so later joiners in the
    fan-out pull from us instead of the central store."""
    from ..data_store.peer_cache import cache_get

    key = request.match_info["key"]
    entry = await asyncio.to_thread(cache_get, key)
    if entry is None:
        return web.json_response({"error": "not cached"}, status=404)
    data, meta = entry
    import json as _json
    return web.Response(body=data, content_type="application/octet-stream",
                        headers={"X-KT-Meta": _json.dumps(meta)})


async def exec_route(request: web.Request) -> web.Response:
    """POST /_kt/exec {"cmd": ..., "timeout": ...} → {rc, stdout, stderr}.

    Backs ``Compute.run_bash``/``pip_install`` (reference pod ops,
    compute.py:2400-2493). The reference reaches pods via ``kubectl exec``;
    here the pod's own server runs the command, so the same surface works on
    the local backend and through the controller's service proxy without
    kubectl credentials. No privilege escalation: this server already
    executes arbitrary user callables by design."""
    body = await request.json()
    cmd = body.get("cmd")
    if not cmd:
        return web.json_response({"error": "missing cmd"}, status=400)
    timeout = float(body.get("timeout", 600))
    try:
        proc = await asyncio.create_subprocess_shell(
            cmd, stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE)
        out, err = await asyncio.wait_for(proc.communicate(), timeout)
    except asyncio.TimeoutError:
        with contextlib.suppress(ProcessLookupError):
            proc.kill()
        return web.json_response({"rc": -1, "stdout": "",
                                  "stderr": f"timed out after {timeout}s"})
    return web.json_response({
        "rc": proc.returncode,
        "stdout": out.decode(errors="replace"),
        "stderr": err.decode(errors="replace"),
    })


async def run_callable(request: web.Request) -> web.Response:
    """POST /{fn}[/{method}] → supervisor (reference run_callable :1720)."""
    state: ServerState = request.app["state"]
    state.request_count += 1
    state.inflight += 1
    state.last_activity = time.time()
    try:
        return await _run_callable_inner(request, state)
    finally:
        state.inflight -= 1
        state.last_activity = time.time()


async def _run_callable_inner(request: web.Request,
                              state: "ServerState") -> web.Response:
    fn_name = request.match_info["fn_name"]
    method = request.match_info.get("method") or None
    fmt = request.headers.get("X-Serialization", ser.JSON)
    try:
        raw = await request.read()
        try:
            with telemetry.stage("deserialize", bytes=len(raw), fmt=fmt):
                body = ser.deserialize(
                    raw, fmt, allowed=state.allowed_serialization()) or {}
        except SerializationError as e:
            return _error_response(e, status=415)

        sup = await state.get_supervisor()
        expected = sup.pointers.cls_or_fn_name if sup.pointers else None
        if expected and fn_name != expected:
            return _error_response(
                KubetorchError(f"This service hosts {expected!r}, not {fn_name!r}"),
                status=404)

        args = body.get("args", [])
        kwargs = body.get("kwargs", {})
        is_subcall = request.query.get("distributed_subcall") == "true"
        call_kwargs: Dict[str, Any] = {}
        if is_subcall:
            call_kwargs["subtree"] = body.get("_kt_subtree") or []
            if body.get("_kt_sel_ips"):
                call_kwargs["sel_ips"] = body["_kt_sel_ips"]
        elif "_kt_workers" in body:
            call_kwargs["workers"] = body.pop("_kt_workers")
        if hasattr(sup, "server_port"):
            fwd = {"X-Request-ID": request["kt_request_id"],
                   "X-Serialization": ser.JSON}
            # the front-door vocabulary must survive the hop: the router
            # sheds on the deadline and tier, and the peer pod re-enforces
            # the deadline on the forwarded leg (ISSUE 9)
            from ..constants import PRIORITY_HEADER, SESSION_HEADER
            for h in (DEADLINE_HEADER, PRIORITY_HEADER, SESSION_HEADER):
                if request.headers.get(h):
                    fwd[h] = request.headers[h]
            call_kwargs.setdefault("headers", fwd)

        if body.get("debugger"):
            from .pdb_ws import arm_debugger
            arm_debugger(body["debugger"])

        with telemetry.stage("execute", fn=fn_name, method=method or ""):
            result = await sup.call(method, args, kwargs, **call_kwargs)
        return web.Response(body=ser.serialize(result, fmt),
                            headers={"X-Serialization": fmt},
                            content_type="application/octet-stream"
                            if fmt != ser.JSON else "application/json")
    except (PodTerminatedError, WorkerDiedError) as e:
        # infra faults, not user errors: 503 so load balancers shed traffic
        # while the watchdog restarts the rank pool
        return _error_response(e, status=503)
    except AdmissionShedError as e:
        # the front door refused before prefill: typed 429 + the router's
        # backpressure hint, so clients back off instead of hammering
        resp = _error_response(e, status=429)
        if e.retry_after is not None:
            resp.headers["Retry-After"] = f"{max(e.retry_after, 0.0):.3f}"
        return resp
    except DeadlineExceededError as e:
        # router-level shed of an expired deadline (the middleware catches
        # arrivals; this catches expiry inside the admission queue)
        return _error_response(e, status=504)
    except BaseException as e:  # noqa: BLE001
        return _error_response(e)


# ---------------------------------------------------------------------------
# App assembly / lifespan
# ---------------------------------------------------------------------------


def create_app(state: Optional[ServerState] = None) -> web.Application:
    # order matters: request-id first; chaos next (faults model the network,
    # so they hit before any server logic); deadline before the dedupe cache
    # (an expired replay is still expired); idempotency outside termination
    # so the cached entry is exactly what the client saw.
    middlewares = [request_id_middleware, deadline_middleware,
                   idempotency_middleware, termination_middleware]
    from ..chaos import maybe_chaos_middleware
    chaos_mw, chaos_engine = maybe_chaos_middleware()
    if chaos_mw is not None:
        middlewares.insert(1, chaos_mw)
    app = web.Application(middlewares=middlewares,
                          client_max_size=1024 ** 3)
    app["chaos"] = chaos_engine
    app["state"] = state or ServerState()
    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/traces", debug_traces)
    app.router.add_get("/app/status", app_status)
    app.router.add_get("/rollout/status", rollout_status)
    app.router.add_post("/_kt/reload", reload_route)
    app.router.add_post("/_kt/profile", profile_route)
    app.router.add_post("/_kt/exec", exec_route)
    app.router.add_get("/_kt/data/{key:.+}", serve_cached_data)
    app.router.add_post("/{fn_name}", run_callable)
    app.router.add_post("/{fn_name}/{method}", run_callable)
    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)
    return app


async def _on_startup(app: web.Application) -> None:
    state: ServerState = app["state"]

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(
                sig, lambda s=sig: state.terminate(_termination_reason()))
        except (NotImplementedError, RuntimeError):
            pass

    # observability
    from .log_capture import LogCapture
    from .metrics_push import MetricsPusher
    if os.environ.get("KT_LOG_SINK_URL"):
        state.log_capture = LogCapture.start_global(
            sink_url=os.environ["KT_LOG_SINK_URL"],
            labels={"service": os.environ.get(KT_SERVICE_NAME, ""),
                    "pod": state.pod_name, "namespace": state.namespace})
    if os.environ.get("KT_METRICS_GATEWAY_URL"):
        state.metrics_pusher = MetricsPusher(
            gateway_url=os.environ["KT_METRICS_GATEWAY_URL"], state=state)
        state.metrics_pusher.start()

    # native bulk-transfer daemon (reference PodDataServer role): serves the
    # peer cache over epoll+sendfile so fan-out bulk bytes never ride the
    # Python event loop. Children learn the port via the store's /route
    # registry; rank workers inherit KT_BLOBD_PORT for their registrations.
    # Pod-only (POD_IP): without an advertisable address the fetchers can
    # never route to it, and an unadvertised 0.0.0.0 listener is pure risk.
    if os.environ.get("POD_IP"):
        from ..native import spawn_blobd
        from ..data_store.peer_cache import cache_dir
        proc, port = spawn_blobd(str(cache_dir()),
                                 host=os.environ["POD_IP"])
        if port is not None:
            state.blobd_proc = proc
            os.environ["KT_BLOBD_PORT"] = str(port)

    # controller WebSocket (metadata + reload push)
    ws_url = os.environ.get("KT_CONTROLLER_WS_URL")
    if ws_url:
        from .controller_ws import ControllerWebSocket
        state.controller_ws = ControllerWebSocket(ws_url, state)
        await state.controller_ws.start()

    # env-driven metadata (BYO pods, `kt server start`): open the load+warmup
    # window now so /ready gates on it; WS-driven pods prewarm from reload()
    state.prewarm_supervisor()


def _termination_reason() -> str:
    """Classify why we are being killed (reference serving/utils.py:111-191).

    On GKE TPU slices, maintenance/preemption arrives as SIGTERM with a node
    taint; we surface it as ``Preempted`` so clients can programmatically
    resize/retry rather than treating it as a crash.
    """
    if os.environ.get("KT_PREEMPTIBLE") or os.path.exists(
            "/var/run/kubetorch/preemption"):
        return "Preempted"
    return os.environ.get("KT_TERMINATION_REASON", "Terminated")


async def _on_cleanup(app: web.Application) -> None:
    state: ServerState = app["state"]
    if state.controller_ws is not None:
        await state.controller_ws.stop()
    # a prewarm in flight is building a supervisor (spawning TPU-holding
    # workers): wait for it, so the cleanup below actually reaches that pool
    # instead of orphaning mid-compile subprocesses
    if state._prewarm_task is not None and not state._prewarm_task.done():
        # gather(return_exceptions) also absorbs CancelledError: even a
        # cancelled shutdown must fall through to supervisor.cleanup()
        await asyncio.gather(state._prewarm_task, return_exceptions=True)
    if state.supervisor is not None:
        await asyncio.to_thread(state.supervisor.cleanup)
    if state.metrics_pusher is not None:
        state.metrics_pusher.stop()
    if state.log_capture is not None:
        state.log_capture.stop()
    from .remote_worker_pool import RemoteWorkerPool
    if RemoteWorkerPool._instance is not None:
        await RemoteWorkerPool._instance.close()
    if state.blobd_proc is not None and state.blobd_proc.poll() is None:
        state.blobd_proc.terminate()


def main(argv: Optional[list] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description="kubetorch-tpu pod server")
    p.add_argument("--port", type=int, default=server_port())
    p.add_argument("--host", default="0.0.0.0")
    args = p.parse_args(argv)
    if args.port == 0:
        # bind-ephemeral: resolve the real port BEFORE advertising, or the
        # WS registration and peer subcalls would publish the unroutable :0
        from ..utils.procs import free_port

        args.port = free_port()
    # Advertise the BOUND port to everything that derives URLs from env —
    # the controller-WS registration and the supervisor's peer subcalls —
    # regardless of how the server was launched (CLI, -m, embedder). A
    # --port flag alone must not leave them pointing at the default.
    os.environ["KT_SERVER_PORT"] = str(args.port)
    # flight recorder (ISSUE 20): armed only when KT_OBS_SPOOL is set —
    # then this pod's telemetry history survives its own SIGKILL
    from ..obs import maybe_start_recorder
    maybe_start_recorder("pod")
    asyncio.run(_serve(create_app(), args.host, args.port))


async def _serve(app: web.Application, host: str, port: int) -> None:
    """Run until SIGTERM/SIGINT, then drain and exit (k8s semantics: a pod
    must vacate before the kubelet's SIGKILL; locally, an orphaned pod that
    kept serving would squat its IP:port and wedge every revival after a
    controller restart). ``web.run_app`` can't express this — the signal
    handlers installed in ``_on_startup`` only set the termination flag, so
    the serve loop below owns the actual shutdown."""
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()          # fires on_startup (installs handlers)
    await web.TCPSite(runner, host, port).start()
    state: ServerState = app["state"]
    await state.termination.wait()
    deadline = time.monotonic() + float(
        os.environ.get("KT_TERMINATION_DRAIN_S", "25"))
    while state.inflight > 0 and time.monotonic() < deadline:
        await asyncio.sleep(0.25)
    await runner.cleanup()        # fires on_cleanup (pools, WS, capture)


if __name__ == "__main__":
    # Re-import under the canonical name: ``python -m ...http_server`` makes
    # this file ``__main__``, and building the app from that duplicate module
    # would split every module-level singleton — request_id_var above, the
    # ServerState caches — from the copies the rest of the package imports
    # (symptom: rank logs lose their request-id labels because the middleware
    # sets one ContextVar and ProcessPool._submit reads another).
    from kubetorch_tpu.serving.http_server import main as _canonical_main

    _canonical_main()
