"""Image-setup cache: replay pseudo-dockerfile instructions inside a live pod.

Reference (``serving/http_server.py:510-831``): the new dockerfile is diffed
line-by-line against the last-applied one and only instructions from the
first mismatch onward are replayed — RUN via shell (with
``$KT_PIP_INSTALL_CMD`` substitution), ENV into the process env, COPY a
no-op (ktsync already placed files), CMD (re)starts the app process. A
pip-freeze diff evicts changed modules from ``sys.modules`` so new package
versions are importable without a pod restart.
"""

from __future__ import annotations

import asyncio
import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

_CACHED_DOCKERFILE: List[str] = []
_PIP_INSTALL_CMD = os.environ.get("KT_PIP_INSTALL_CMD", f"{sys.executable} -m pip install")


def _parse(dockerfile: str) -> List[Tuple[str, str]]:
    out = []
    for line in dockerfile.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or line.upper().startswith("FROM "):
            continue
        kind, _, value = line.partition(" ")
        out.append((kind.upper(), value.strip()))
    return out


def first_mismatch(old: List[Tuple[str, str]], new: List[Tuple[str, str]]) -> int:
    for i, (a, b) in enumerate(zip(old, new)):
        if a != b:
            return i
    return min(len(old), len(new))


async def run_image_setup(dockerfile: str, state=None) -> Dict:
    """Apply only the changed suffix of the dockerfile. Returns stats."""
    global _CACHED_DOCKERFILE

    new = _parse(dockerfile)
    old = _parse("\n".join(_CACHED_DOCKERFILE))
    start = first_mismatch(old, new)
    replayed = 0
    pip_touched = any("pip install" in v.replace("$KT_PIP_INSTALL_CMD",
                                                 _PIP_INSTALL_CMD)
                      for k, v in new[start:] if k == "RUN")
    before = _installed_versions() if pip_touched else {}
    for kind, value in new[start:]:
        if kind == "RUN":
            cmd = value.replace("$KT_PIP_INSTALL_CMD", _PIP_INSTALL_CMD)
            proc = await asyncio.create_subprocess_shell(
                cmd, stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT)
            out, _ = await proc.communicate()
            if proc.returncode != 0:
                raise RuntimeError(
                    f"image setup RUN failed ({proc.returncode}): {cmd}\n"
                    f"{out.decode()[-2000:]}")
        elif kind == "ENV":
            key, _, val = value.partition("=")
            os.environ[key.strip()] = val.strip()
        elif kind == "COPY":
            pass  # ktsync already placed the files (reference: no-op verify)
        elif kind == "SYNC":
            pass  # handled by the code-sync step before setup
        elif kind == "CMD":
            if state is not None:
                await start_app_process(state, value)
        replayed += 1

    if pip_touched:
        _evict_changed_distributions(before)
    _CACHED_DOCKERFILE = dockerfile.splitlines()
    return {"instructions": len(new), "replayed": replayed}


def _installed_versions() -> dict:
    import importlib
    import importlib.metadata as md

    importlib.invalidate_caches()
    out = {}
    for dist in md.distributions():
        try:
            out[dist.metadata["Name"]] = dist.version
        except Exception:
            continue
    return out


def _evict_changed_distributions(before: dict) -> None:
    """Pip-freeze diff (reference :775-815): evict only the modules of
    distributions whose version changed — never the whole of site-packages
    (dropping live jax/aiohttp would break the running server and re-init
    libtpu, which is single-client)."""
    import importlib
    import importlib.metadata as md

    importlib.invalidate_caches()
    after = _installed_versions()
    changed = {name for name, ver in after.items()
               if before.get(name) != ver}
    if not changed:
        return
    evict_roots = set()
    for dist_name in changed:
        try:
            dist = md.distribution(dist_name)
            top = (dist.read_text("top_level.txt") or "").split()
            evict_roots.update(top or [dist_name.replace("-", "_")])
        except Exception:
            evict_roots.add(dist_name.replace("-", "_"))
    evict_roots.discard("kubetorch_tpu")
    for name in list(sys.modules):
        if name.split(".")[0] in evict_roots:
            sys.modules.pop(name, None)


async def start_app_process(state, command: str,
                            wait_start_s: float = 2.0) -> None:
    """(Re)start the App child process (reference CMD handling +
    wait_for_app_start)."""
    if getattr(state, "app_process", None) is not None and \
            state.app_process.returncode is None:
        state.app_process.terminate()
        try:
            await asyncio.wait_for(state.app_process.wait(), 10)
        except asyncio.TimeoutError:
            state.app_process.kill()
    state.app_process = await asyncio.create_subprocess_exec(
        *shlex.split(command))
    await asyncio.sleep(wait_start_s)
    if state.app_process.returncode is not None:
        raise RuntimeError(
            f"App process exited immediately (rc={state.app_process.returncode}): "
            f"{command}")
