"""Load-balanced dispatch: one call → the front door's chosen pod.

The third dispatch mode of the reference's CRD enum (``regular | spmd |
load_balanced``, charts/.../kubetorchworkload-crd.yaml:80-86). In k8s the
Service's ClusterIP already spreads *connections*; this supervisor spreads
*calls* — but the policy is no longer a blind round-robin: replica
selection, continuous batching, affinity, and admission control all live in
:class:`serving.router.Router` (ISSUE 9), the only module allowed to make
that decision. This class is the thin seam between the supervisor hierarchy
(membership, rank pool, restart guard) and the router.

Unlike SPMD, the result is a single value (the chosen pod's), not a
per-rank list.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .discovery import my_pod_ip
from .execution_supervisor import DistributedSupervisor
from .remote_worker_pool import RemoteWorkerPool
from .router import Router


class LoadBalancedSupervisor(DistributedSupervisor):
    def __init__(self, *args, server_port: int = 32300, fn_name: str = "",
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.server_port = server_port
        self.fn_name = fn_name
        self.router = Router(server_port=server_port, fn_name=fn_name)

    async def _call_local(self, method, args, kwargs, timeout) -> Any:
        # the restart guard wraps ONLY local execution: forwarded calls must
        # not churn this pod's (unused) ranks or serialize behind its lock
        async with self.restart_guard():
            assert self.pool is not None, "supervisor not set up"
            return await self.pool.call(0, method, args, kwargs, timeout)

    async def call(self, method: Optional[str], args: list, kwargs: dict,
                   timeout: Optional[float] = None,
                   subtree: Optional[List[str]] = None,
                   headers: Optional[Dict[str, str]] = None,
                   **_ignored) -> Any:
        if subtree is not None:
            # we are the chosen pod for a forwarded call: run locally
            return await self._call_local(method, args, kwargs, timeout)
        ips = sorted(self.pod_ips() or [my_pod_ip()])
        pool = RemoteWorkerPool.shared(self.server_port)
        # readiness fence wiring (ISSUE 16): ips that just appeared in the
        # membership are still-booting replicas — fence them and let the
        # router's background prober admit each one when its probe passes
        self.router.observe_membership(ips, pool)
        return await self.router.dispatch(
            pool=pool, ips=ips,
            my_ip=my_pod_ip(), method=method, args=args, kwargs=kwargs,
            headers=headers, timeout=timeout, local_call=self._call_local)

    def router_state(self) -> Dict[str, Any]:
        """Front-door accounting for ``/health`` and ``kt serve status``."""
        return self.router.state_dict()
