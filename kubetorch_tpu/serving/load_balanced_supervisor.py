"""Load-balanced dispatch: one call → one pod, rotated.

The third dispatch mode of the reference's CRD enum (``regular | spmd |
load_balanced``, charts/.../kubetorchworkload-crd.yaml:80-86). In k8s the
Service's ClusterIP already spreads *connections*; this supervisor spreads
*calls* — deterministic round-robin with health skipping, which matters for
long-lived clients holding keep-alive connections to one pod and for the
local backend (whose service_url always points at pod 0).

Unlike SPMD, the result is a single value (the chosen pod's), not a
per-rank list.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

from ..exceptions import WorkerCallError
from .discovery import my_pod_ip
from .execution_supervisor import DistributedSupervisor
from .remote_worker_pool import RemoteWorkerPool


class LoadBalancedSupervisor(DistributedSupervisor):
    def __init__(self, *args, server_port: int = 32300, fn_name: str = "",
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.server_port = server_port
        self.fn_name = fn_name
        self._rr = itertools.count()

    async def _call_local(self, method, args, kwargs, timeout) -> Any:
        # the restart guard wraps ONLY local execution: forwarded calls must
        # not churn this pod's (unused) ranks or serialize behind its lock
        async with self.restart_guard():
            assert self.pool is not None, "supervisor not set up"
            return await self.pool.call(0, method, args, kwargs, timeout)

    async def call(self, method: Optional[str], args: list, kwargs: dict,
                   timeout: Optional[float] = None,
                   subtree: Optional[List[str]] = None,
                   headers: Optional[Dict[str, str]] = None,
                   **_ignored) -> Any:
        if subtree is not None:
            # we are the chosen pod for a forwarded call: run locally
            return await self._call_local(method, args, kwargs, timeout)

        ips = sorted(self.pod_ips() or [my_pod_ip()])
        my_ip = my_pod_ip()
        pool = RemoteWorkerPool.shared(self.server_port)
        # try up to len(ips) pods starting at the round-robin cursor,
        # skipping unhealthy ones (elastic by default)
        start = next(self._rr)
        last_err: Optional[BaseException] = None
        for offset in range(len(ips)):
            target = ips[(start + offset) % len(ips)]
            if target == my_ip:
                return await self._call_local(method, args, kwargs, timeout)
            if not await pool.check_health(target):
                continue
            try:
                return await pool.call_worker(
                    target, self.fn_name, method,
                    {"args": args, "kwargs": kwargs}, headers or {},
                    timeout, subtree=[])
            except WorkerCallError as e:
                # failover ONLY on transport failure — an application
                # exception from the peer must propagate, never re-run a
                # (possibly non-idempotent) call on another pod
                last_err = e
        if last_err is not None:
            raise last_err
        # no healthy peer: serve locally
        return await self._call_local(method, args, kwargs, timeout)
