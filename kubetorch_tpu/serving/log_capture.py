"""Log capture: intercept stdout/stderr/logging, batch, push to a sink.

Reference (``serving/log_capture.py``): replaces sys.stdout/stderr with
interceptors, batches 100 entries / 1s, pushes to Loki with labels
{service, pod, namespace, level, request_id, trace_id}, dual-writes to the
original streams so ``kubectl logs`` still works.

``request_id`` comes from the server's contextvar and ``trace_id`` from the
active telemetry span (ISSUE 5), so every ``kt logs`` line is joinable
against ``kt trace <request_id>`` / ``/debug/traces``; rank-subprocess
lines arrive with their own bindings over the response queue. The buffer
flushes on atexit (via the registered :meth:`LogCapture.stop`) so one-shot
processes don't lose their final batch.

The sink here is pluggable: a Loki push endpoint when the charts deploy Loki,
or the controller's ``/controller/logs`` ingestion route (our controller
stores a ring buffer per service for `kt logs` without Loki).
"""

from __future__ import annotations

import atexit
import json
import logging
import sys
import threading
import time
from typing import Dict, List, Optional

BATCH_SIZE = 100
FLUSH_INTERVAL_S = 1.0


class _StreamInterceptor:
    def __init__(self, original, capture: "LogCapture", source: str):
        self.original = original
        self.capture = capture
        self.source = source

    def write(self, data: str):
        self.original.write(data)
        if data.strip():
            self.capture.add(data.rstrip("\n"), source=self.source)
        return len(data)

    def flush(self):
        self.original.flush()

    def isatty(self):
        return False

    def fileno(self):
        return self.original.fileno()


class _LogHandler(logging.Handler):
    def __init__(self, capture: "LogCapture"):
        super().__init__()
        self.capture = capture

    def emit(self, record: logging.LogRecord):
        try:
            self.capture.add(self.format(record), source="logger",
                             level=record.levelname)
        except Exception:
            pass


class LogCapture:
    _global: Optional["LogCapture"] = None

    def __init__(self, sink_url: str, labels: Dict[str, str]):
        self.sink_url = sink_url
        self.labels = labels
        self._buffer: List[Dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._originals = None
        self._handler: Optional[_LogHandler] = None

    @classmethod
    def start_global(cls, sink_url: str, labels: Dict[str, str]) -> "LogCapture":
        if cls._global is not None:
            return cls._global
        cap = cls(sink_url, labels)
        cap.start()
        cls._global = cap
        return cap

    def start(self) -> None:
        self._originals = (sys.stdout, sys.stderr)
        sys.stdout = _StreamInterceptor(sys.stdout, self, "stdout")
        sys.stderr = _StreamInterceptor(sys.stderr, self, "stderr")
        self._handler = _LogHandler(self)
        logging.getLogger().addHandler(self._handler)
        self._thread = threading.Thread(target=self._flush_loop, daemon=True)
        self._thread.start()
        atexit.register(self.stop)

    def add(self, line: str, source: str = "stdout", level: str = "INFO",
            request_id: Optional[str] = None,
            trace_id: Optional[str] = None) -> None:
        """``request_id=None`` / ``trace_id=None`` → this process's
        contextvars (server-side interception); an explicit value (may be
        "") is authoritative — rank-subprocess logs arrive with their own
        bindings over the response queue."""
        from .. import telemetry
        from .http_server import request_id_var

        entry = {
            "ts": time.time(),
            "line": line,
            "source": source,
            "level": level,
            "request_id": (request_id if request_id is not None
                           else request_id_var.get("")),
            "trace_id": (trace_id if trace_id is not None
                         else telemetry.current_trace_id() or ""),
            **self.labels,
        }
        flush_now = False
        with self._lock:
            self._buffer.append(entry)
            flush_now = len(self._buffer) >= BATCH_SIZE
        if flush_now:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            batch, self._buffer = self._buffer, []
        if not batch:
            return
        try:
            import requests
            requests.post(self.sink_url, json={"entries": batch}, timeout=5)
        except Exception:
            pass  # logging must never take down the pod

    def _flush_loop(self) -> None:
        while not self._stop.wait(FLUSH_INTERVAL_S):
            self.flush()

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self._originals:
            sys.stdout, sys.stderr = self._originals
            self._originals = None
        if self._handler:
            logging.getLogger().removeHandler(self._handler)
            self._handler = None
        self.flush()
        LogCapture._global = None
