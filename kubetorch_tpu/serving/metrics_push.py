"""Metrics push loop.

Reference (``serving/metrics_push.py``): pushes http_requests_total, request
durations, ``kubetorch_last_activity_timestamp`` (the TTL-reaper signal) and
a heartbeat to a Prometheus pushgateway every 15s.

TPU delta: when running on a TPU host we also export duty-cycle/HBM gauges
read from jax's local device memory stats (the DCGM-equivalent for TPU).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

PUSH_INTERVAL_S = 15.0


class MetricsPusher:
    def __init__(self, gateway_url: str, state, interval: float = PUSH_INTERVAL_S):
        self.gateway_url = gateway_url
        self.state = state
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _tpu_metrics(self) -> dict:
        try:
            import jax
            devs = [d for d in jax.local_devices() if d.platform == "tpu"]
            out = {}
            for d in devs:
                stats = d.memory_stats() or {}
                out[f"kt_tpu_hbm_bytes_in_use{{device=\"{d.id}\"}}"] = \
                    stats.get("bytes_in_use", 0)
                out[f"kt_tpu_hbm_bytes_limit{{device=\"{d.id}\"}}"] = \
                    stats.get("bytes_limit", 0)
            return out
        except Exception:
            return {}

    def _payload(self) -> str:
        lines = {
            "kubetorch_last_activity_timestamp": self.state.last_activity,
            "kt_http_requests_total": self.state.request_count,
            "kt_heartbeat_sent": time.time(),
        }
        lines.update(self._tpu_metrics())
        return "\n".join(f"{k} {v}" for k, v in lines.items()) + "\n"

    def _loop(self) -> None:
        import requests
        while not self._stop.wait(self.interval):
            try:
                requests.post(self.gateway_url, data=self._payload(), timeout=5)
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
