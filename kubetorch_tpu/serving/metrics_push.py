"""Metrics push loop.

Reference (``serving/metrics_push.py``): pushes http_requests_total, request
durations, ``kubetorch_last_activity_timestamp`` (the TTL-reaper signal) and
a heartbeat to a Prometheus pushgateway every 15s.

TPU delta: when running on a TPU host we also export duty-cycle/HBM gauges
read from jax's local device memory stats (the DCGM-equivalent for TPU).

ISSUE 5 fixes: device labels are exposition-escaped (a hostile/odd device
id can no longer corrupt the series name), the payload carries proper
``# TYPE``/``# HELP`` headers plus the full telemetry registry (stage
histograms, retry/death/chaos counters), and push failures are counted in
``kt_metrics_push_failures_total`` and logged once per failure streak
instead of being swallowed forever.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import telemetry

PUSH_INTERVAL_S = 15.0

_PUSH_FAILURES = telemetry.counter(
    "kt_metrics_push_failures_total",
    "Pushgateway POSTs that failed (connection error or non-2xx)")


def tpu_gauges() -> dict:
    """Per-device HBM gauges from jax's memory stats — the TPU analog of the
    DCGM exporter's GPU_UTIL/FB_USED signal. Shared by the push loop AND the
    pod's ``/metrics`` scrape endpoint so Prometheus (deploy/metrics.yaml)
    and live client streaming see the same series.

    Keys carry the ``{device="..."}`` label suffix with the label value
    exposition-escaped (``telemetry.escape_label_value``) — never raw
    interpolation.

    Reads stats only when the workload has ALREADY imported jax: an
    external scraper must never be the thing that initializes the TPU
    runtime (backend init takes tens of seconds and would also claim the
    chips before user code configures them)."""
    import sys
    if "jax" not in sys.modules:
        return {}
    try:
        import jax
        devs = [d for d in jax.local_devices() if d.platform == "tpu"]
        out = {}
        for d in devs:
            stats = d.memory_stats() or {}
            dev = telemetry.escape_label_value(d.id)
            out[f'kt_tpu_hbm_bytes_in_use{{device="{dev}"}}'] = \
                stats.get("bytes_in_use", 0)
            out[f'kt_tpu_hbm_bytes_limit{{device="{dev}"}}'] = \
                stats.get("bytes_limit", 0)
        return out
    except Exception:
        return {}


class MetricsPusher:
    def __init__(self, gateway_url: str, state, interval: float = PUSH_INTERVAL_S):
        self.gateway_url = gateway_url
        self.state = state
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fail_streak = 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _payload(self) -> str:
        lines = {
            "kubetorch_last_activity_timestamp": self.state.last_activity,
            "kt_http_requests_total": self.state.request_count,
            "kt_heartbeat_sent": time.time(),
        }
        lines.update(tpu_gauges())
        # exposition-format body: # TYPE/# HELP-headed registry series plus
        # the ad-hoc gauge lines above (each base name TYPE-headed too)
        return (telemetry.REGISTRY.render()
                + telemetry.render_untyped_gauges(lines))

    def _loop(self) -> None:
        import requests
        while not self._stop.wait(self.interval):
            try:
                r = requests.post(self.gateway_url, data=self._payload(),
                                  timeout=5)
                if r.status_code >= 400:
                    raise requests.HTTPError(f"push → {r.status_code}")
            except Exception as e:  # noqa: BLE001 — the pusher must survive
                self._record_failure(e)
            else:
                if self._fail_streak:
                    print(f"[kt] metrics push recovered after "
                          f"{self._fail_streak} failure(s)")
                self._fail_streak = 0

    def _record_failure(self, exc: BaseException) -> None:
        """Count every failure; log only the FIRST of a streak — a dead
        gateway must neither be silent forever nor spam one line per
        interval for days."""
        _PUSH_FAILURES.inc()
        self._fail_streak += 1
        if self._fail_streak == 1:
            print(f"[kt] metrics push to {self.gateway_url} failing "
                  f"({type(exc).__name__}: {exc}); will keep retrying "
                  f"every {self.interval:g}s (logged once per streak)")

    def stop(self) -> None:
        self._stop.set()
