"""Metrics push loop.

Reference (``serving/metrics_push.py``): pushes http_requests_total, request
durations, ``kubetorch_last_activity_timestamp`` (the TTL-reaper signal) and
a heartbeat to a Prometheus pushgateway every 15s.

TPU delta: when running on a TPU host we also export duty-cycle/HBM gauges
read from jax's local device memory stats (the DCGM-equivalent for TPU).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

PUSH_INTERVAL_S = 15.0


def tpu_gauges() -> dict:
    """Per-device HBM gauges from jax's memory stats — the TPU analog of the
    DCGM exporter's GPU_UTIL/FB_USED signal. Shared by the push loop AND the
    pod's ``/metrics`` scrape endpoint so Prometheus (deploy/metrics.yaml)
    and live client streaming see the same series.

    Reads stats only when the workload has ALREADY imported jax: an
    external scraper must never be the thing that initializes the TPU
    runtime (backend init takes tens of seconds and would also claim the
    chips before user code configures them)."""
    import sys
    if "jax" not in sys.modules:
        return {}
    try:
        import jax
        devs = [d for d in jax.local_devices() if d.platform == "tpu"]
        out = {}
        for d in devs:
            stats = d.memory_stats() or {}
            out[f"kt_tpu_hbm_bytes_in_use{{device=\"{d.id}\"}}"] = \
                stats.get("bytes_in_use", 0)
            out[f"kt_tpu_hbm_bytes_limit{{device=\"{d.id}\"}}"] = \
                stats.get("bytes_limit", 0)
        return out
    except Exception:
        return {}


class MetricsPusher:
    def __init__(self, gateway_url: str, state, interval: float = PUSH_INTERVAL_S):
        self.gateway_url = gateway_url
        self.state = state
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _payload(self) -> str:
        lines = {
            "kubetorch_last_activity_timestamp": self.state.last_activity,
            "kt_http_requests_total": self.state.request_count,
            "kt_heartbeat_sent": time.time(),
        }
        lines.update(tpu_gauges())
        return "\n".join(f"{k} {v}" for k, v in lines.items()) + "\n"

    def _loop(self) -> None:
        import requests
        while not self._stop.wait(self.interval):
            try:
                requests.post(self.gateway_url, data=self._payload(), timeout=5)
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
