"""Remote debugging over WebSocket.

Reference (``serving/pdb_websocket.py``): a WebSocketIO object impersonates
stdin/stdout for pdb; when a request carries ``debugger: {mode, port}``, the
next breakpoint in user code attaches to a WS server the client's ``kt
debug`` command dials into with a PTY.

Here the debug server is an aiohttp WS route bound on demand; ``arm_debugger``
stores the request's debug spec so ``kt_breakpoint()`` (the user-facing hook)
starts the session.
"""

from __future__ import annotations

import asyncio
import pdb
import threading
from typing import Optional

_armed: Optional[dict] = None
_lock = threading.Lock()


def arm_debugger(spec: dict) -> None:
    global _armed
    with _lock:
        _armed = dict(spec)


def debugger_spec() -> Optional[dict]:
    with _lock:
        return dict(_armed) if _armed else None


def _disarm() -> None:
    """One-shot: the armed spec (and its token) dies with the session — a
    later connection can't replay it."""
    global _armed
    with _lock:
        _armed = None


class _SocketIO:
    """File-like adapter over a blocking socket for pdb's stdin/stdout."""

    def __init__(self, conn):
        self.conn = conn
        self._buf = b""

    def readline(self):
        while b"\n" not in self._buf:
            chunk = self.conn.recv(4096)
            if not chunk:
                return ""
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line.decode() + "\n"

    def write(self, data: str):
        self.conn.sendall(data.encode())
        return len(data)

    def flush(self):
        pass


def kt_breakpoint(port: Optional[int] = None,
                  _accept_timeout: Optional[float] = None) -> None:
    """Block until an AUTHORIZED debug client connects, then drop into pdb
    over the socket. Import-safe: no-op unless a request armed the debugger.

    Auth: when the armed spec carries a ``token`` (clients generate one per
    call — reference ``pdb_websocket.py:175-323`` session handshake), the
    first line a connection sends must match it; a wrong token gets the
    connection closed and the breakpoint keeps waiting. The spec is
    one-shot: consumed when the session starts.
    """
    import socket
    import sys

    spec = debugger_spec()
    if spec is None and port is None:
        return
    spec = spec or {}
    port = port or int(spec.get("port", 5678))
    token = spec.get("token")
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", port))
    srv.listen(1)
    if _accept_timeout:
        srv.settimeout(_accept_timeout)
    try:
        while True:
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                return
            if token:
                conn.settimeout(10.0)
                io_probe = _SocketIO(conn)
                try:
                    offered = io_probe.readline().strip()
                except (socket.timeout, OSError):
                    offered = None
                if offered != token:
                    try:
                        conn.sendall(b"unauthorized\n")
                        conn.close()
                    except OSError:
                        pass
                    continue
                conn.settimeout(None)
                io = io_probe
            else:
                io = _SocketIO(conn)
            break
    finally:
        srv.close()
    _disarm()
    io.write("kt-debug: session started\n")
    debugger = pdb.Pdb(stdin=io, stdout=io)
    debugger.set_trace(frame=sys._getframe(1))


# reference name for the user-facing hook (serving/utils.deep_breakpoint):
# call it inside remote code; a request that armed the debugger turns it
# into a live session, otherwise it is a no-op
deep_breakpoint = kt_breakpoint
