"""Stage-gang supervision for elastic pipeline parallelism (ISSUE 17).

The host-side loop that keeps a pipelined job's stage gang alive:

- watches one subprocess per stage (anything with ``poll()``/``kill()`` —
  ``subprocess.Popen`` or a test double),
- classifies a dead stage with the watchdog's taxonomy
  (:func:`~.watchdog.classify_death`) and a *live but stalled* stage with
  :func:`~.watchdog.classify_straggler` (heartbeat age → ``Slow``),
- drives the membership re-group through the ONLY site allowed to do it
  (:class:`~..parallel.pipeline_elastic.ElasticPipeline`) and relaunches
  the new membership's stages — old-epoch processes are killed, not
  reasoned with; a zombie that survives the kill is fenced by
  ``StaleStageEpochError`` at its next confirm,
- measures the re-group stall (fault detected → first post-re-group step
  committed) into ``kt_pipeline_regroup_seconds`` and checks it against
  the elastic resume window — the acceptance bar is "progress resumes
  within ONE window, never a full-pipeline stall".

The supervisor never touches membership state itself — it asks the
``ElasticPipeline`` and relaunches whatever comes back. ``launch`` is the
embedder's factory: ``launch(assignment, epoch, resume)`` → process
handle. The trainer assets and ``bench.py --pipeline`` embed this class
directly; a serving supervisor exposes :meth:`pipeline_state` and the
``/health`` handler picks it up by duck type (``body["pipeline"]``).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from .. import telemetry
from .watchdog import CAUSE_SLOW, classify_death, classify_straggler


class PipelineSupervisor:
    """Supervise one stage gang. Single-threaded by design: the embedder
    owns the loop and calls :meth:`poll` between steps (the trainer
    drivers) or from a timer (a serving pod)."""

    def __init__(self, pipe, launch: Callable[..., Any], *,
                 stall_after_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.pipe = pipe
        self.launch = launch
        self.stall_after_s = float(stall_after_s)
        self.clock = clock
        self.procs: Dict[int, Any] = {}
        self._beats: Dict[int, float] = {}
        # a re-group in flight: t0 is fault-detection time; cleared (and
        # observed) when the first post-re-group step commits
        self._regroup_t0: Optional[float] = None
        self.last_regroup_stall_s: Optional[float] = None
        self.regroups_over_window = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        membership = self.pipe.membership
        for a in membership.assignments:
            self.procs[a.stage] = self.launch(a, membership.epoch,
                                              resume=False)
            self._beats[a.stage] = self.clock()

    def beat(self, stage: int) -> None:
        """Heartbeat from a stage (the driver calls this when it sees any
        output/activation from the stage) — feeds the straggler check."""
        self._beats[stage] = self.clock()

    def stop(self) -> None:
        for proc in self.procs.values():
            try:
                proc.kill()
            except (OSError, AttributeError):
                pass
        self.procs.clear()

    # -- fault detection -----------------------------------------------------

    def poll(self) -> Optional[Dict[str, Any]]:
        """One supervision pass: find at most one dead/stalled stage and
        re-group around it. Returns the re-group event dict, or None when
        every stage is healthy. One fault per pass — a second casualty is
        found on the next poll, against the already-re-grouped membership
        (its stage numbering, not the old one)."""
        now = self.clock()
        for stage, proc in list(self.procs.items()):
            exitcode = proc.poll()
            if exitcode is not None and exitcode != 0:
                return self._regroup(stage, classify_death(exitcode))
        if self.stall_after_s > 0:
            for stage, proc in list(self.procs.items()):
                if proc.poll() is not None:
                    continue    # exited 0 = done, not a straggler
                age = now - self._beats.get(stage, now)
                if classify_straggler(age, self.stall_after_s) is not None:
                    return self._regroup(stage, CAUSE_SLOW, stall_age=age)
        return None

    def _regroup(self, lost_stage: int, cause: str,
                 stall_age: Optional[float] = None) -> Dict[str, Any]:
        t0 = self.clock()
        # the lost stage's process first: a Slow stage is still alive and
        # would otherwise keep publishing under the old epoch until its
        # next confirm bounces off the fence
        doomed = self.procs.pop(lost_stage, None)
        if doomed is not None:
            try:
                doomed.kill()
            except (OSError, AttributeError):
                pass
        membership = self.pipe.regroup(lost_stage, cause)
        # stage↔layer ownership changed for the survivors too (absorbed
        # shards, renumbered stages): relaunch the whole new membership
        # from the last committed checkpoint rather than guessing which
        # old process maps to which new assignment
        for proc in self.procs.values():
            try:
                proc.kill()
            except (OSError, AttributeError):
                pass
        self.procs.clear()
        for a in membership.assignments:
            self.procs[a.stage] = self.launch(a, membership.epoch,
                                              resume=True)
            self._beats[a.stage] = self.clock()
        self._regroup_t0 = t0
        event = dict(self.pipe.regroups[-1])
        if stall_age is not None:
            event["stall_age_s"] = round(stall_age, 3)
        return event

    def note_committed_step(self, step: int) -> Optional[float]:
        """The driver reports a committed step. The first one after a
        re-group closes the stall clock: observe it, compare against the
        elastic resume window, and return the stall seconds (None when no
        re-group was pending)."""
        if self._regroup_t0 is None:
            return None
        stall = self.clock() - self._regroup_t0
        self._regroup_t0 = None
        self.last_regroup_stall_s = stall
        telemetry.pipeline_metrics()["regroup_seconds"].observe(stall)
        window = getattr(self.pipe.policy, "resume_window_s", 0.0)
        if window and stall > window:
            self.regroups_over_window += 1
        return stall

    # -- surfacing -----------------------------------------------------------

    def pipeline_state(self) -> Dict[str, Any]:
        """``/health``'s ``pipeline`` section (duck-typed hook)."""
        state = self.pipe.state_dict()
        state["stages_live"] = sum(
            1 for p in self.procs.values() if p.poll() is None)
        state["regroup_pending"] = self._regroup_t0 is not None
        if self.last_regroup_stall_s is not None:
            state["last_regroup_stall_s"] = round(
                self.last_regroup_stall_s, 3)
        state["regroups_over_window"] = self.regroups_over_window
        return state
