"""Pool of rank subprocesses with an async request/response router.

Reference (``serving/process_pool.py``): N ProcessWorkers + mp queues, a
response-router thread matching req_ids to futures, ``call`` (one rank) and
``call_all`` (every local rank in parallel), queue draining on restart.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import threading
import time
import queue as queue_mod
from typing import Any, Dict, List, Optional

from ..exceptions import rehydrate_exception
from ..resources.pointers import Pointers
from .env_contract import RankInfo


class ProcessPool:
    def __init__(self, num_procs: int, framework_name: str,
                 pointers: Optional[Pointers], init_args: Optional[Dict],
                 node_rank: int = 0, num_nodes: int = 1,
                 pod_ips: Optional[List[str]] = None,
                 base_env: Optional[Dict[str, str]] = None):
        from .process_worker import ProcessWorker

        self.num_procs = num_procs
        self.framework_name = framework_name
        self.workers: List[ProcessWorker] = []
        for local_rank in range(num_procs):
            info = RankInfo(node_rank=node_rank, local_rank=local_rank,
                            nproc_per_node=num_procs, num_nodes=num_nodes,
                            pod_ips=pod_ips or ["127.0.0.1"])
            self.workers.append(ProcessWorker(info, framework_name, pointers,
                                              init_args, base_env))
        self._futures: Dict[str, asyncio.Future] = {}
        self._futures_lock = threading.Lock()
        self._req_counter = itertools.count()
        self._router_threads: List[threading.Thread] = []
        self._stopping = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def start(self) -> None:
        # NOTE: often called from a worker thread (asyncio.to_thread), where
        # there is no event loop — the loop is captured on first call().
        for w in self.workers:
            w.start()
        for w in self.workers:
            t = threading.Thread(target=self._route_responses, args=(w,), daemon=True)
            t.start()
            self._router_threads.append(t)

    def _route_responses(self, worker) -> None:
        while not self._stopping.is_set():
            try:
                resp = worker.response_q.get(timeout=0.2)
            except (queue_mod.Empty, OSError, ValueError, EOFError):
                if not worker.alive and self._stopping.is_set():
                    return
                continue
            if resp.get("op") == "log":
                self._forward_log(resp, worker)
                continue
            if resp.get("op") == "state":
                # load+warmup bracket: gates /ready and shutdown escalation
                worker.in_warmup = resp.get("warmup") == "started"
                continue
            req_id = resp.get("req_id")
            with self._futures_lock:
                fut = self._futures.pop(req_id, None)
            if fut is not None and self._loop is not None and not fut.done():
                self._loop.call_soon_threadsafe(self._resolve, fut, resp)

    @staticmethod
    def _forward_log(resp: Dict, worker) -> None:
        from .log_capture import LogCapture

        cap = LogCapture._global
        if cap is not None:
            cap.add(resp.get("line", ""),
                    source=f"rank{resp.get('rank', '?')}-{resp.get('source', 'stdout')}",
                    request_id=resp.get("request_id", ""))

    @staticmethod
    def _resolve(fut: asyncio.Future, resp: Dict) -> None:
        if fut.done():
            return
        if resp.get("ok"):
            fut.set_result(resp.get("result"))
        else:
            fut.set_exception(rehydrate_exception(resp["error"]))

    async def _submit(self, idx: int, payload: Dict,
                      timeout: Optional[float]) -> Any:
        """Shared request plumbing: liveness check, future registration,
        queue submit, awaited response."""
        worker = self.workers[idx]
        if not worker.alive:
            raise RuntimeError(f"Rank subprocess {idx} is dead")
        self._loop = asyncio.get_running_loop()
        req_id = f"r{next(self._req_counter)}"
        fut = self._loop.create_future()
        with self._futures_lock:
            self._futures[req_id] = fut
        # carry the HTTP request id across the process boundary so the
        # worker's prints stay correlated to this call in the log stream
        from .http_server import request_id_var
        worker.submit({"req_id": req_id,
                       "request_id": request_id_var.get(""), **payload})
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            # a wedged worker never answers this req_id — drop the future
            # or periodic submitters (the 3s user_metrics scrape) leak one
            # registry entry per attempt for the pod's lifetime
            with self._futures_lock:
                self._futures.pop(req_id, None)
            raise

    async def call(self, idx: int, method: Optional[str], args: list,
                   kwargs: dict, timeout: Optional[float] = None,
                   dist_env: Optional[Dict[str, str]] = None) -> Any:
        payload: Dict[str, Any] = {"method": method, "args": args,
                                   "kwargs": kwargs}
        if dist_env:
            payload["dist_env"] = dist_env
        return await self._submit(idx, payload, timeout)

    def subset_env(self, local_rank: int, sel_ips: List[str],
                   sel_node_rank: int) -> Optional[Dict[str, str]]:
        """Selection-relative rank env for a worker-subset call (reference
        per-call env assembly, spmd_supervisor.py:345-364): WORLD_SIZE/RANK/
        MASTER_ADDR reflect the *selected* pods, so e.g. ``workers=[2, 5]``
        behaves as a clean 2-node world for frameworks that initialize their
        collectives inside the request. ``None`` when the framework's identity
        is fixed at spawn (JAX/TPU)."""
        from .env_contract import framework_for

        fw = framework_for(self.framework_name)
        if not fw.per_call_identity:
            return None
        info = RankInfo(node_rank=sel_node_rank, local_rank=local_rank,
                        nproc_per_node=self.num_procs,
                        num_nodes=len(sel_ips), pod_ips=list(sel_ips))
        return fw.env(info)

    async def profile(self, idx: int = 0, duration_s: float = 3.0,
                      timeout: Optional[float] = None) -> Any:
        """Capture a jax.profiler trace in rank subprocess ``idx``."""
        return await self._submit(idx, {"op": "profile",
                                        "duration_s": duration_s},
                                  timeout or duration_s + 60)

    async def user_metrics(self, idx: int = 0,
                           timeout: float = 5.0) -> Dict[str, float]:
        """Rank ``idx``'s ``__kt_metrics__`` gauges ({} when undefined) —
        merged into the pod /metrics scrape by the server."""
        return await self._submit(idx, {"op": "user_metrics"}, timeout)

    async def call_all(self, method: Optional[str], args: list, kwargs: dict,
                       timeout: Optional[float] = None,
                       subset: Optional[tuple] = None) -> List[Any]:
        """``subset=(sel_ips, sel_node_rank)`` rebinds rank identity to the
        selected pod set for this request (see :meth:`subset_env`)."""
        tasks = [self.call(i, method, args, kwargs, timeout,
                           dist_env=(self.subset_env(i, *subset)
                                     if subset else None))
                 for i in range(self.num_procs)]
        return list(await asyncio.gather(*tasks))

    def cancel_pending(self, exc: BaseException) -> None:
        with self._futures_lock:
            futs, self._futures = list(self._futures.values()), {}
        for fut in futs:
            if self._loop is not None and not fut.done():
                self._loop.call_soon_threadsafe(
                    lambda f=fut: (not f.done()) and f.set_exception(exc))

    def shutdown(self) -> None:
        """Stop every worker: shutdown ops go out to ALL workers first, one
        shared join deadline covers them together (not per-worker serially),
        and the response routers stay alive until the end so a worker's
        ``warmup: done`` state op can still flip ``in_warmup`` mid-wait —
        the flag that decides whether SIGKILL escalation is allowed (a jit
        compile in flight must never be force-killed while it holds the
        TPU). Workers still warming get one shared KT_WARMUP_SHUTDOWN_GRACE
        window (default 600s) before the last-resort kill."""
        self.cancel_pending(RuntimeError("ProcessPool shutting down"))
        for w in self.workers:
            w.request_shutdown()

        def join_all(deadline: float) -> bool:
            while any(w.alive for w in self.workers):
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.1)
            return True

        done = join_all(time.monotonic() + 5.0)
        if not done and any(w.alive and w.in_warmup for w in self.workers):
            grace = float(os.environ.get("KT_WARMUP_SHUTDOWN_GRACE", "600"))
            deadline = time.monotonic() + grace
            while (time.monotonic() < deadline
                   and any(w.alive and w.in_warmup for w in self.workers)):
                time.sleep(1.0)
            # stragglers past warmup get the normal short window
            join_all(time.monotonic() + 5.0)
        self._stopping.set()
        for w in self.workers:
            w.force_kill_if_alive()

    @property
    def healthy(self) -> bool:
        return all(w.alive for w in self.workers)

    @property
    def warming(self) -> bool:
        """True while any live rank is still in its load+warmup window."""
        return any(w.alive and w.in_warmup for w in self.workers)
