"""Pool of rank subprocesses with an async request/response router.

Reference (``serving/process_pool.py``): N ProcessWorkers + mp queues, a
response-router thread matching req_ids to futures, ``call`` (one rank) and
``call_all`` (every local rank in parallel), queue draining on restart.

Liveness is owned by the pool's :class:`~.watchdog.Watchdog` (ISSUE 3): a
rank that dies *mid-call* gets its in-flight futures failed with a typed
:class:`~..exceptions.WorkerDiedError` within the watchdog interval — not
the call timeout — and the pool self-heals within a bounded restart budget
(full-pool for spawn-fixed collective identity, single-rank otherwise).
"""

from __future__ import annotations

import asyncio
import itertools
import os
import threading
import time
import queue as queue_mod
from typing import Any, Dict, List, Optional, Tuple

from ..exceptions import DataCorruptionError, rehydrate_exception
from ..resources.pointers import Pointers
from . import shm_ring
from .env_contract import RankInfo
from .watchdog import Watchdog


class ProcessPool:
    def __init__(self, num_procs: int, framework_name: str,
                 pointers: Optional[Pointers], init_args: Optional[Dict],
                 node_rank: int = 0, num_nodes: int = 1,
                 pod_ips: Optional[List[str]] = None,
                 base_env: Optional[Dict[str, str]] = None):
        self.num_procs = num_procs
        self.framework_name = framework_name
        # spawn parameters are kept so the watchdog can respawn dead ranks
        # with their original identity
        self._pointers = pointers
        self._init_args = init_args
        self._node_rank = node_rank
        self._num_nodes = num_nodes
        self._pod_ips = list(pod_ips or ["127.0.0.1"])
        self._base_env = base_env
        self.workers: List[Any] = [self._new_worker(lr)
                                   for lr in range(num_procs)]
        # req_id → (future, worker index): the index is what lets a death
        # fail exactly the dead rank's in-flight calls
        self._futures: Dict[str, Tuple[asyncio.Future, int]] = {}
        self._futures_lock = threading.Lock()
        self._req_counter = itertools.count()
        self._router_threads: List[threading.Thread] = []
        self._stopping = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # router wake pipe (ISSUE 10): response routers BLOCK on the
        # queue's pipe instead of polling at 5 Hz; state changes that a
        # queue read can't observe (shutdown, a rank death noticed by the
        # watchdog) write a byte here to wake every router immediately
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        # elastic re-mesh hook (ISSUE 6): set by supervisors; called with the
        # new LOCAL world size on a resizing restart and returns env
        # overrides (a shrunken KT_MESH) so the fresh ranks rebuild a mesh
        # that matches the surviving device count instead of the spawn-time N
        self.remesh_env: Optional[Any] = None
        self.watchdog = Watchdog(self)

    def _new_worker(self, local_rank: int):
        from .process_worker import ProcessWorker

        info = RankInfo(node_rank=self._node_rank, local_rank=local_rank,
                        nproc_per_node=self.num_procs,
                        num_nodes=self._num_nodes, pod_ips=self._pod_ips)
        return ProcessWorker(info, self.framework_name, self._pointers,
                             self._init_args, self._base_env)

    def start(self) -> None:
        # NOTE: often called from a worker thread (asyncio.to_thread), where
        # there is no event loop — the loop is captured on first call().
        for w in self.workers:
            w.start()
        for w in self.workers:
            self._start_router(w)
        self.watchdog.start()

    def _start_router(self, worker) -> None:
        t = threading.Thread(target=self._route_responses, args=(worker,),
                             daemon=True)
        t.start()
        self._router_threads.append(t)

    # -- restart hooks (driven by the watchdog thread only) -------------------

    def restart_worker(self, idx: int) -> None:
        """Respawn one dead rank in place (per-call-identity frameworks:
        live ranks keep serving). The old router thread exits on its own
        once the dead worker's queue is drained."""
        old = self.workers[idx]
        old.force_kill_if_alive()
        self.wake_routers()            # the old router exits now, not later
        fresh = self._new_worker(idx)
        self.workers[idx] = fresh
        fresh.start()
        self._start_router(fresh)

    def restart_all(self, exc: Optional[BaseException] = None,
                    num_procs: Optional[int] = None,
                    extra_env: Optional[Dict[str, str]] = None) -> None:
        """Full-pool respawn for spawn-fixed collective identity (JAX/TPU
        mesh): surviving ranks hold half a broken collective, so their
        in-flight futures fail with the dead rank's typed cause and every
        rank restarts together.

        ``num_procs``/``extra_env`` are the elastic re-mesh surface
        (ISSUE 6): a resize respawns the pool at the surviving N-1 world
        size, folds the coordinator's env overrides (batch scale) into the
        base env, and asks ``remesh_env`` for a mesh matching the new size
        — the fresh ranks come up as a coherent smaller world, not a
        truncated copy of the old one."""
        if exc is not None:
            self.cancel_pending(exc)
        for w in self.workers:
            w.request_shutdown()
        deadline = time.monotonic() + 2.0
        while any(w.alive for w in self.workers) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        for w in self.workers:
            w.force_kill_if_alive()
        self.wake_routers()            # retired routers exit now
        resized = num_procs is not None and num_procs != self.num_procs
        if num_procs is not None:
            self.num_procs = max(1, num_procs)
        if extra_env:
            self._base_env = {**(self._base_env or {}), **extra_env}
        if self.remesh_env is not None and (resized or extra_env):
            try:
                self._base_env = {**(self._base_env or {}),
                                  **(self.remesh_env(
                                      self.num_procs * self._num_nodes) or {})}
            except Exception:  # noqa: BLE001 — a bad hook must not stop heal
                import traceback as _tb
                print("[kt] pool remesh_env hook failed:\n" + _tb.format_exc())
        self.workers = [self._new_worker(lr) for lr in range(self.num_procs)]
        for w in self.workers:
            w.start()
        for w in self.workers:
            self._start_router(w)

    # -- response routing -----------------------------------------------------

    def wake_routers(self) -> None:
        """Write the wake byte: every blocked router re-checks stop/death
        state immediately instead of on its next (late) poll tick."""
        try:
            os.write(self._wake_w, b"w")
        except OSError:
            pass

    def _drain_wake(self) -> None:
        try:
            while os.read(self._wake_r, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _route_responses(self, worker) -> None:
        """Poll-free response router (ISSUE 10): blocks on the queue's
        underlying pipe AND the pool wake pipe via
        ``multiprocessing.connection.wait`` — a response wakes it the
        instant the feeder writes it, with no 5 Hz poll burning a wakeup
        (and no 0–200 ms artificial tail when a get/timeout raced the
        arrival). The 1 s timeout is a belt-and-braces heartbeat only."""
        from multiprocessing.connection import wait as mpc_wait

        reader = worker.response_q._reader
        while not self._stopping.is_set():
            try:
                if not reader.poll(0):
                    try:
                        ready = mpc_wait([reader, self._wake_r],
                                         timeout=1.0)
                    except OSError:      # wake fd reclaimed mid-teardown
                        ready = []
                    if self._wake_r in ready:
                        self._drain_wake()
                    if reader not in ready:
                        if not worker.alive:
                            self._drain_dead_queue(worker)
                            return
                        continue
                resp = worker.response_q.get_nowait()
            except (queue_mod.Empty, OSError, ValueError, EOFError):
                if not worker.alive:
                    # dead worker: ship whatever its feeder already wrote,
                    # then exit — a router thread pinned to a queue that
                    # can never produce again would leak per death for the
                    # pod's lifetime
                    self._drain_dead_queue(worker)
                    return
                continue
            self._dispatch_response(resp, worker)

    def _drain_dead_queue(self, worker) -> None:
        while True:
            try:
                resp = worker.response_q.get(timeout=0.2)
            except (queue_mod.Empty, OSError, ValueError, EOFError):
                return
            self._dispatch_response(resp, worker)

    def _dispatch_response(self, resp: Dict, worker) -> None:
        if resp.get("op") == "log":
            self._forward_log(resp, worker)
            return
        if resp.get("op") == "span":
            # finished rank-side spans (worker.execute + everything the user
            # code opened under it, e.g. store fetches) merge into THIS
            # process's ring so one /debug/traces query shows the whole
            # request; the dedup ring absorbs re-shipped trace prefixes
            from .. import telemetry
            span = resp.get("span") or {}
            fresh = telemetry.ingest_span(span)
            qwait = span.get("attrs", {}).get("queue_wait_s")
            if isinstance(qwait, (int, float)):
                telemetry.observe_stage("queue_wait", float(qwait))
            # kt_checkpoint_seconds is observed in the RANK process (where
            # Checkpointer runs) but scraped from THIS one: re-derive it
            # from the shipped span, first arrival only (prefixes re-ship)
            if fresh and span.get("name") in ("checkpoint.save",
                                              "checkpoint.restore"):
                dur = (span.get("end") or 0) - (span.get("start") or 0)
                if dur >= 0:
                    telemetry.histogram(
                        "kt_checkpoint_seconds",
                        "Checkpoint commit/restore wall-clock seconds",
                        labels=("op",),
                    ).observe(dur, op=span["name"].split(".", 1)[1])
            return
        if resp.get("op") == "state":
            # load+warmup bracket: gates /ready and shutdown escalation
            worker.in_warmup = resp.get("warmup") == "started"
            return
        req_id = resp.get("req_id")
        decode_error: Optional[BaseException] = None
        if resp.get("_kt_shm"):
            # decode BEFORE the future lookup: ring slots must free in
            # queue order even when the waiter already timed out/cancelled
            from .. import telemetry
            try:
                with telemetry.stage("shm_copy", dir="resp"):
                    shm_ring.decode_item_fields(
                        resp, getattr(worker, "shm_resp", None),
                        ("result",), "resp")
            except BaseException as e:  # noqa: BLE001
                decode_error = e
        with self._futures_lock:
            entry = self._futures.pop(req_id, None)
        if entry is None:
            return
        fut, _idx = entry
        if decode_error is not None:
            self._fail_future(fut, decode_error)
            return
        if self._loop is not None and not fut.done():
            self._loop.call_soon_threadsafe(self._resolve, fut, resp)

    @staticmethod
    def _forward_log(resp: Dict, worker) -> None:
        from .log_capture import LogCapture

        cap = LogCapture._global
        if cap is not None:
            cap.add(resp.get("line", ""),
                    source=f"rank{resp.get('rank', '?')}-{resp.get('source', 'stdout')}",
                    request_id=resp.get("request_id", ""),
                    trace_id=resp.get("trace_id", ""))

    @staticmethod
    def _resolve(fut: asyncio.Future, resp: Dict) -> None:
        if fut.done():
            return
        if resp.get("ok"):
            fut.set_result(resp.get("result"))
        else:
            fut.set_exception(rehydrate_exception(resp["error"]))

    # -- failing futures (watchdog + shutdown paths) --------------------------

    def _fail_future(self, fut: asyncio.Future, exc: BaseException) -> None:
        if fut.done():
            return
        if self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda f=fut: (not f.done()) and f.set_exception(exc))
        else:
            # no loop ever served a call (pool set up but never hit):
            # fail synchronously so shutdown never strands a waiter
            try:
                fut.set_exception(exc)
            except Exception:  # noqa: BLE001 — e.g. already-cancelled
                pass

    def fail_worker_futures(self, idx: int, exc: BaseException) -> None:
        """Fail every in-flight future registered to rank ``idx`` — the
        watchdog's fail-fast path on observed death."""
        with self._futures_lock:
            doomed = [(rid, fut) for rid, (fut, i) in self._futures.items()
                      if i == idx]
            for rid, _ in doomed:
                self._futures.pop(rid, None)
        for _, fut in doomed:
            self._fail_future(fut, exc)

    def cancel_pending(self, exc: BaseException) -> None:
        with self._futures_lock:
            entries, self._futures = list(self._futures.values()), {}
        for fut, _idx in entries:
            self._fail_future(fut, exc)

    def raise_if_failed(self) -> None:
        """Raise the permanent typed failure after restart-budget
        exhaustion — callers (and fan-out coordinators) fail immediately
        instead of submitting into a pool that can never answer."""
        exc = self.watchdog.permanent_error()
        if exc is not None:
            raise exc

    # -- submission -----------------------------------------------------------

    async def _submit(self, idx: int, payload: Dict,
                      timeout: Optional[float]) -> Any:
        """Shared request plumbing: liveness check, future registration,
        queue submit, awaited response."""
        worker = self.workers[idx]
        self.raise_if_failed()
        if not worker.alive:
            raise self.watchdog.death_error(idx, worker)
        self._loop = asyncio.get_running_loop()
        # zero-copy envelope encode (ISSUE 10): large arrays in
        # args/kwargs move through the worker's request ring; the queue
        # item carries only {pos, len, dtype, shape, hash} headers. Done
        # BEFORE future registration so an encode failure leaks nothing.
        if getattr(worker, "shm_req", None) is not None \
                and not payload.get("no_shm"):
            threshold = shm_ring.shm_threshold()
            if threshold > 0:
                from .. import telemetry
                with telemetry.stage("shm_copy", dir="req"):
                    n_env = shm_ring.encode_item_fields(
                        payload, worker.shm_req, ("args", "kwargs"),
                        threshold, "req")
                if n_env:
                    payload["_kt_shm"] = n_env
        req_id = f"r{next(self._req_counter)}"
        fut = self._loop.create_future()
        with self._futures_lock:
            self._futures[req_id] = (fut, idx)
        # carry the HTTP request id AND the trace context across the process
        # boundary so the worker's prints stay correlated to this call in
        # the log stream and its spans join the request's trace; submit_ts
        # lets the worker measure queue-wait on its own clock axis
        from .. import telemetry
        from .http_server import request_id_var
        try:
            worker.submit({"req_id": req_id,
                           "request_id": request_id_var.get(""),
                           "trace": telemetry.current_header(),
                           "submit_ts": time.time(), **payload})
        except BaseException as e:  # noqa: BLE001
            # the worker died between the liveness check and the queue put:
            # pop the registered future (it would leak in self._futures
            # forever) and surface the typed death, not a bare queue error
            with self._futures_lock:
                self._futures.pop(req_id, None)
            raise self.watchdog.death_error(idx, worker) from e
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            # a wedged worker never answers this req_id — drop the future
            # or periodic submitters (the 3s user_metrics scrape) leak one
            # registry entry per attempt for the pod's lifetime
            with self._futures_lock:
                self._futures.pop(req_id, None)
            raise

    async def call(self, idx: int, method: Optional[str], args: list,
                   kwargs: dict, timeout: Optional[float] = None,
                   dist_env: Optional[Dict[str, str]] = None) -> Any:
        def _payload(no_shm: bool = False) -> Dict[str, Any]:
            p: Dict[str, Any] = {"method": method, "args": args,
                                 "kwargs": kwargs}
            if dist_env:
                p["dist_env"] = dist_env
            if no_shm:
                p["no_shm"] = True
            return p

        try:
            return await self._submit(idx, _payload(), timeout)
        except DataCorruptionError as e:
            if getattr(e, "source", None) != "shm" \
                    or getattr(e, "key", None) != "req":
                # response-direction corruption means the call already
                # EXECUTED — blind re-execution would violate the
                # never-replay-established discipline, so it surfaces
                # typed instead
                raise
            # a request envelope failed its blake2b check in the worker
            # BEFORE any user code ran (flipped bit in the segment, chaos
            # shm-corrupt): the original arrays are intact on this side, so
            # retry ONCE over the classic queue path — garbage never
            # reaches device_put, and a persistently bad segment degrades
            # to pre-envelope behavior
            print(f"[kt] shm envelope corruption on rank {idx} "
                  f"({e}); retrying over the queue path")
            return await self._submit(idx, _payload(no_shm=True), timeout)

    def subset_env(self, local_rank: int, sel_ips: List[str],
                   sel_node_rank: int) -> Optional[Dict[str, str]]:
        """Selection-relative rank env for a worker-subset call (reference
        per-call env assembly, spmd_supervisor.py:345-364): WORLD_SIZE/RANK/
        MASTER_ADDR reflect the *selected* pods, so e.g. ``workers=[2, 5]``
        behaves as a clean 2-node world for frameworks that initialize their
        collectives inside the request. ``None`` when the framework's identity
        is fixed at spawn (JAX/TPU)."""
        from .env_contract import framework_for

        fw = framework_for(self.framework_name)
        if not fw.per_call_identity:
            return None
        info = RankInfo(node_rank=sel_node_rank, local_rank=local_rank,
                        nproc_per_node=self.num_procs,
                        num_nodes=len(sel_ips), pod_ips=list(sel_ips))
        return fw.env(info)

    async def profile(self, idx: int = 0, duration_s: float = 3.0,
                      timeout: Optional[float] = None) -> Any:
        """Capture a jax.profiler trace in rank subprocess ``idx``."""
        return await self._submit(idx, {"op": "profile",
                                        "duration_s": duration_s},
                                  timeout or duration_s + 60)

    async def user_metrics(self, idx: int = 0,
                           timeout: float = 5.0) -> Dict[str, float]:
        """Rank ``idx``'s ``__kt_metrics__`` gauges ({} when undefined) —
        merged into the pod /metrics scrape by the server."""
        return await self._submit(idx, {"op": "user_metrics"}, timeout)

    async def call_all(self, method: Optional[str], args: list, kwargs: dict,
                       timeout: Optional[float] = None,
                       subset: Optional[tuple] = None) -> List[Any]:
        """``subset=(sel_ips, sel_node_rank)`` rebinds rank identity to the
        selected pod set for this request (see :meth:`subset_env`)."""
        tasks = [self.call(i, method, args, kwargs, timeout,
                           dist_env=(self.subset_env(i, *subset)
                                     if subset else None))
                 for i in range(self.num_procs)]
        return list(await asyncio.gather(*tasks))

    # -- teardown / health ----------------------------------------------------

    def shutdown(self) -> None:
        """Stop every worker: the watchdog stops FIRST (intentional exits
        must not classify as deaths or burn the restart budget), shutdown
        ops go out to ALL workers, one shared join deadline covers them
        together (not per-worker serially), and the response routers stay
        alive until the end so a worker's ``warmup: done`` state op can
        still flip ``in_warmup`` mid-wait — the flag that decides whether
        SIGKILL escalation is allowed (a jit compile in flight must never
        be force-killed while it holds the TPU). Workers still warming get
        one shared KT_WARMUP_SHUTDOWN_GRACE window (default 600s) before
        the last-resort kill."""
        self.watchdog.stop()
        self.cancel_pending(RuntimeError("ProcessPool shutting down"))
        for w in self.workers:
            w.request_shutdown()

        def join_all(deadline: float) -> bool:
            while any(w.alive for w in self.workers):
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.1)
            return True

        done = join_all(time.monotonic() + 5.0)
        if not done and any(w.alive and w.in_warmup for w in self.workers):
            grace = float(os.environ.get("KT_WARMUP_SHUTDOWN_GRACE", "600"))
            deadline = time.monotonic() + grace
            while (time.monotonic() < deadline
                   and any(w.alive and w.in_warmup for w in self.workers)):
                time.sleep(1.0)
            # stragglers past warmup get the normal short window
            join_all(time.monotonic() + 5.0)
        self._stopping.set()
        self.wake_routers()
        for w in self.workers:
            w.force_kill_if_alive()
        # reclaim the wake pipe once every router thread has actually
        # exited — closing an fd a selector still waits on invites reuse
        # races, so a straggler (bounded dead-queue drain) keeps it open
        for t in self._router_threads:
            t.join(timeout=2.0)
        if not any(t.is_alive() for t in self._router_threads):
            for fd in (self._wake_r, self._wake_w):
                try:
                    os.close(fd)
                except OSError:
                    pass

    @property
    def healthy(self) -> bool:
        if self.watchdog.failed:
            return False
        return all(w.alive for w in self.workers)

    @property
    def recovering(self) -> bool:
        """True while the watchdog is mid-respawn — /ready flips unhealthy
        for exactly this window."""
        return self.watchdog.recovering

    @property
    def warming(self) -> bool:
        """True while any live rank is still in its load+warmup window."""
        return any(w.alive and w.in_warmup for w in self.workers)
