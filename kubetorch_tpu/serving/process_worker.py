"""Rank subprocess: loads the user callable and executes requests.

Reference model (``serving/process_worker.py``): a spawned
``multiprocessing.Process`` running an asyncio loop that polls a request
queue and handles requests concurrently (async callables awaited, sync ones
in a thread pool), with per-request distributed env vars and child-process
cleanup on teardown.

TPU-first deltas:
- **spawn** start method is mandatory (fork would duplicate a libtpu handle;
  TPU chips are exclusively owned per-process).
- The framework env (JAX coordinator, TPU_WORKER_ID) is applied *before* the
  callable module is imported, because importing user code typically imports
  jax, which reads these at first device query.
- HBM OOM from XLA is detected and repackaged as a typed ``HbmOomError``.
"""

from __future__ import annotations

import asyncio
import contextvars
import multiprocessing as mp
import os
import queue as queue_mod
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ..exceptions import detect_hbm_oom, package_exception
from ..resources.pointers import Pointers, import_callable
from .env_contract import RankInfo, framework_for

_SYNC_EXECUTOR_THREADS = 40  # matches the server's sync-callable concurrency


# The HTTP X-Request-ID travels server → worker in the request item and is
# re-bound here per handled request, so rank prints stay correlated to the
# originating call even across the process boundary (the reference threads
# the same label through its subprocess LogCapture queue). The trace
# context rides the same envelope: the rank's execute span joins the
# request's trace, and rank log lines carry its trace_id.
_rank_request_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "kt_rank_request_id", default="")


class _QueueTee:
    """Mirror a worker's stream into the response queue so the server-side
    LogCapture ships rank logs too (reference create_subprocess_log_capture,
    serving/log_capture.py:416). Dual-writes so `kubectl logs` still works."""

    def __init__(self, original, response_q, source: str):
        self.original = original
        self.response_q = response_q
        self.source = source

    def write(self, data: str):
        self.original.write(data)
        if data.strip():
            try:
                from .. import telemetry
                self.response_q.put({"op": "log", "line": data.rstrip("\n"),
                                     "source": self.source,
                                     "rank": os.environ.get("RANK", "0"),
                                     "request_id": _rank_request_id.get(""),
                                     "trace_id":
                                         telemetry.current_trace_id() or ""})
            except Exception:
                pass
        return len(data)

    def flush(self):
        self.original.flush()

    def fileno(self):
        # libraries probing the stream (absl/jax logging, subprocess
        # stdout= pass-through) need the REAL descriptor; without this the
        # first fileno() call kills the rank worker mid-request
        return self.original.fileno()

    def isatty(self):
        return False


def _worker_main(request_q: mp.Queue, response_q: mp.Queue,
                 env: Dict[str, str], pointers_dict: Optional[Dict],
                 init_args: Optional[Dict], framework_name: str,
                 identity_env: Optional[Dict[str, str]] = None,
                 shm_spec: Optional[Dict[str, str]] = None) -> None:
    import sys as _sys

    os.environ.update(env)
    _sys.stdout = _QueueTee(_sys.stdout, response_q, "stdout")
    _sys.stderr = _QueueTee(_sys.stderr, response_q, "stderr")
    # Cooperative preemption (ISSUE 6): SIGTERM no longer kills the rank
    # mid-step — it flips the process-local drain flag, the in-flight user
    # step observes it via elastic.drain_requested() and flushes a committed
    # checkpoint inside the grace window, then the loop below exits cleanly.
    # The sender's SIGKILL (kubelet / term-rank chaos) stays the backstop.
    from .elastic import install_sigterm_drain
    install_sigterm_drain()
    # after the tees: a failed sync must reach the rank-log channel
    from .env_contract import sync_jax_runtime_config
    sync_jax_runtime_config()
    # flight recorder (ISSUE 20): armed only when KT_OBS_SPOOL is set —
    # a kill-rank SIGKILL mid-call then leaves this rank's in-flight span
    # and final metric snapshot in its own spool
    from ..obs import maybe_start_recorder
    rank = (identity_env or {}).get("RANK", os.environ.get("RANK", ""))
    maybe_start_recorder(f"rank{rank}" if rank != "" else "rank")
    asyncio.run(_worker_loop(request_q, response_q, pointers_dict, init_args,
                             framework_name, identity_env, shm_spec))


async def _worker_loop(request_q, response_q, pointers_dict, init_args,
                       framework_name, identity_env=None,
                       shm_spec=None) -> None:
    loop = asyncio.get_running_loop()
    executor = ThreadPoolExecutor(max_workers=_SYNC_EXECUTOR_THREADS)
    target: Any = None
    load_error: Optional[BaseException] = None
    # zero-copy envelope rings (ISSUE 10): the parent created one segment
    # per direction; attach both (req: parent writes / this rank reads,
    # resp: this rank writes / parent reads). Attach failure downgrades to
    # the classic queue path — never a dead rank.
    rings: Dict[str, Any] = {}
    if shm_spec:
        from . import shm_ring
        try:
            rings["req"] = shm_ring.ShmRing(shm_spec["req"])
            rings["resp"] = shm_ring.ShmRing(shm_spec["resp"])
        except Exception:  # noqa: BLE001 — degrade, don't die
            for r in rings.values():
                r.close()
            rings = {}
            print("[kt] shm ring attach failed; falling back to queue "
                  "path:\n" + traceback.format_exc())
    # process-level chaos (ISSUE 3/6): KT_CHAOS kill-rank verbs make THIS
    # rank kill itself at a chosen call index — the deterministic stand-in
    # for an OOM kill landing mid-call — and term-rank verbs deliver the
    # graceful SIGTERM + grace-window SIGKILL pair (the GKE preemption
    # contract) so the drain-and-checkpoint path is testable too
    from ..chaos import rank_kill_plan, rank_term_plan
    from .elastic import drain_requested
    kill_plan = rank_kill_plan()
    term_plan = rank_term_plan()
    call_index = 0

    # Eager-load the callable at spawn (reference :236-247) so first-request
    # latency excludes import cost, and failures surface in health checks.
    # The state ops bracket the load+warmup window: the parent ProcessPool
    # marks the worker in_warmup and (a) /ready reports not-ready until done,
    # (b) shutdown withholds its SIGKILL escalation — a jit compile in
    # flight must never be force-killed (it can wedge the TPU runtime).
    response_q.put({"op": "state", "warmup": "started"})
    if pointers_dict:
        try:
            target = _load_target(pointers_dict, init_args)
        except BaseException as e:  # noqa: BLE001 — must report, not die
            load_error = e
        else:
            await _run_warmup(target)
    response_q.put({"op": "state", "warmup": "done"})

    pending = set()

    def poll():
        try:
            return request_q.get(timeout=0.2)
        except queue_mod.Empty:
            return None

    while True:
        item = await loop.run_in_executor(None, poll)
        if item is None:
            pending = {t for t in pending if not t.done()}
            if drain_requested() and not pending:
                # cooperative drain completed: every in-flight step has
                # observed the flag (and flushed its checkpoint) — exit
                # cleanly so the parent's watchdog classifies a drained
                # rank, not an anonymous kill, and the elastic layer can
                # resume from the fresh commit with zero lost steps
                print("[kt] rank draining: all in-flight work done, exiting")
                framework_for(framework_name).worker_cleanup()
                break
            continue
        if item.get("op") == "shutdown":
            framework_for(framework_name).worker_cleanup()
            break
        if item.get("op") == "profile":
            task = asyncio.ensure_future(_handle_profile(item, response_q))
        elif item.get("op") == "user_metrics":
            task = asyncio.ensure_future(
                _handle_user_metrics(item, target, response_q, executor))
        else:
            if kill_plan:
                sig = kill_plan.get(call_index)
                if sig is not None:
                    # mid-call by construction: the parent registered this
                    # req's future at submit, and no response will ever come
                    print(f"[kt] chaos: kill-rank sig={sig} "
                          f"at call index {call_index}")
                    os.kill(os.getpid(), sig)
            if term_plan:
                grace = term_plan.get(call_index)
                if grace is not None:
                    term_plan.pop(call_index)
                    _chaos_term_self(grace, call_index)
            call_index += 1
            if item.get("_kt_shm"):
                # envelopes decode IMMEDIATELY at dequeue (queue order ==
                # ring order, so slots free in allocation order); a hash
                # mismatch answers this req_id with the typed corruption
                # error — the parent pool retries once over the queue path
                from .. import telemetry
                from . import shm_ring
                try:
                    with telemetry.stage("shm_copy", dir="req"):
                        shm_ring.decode_item_fields(
                            item, rings.get("req"), ("args", "kwargs"),
                            "req")
                except BaseException as e:  # noqa: BLE001
                    from ..exceptions import package_exception
                    response_q.put({"req_id": item.get("req_id"),
                                    "ok": False,
                                    "error": package_exception(e)})
                    continue
            task = asyncio.ensure_future(
                _handle(item, target, load_error, response_q, executor,
                        identity_env, rings.get("resp")))
        pending.add(task)
    for r in rings.values():
        r.close()


def _chaos_term_self(grace_s: float, call_index: int) -> None:
    """term-rank chaos: the GKE preemption contract, self-delivered — the
    op just dequeued still runs and can flush a checkpoint inside the
    grace window. Delivery itself (SIGTERM + daemon SIGKILL timer) is the
    shared :func:`~..chaos.deliver_term_with_grace` contract, the same one
    scheduler-preemption tests use against external pids."""
    from ..chaos import deliver_term_with_grace

    deliver_term_with_grace(os.getpid(), grace_s,
                            label=f"term-rank at call index {call_index}")


def _host_view(obj: Any) -> Any:
    """Device arrays can't cross the mp.Queue (no cross-process device
    handles on TPU — SURVEY §2.9); pull them to host numpy here."""
    t = type(obj)
    if t.__module__.startswith(("jax", "jaxlib")) and hasattr(obj, "dtype"):
        import numpy as np
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _host_view(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        vals = [_host_view(v) for v in obj]
        return type(obj)(*vals) if hasattr(obj, "_fields") else tuple(vals)
    if isinstance(obj, list):
        return [_host_view(v) for v in obj]
    return obj


async def _run_warmup(target: Any) -> None:
    """Run the user's ``__kt_warmup__`` hook (method on a class instance, or
    attribute attached to a function) right after the eager load — inference
    pools pay jit compilation at deploy time, not on the first user request
    (``/ready`` reports not-ready until the bracketing state ops complete).
    A failed warmup is logged (the stream tee ships it to the supervisor's
    rank logs) but never poisons the worker: requests may still succeed, and
    if not they produce their own errors."""
    hook = getattr(target, "__kt_warmup__", None)
    if hook is None:
        return
    try:
        result = hook()
        if asyncio.iscoroutine(result):
            await result
    except BaseException:  # noqa: BLE001
        print(f"[kt] __kt_warmup__ failed:\n{traceback.format_exc()}")


def _load_target(pointers_dict: Dict, init_args: Optional[Dict]) -> Any:
    obj = import_callable(Pointers.from_dict(pointers_dict))
    if isinstance(obj, type):
        args = (init_args or {}).get("args", [])
        kwargs = (init_args or {}).get("kwargs", {})
        return obj(*args, **kwargs)
    return obj


async def _handle_profile(item: Dict, response_q) -> None:
    """Capture a jax.profiler trace in THIS process — the one that owns the
    TPU chips (the profiling story replacing the reference's DCGM/OTel,
    SURVEY §5.1). Produces a TensorBoard-loadable trace directory."""
    req_id = item.get("req_id")
    try:
        import glob
        import tempfile

        import jax

        duration = float(item.get("duration_s", 3.0))
        outdir = item.get("outdir") or tempfile.mkdtemp(prefix="kt-profile-")
        with jax.profiler.trace(outdir):
            await asyncio.sleep(duration)
        files = sorted(glob.glob(os.path.join(outdir, "**", "*"),
                                 recursive=True))
        response_q.put({"req_id": req_id, "ok": True,
                        "result": {"trace_dir": outdir,
                                   "files": [f for f in files
                                             if os.path.isfile(f)]}})
    except BaseException as e:  # noqa: BLE001
        response_q.put({"req_id": req_id, "ok": False,
                        "error": package_exception(e)})


async def _handle_user_metrics(item: Dict, target: Any, response_q,
                               executor) -> None:
    """Poll the user's ``__kt_metrics__`` hook (sibling of
    ``__kt_warmup__``): a dict of numeric gauges the pod's ``/metrics``
    scrape merges under a ``kt_user_`` prefix — how long-lived serving
    state (the generation engine's tokens/s, acceptance rate, slot
    occupancy) reaches Prometheus without the user writing an exporter.
    Runs on every scrape (3s): keep the hook cheap. Absent hook → {}.
    Sync hooks run in the executor like regular calls (``_handle``) — a
    blocking hook must stall its scrape, never the worker loop that every
    in-flight request's response rides on."""
    req_id = item.get("req_id")
    try:
        hook = getattr(target, "__kt_metrics__", None)
        result = {}
        if hook is not None:
            loop = asyncio.get_running_loop()
            out = await loop.run_in_executor(executor, hook)
            if asyncio.iscoroutine(out):
                out = await out
            result = {str(k): float(v) for k, v in (out or {}).items()
                      if isinstance(v, (int, float))}
        response_q.put({"req_id": req_id, "ok": True, "result": result})
    except BaseException as e:  # noqa: BLE001 — a broken hook must not
        # poison the worker; the scrape just misses user gauges
        response_q.put({"req_id": req_id, "ok": False,
                        "error": package_exception(e)})


def _ship_trace_spans(response_q, sp) -> None:
    """Send every finished span of this request's trace (the execute span
    plus whatever user code opened under it — store fetches, nested store
    requests) back to the parent process, where the pool ingests them into
    the server's ring. Re-shipped prefixes dedup there by span id."""
    from .. import telemetry

    d = sp.to_dict() if sp else None
    if d is None:
        return
    to_ship = telemetry.RING.find(d["trace_id"])
    # checkpoint spans can finish OFF this trace (the drain-path sync save,
    # an async commit whose step already returned): ship the recent ones
    # too so the pool can derive kt_checkpoint_seconds in the process that
    # actually serves /metrics — the parent ring dedups re-ships
    shipped = {(s.get("trace_id"), s.get("span_id")) for s in to_ship}
    for span_dict in telemetry.RING.snapshot(limit=32):
        if str(span_dict.get("name", "")).startswith("checkpoint.") and \
                (span_dict.get("trace_id"),
                 span_dict.get("span_id")) not in shipped:
            to_ship.append(span_dict)
    for span_dict in to_ship:
        try:
            response_q.put({"op": "span", "span": span_dict})
        except Exception:  # noqa: BLE001 — telemetry must not fail the call
            pass


async def _handle(item: Dict, target: Any, load_error, response_q, executor,
                  identity_env: Optional[Dict[str, str]] = None,
                  resp_ring=None) -> None:
    import time as _time

    from .. import telemetry

    req_id = item.get("req_id")
    _rank_request_id.set(item.get("request_id", ""))
    now = _time.time()
    queue_wait = max(0.0, now - float(item.get("submit_ts") or now))
    sp = telemetry.span(
        "worker.execute", parent=telemetry.parse_trace(item.get("trace")),
        rank=os.environ.get("RANK", "0"), method=item.get("method") or "",
        request_id=item.get("request_id", ""),
        queue_wait_s=round(queue_wait, 6))
    try:
        with sp:
            await _handle_inner(item, target, load_error, response_q,
                                executor, sp, identity_env, resp_ring)
    finally:
        _ship_trace_spans(response_q, sp)


async def _handle_inner(item: Dict, target: Any, load_error, response_q,
                        executor, sp,
                        identity_env: Optional[Dict[str, str]] = None,
                        resp_ring=None) -> None:
    from .. import telemetry

    req_id = item.get("req_id")
    try:
        if load_error is not None:
            raise load_error
        if target is None:
            raise RuntimeError("No callable loaded in worker")
        # Per-call rank identity: a worker-subset call carries dist_env with
        # selection-relative WORLD_SIZE/RANK/...; a full-set call carries
        # none and must restore the spawn identity (a previous subset call's
        # values would otherwise leak into it). Process-global by nature,
        # like the reference's per-request env writes — overlapping calls
        # with different selections are a caller error there too.
        dist_env = item.get("dist_env") or identity_env
        if dist_env:
            os.environ.update(dist_env)
        method = item.get("method")
        fn = getattr(target, method) if method else target
        args = item.get("args", [])
        kwargs = item.get("kwargs", {})
        if asyncio.iscoroutinefunction(fn):
            result = await fn(*args, **kwargs)
        else:
            loop = asyncio.get_running_loop()
            # copy_context: run_in_executor does not propagate contextvars,
            # and sync user code printing from the executor thread must keep
            # its request-id binding
            ctx = contextvars.copy_context()
            result = await loop.run_in_executor(
                executor, lambda: ctx.run(lambda: fn(*args, **kwargs)))
        with telemetry.stage("device_transfer"):
            # pulling device arrays to host numpy is the rank's last
            # per-request device touch — the transfer stage on the waterfall
            host = _host_view(result)
        resp = {"req_id": req_id, "ok": True, "result": host}
        if resp_ring is not None and not item.get("no_shm"):
            # result arrays ride the response ring the same way args rode
            # the request ring; encode and enqueue with no await between
            # them so queue order stays ring-allocation order
            from . import shm_ring
            threshold = shm_ring.shm_threshold()
            if threshold > 0:
                with telemetry.stage("shm_copy", dir="resp"):
                    n = shm_ring.encode_item_fields(
                        resp, resp_ring, ("result",), threshold, "resp")
                if n:
                    resp["_kt_shm"] = n
        response_q.put(resp)
    except BaseException as e:  # noqa: BLE001
        oom = detect_hbm_oom(e)
        payload = package_exception(oom if oom is not None else e)
        sp.set_status("error")
        sp.set_attr("error", payload.get("error_type", type(e).__name__))
        response_q.put({"req_id": req_id, "ok": False, "error": payload})


class ProcessWorker:
    """Handle to one rank subprocess."""

    def __init__(self, rank_info: RankInfo, framework_name: str,
                 pointers: Optional[Pointers], init_args: Optional[Dict],
                 base_env: Optional[Dict[str, str]] = None):
        self.rank_info = rank_info
        self.framework_name = framework_name
        ctx = mp.get_context("spawn")
        self.request_q: mp.Queue = ctx.Queue()
        self.response_q: mp.Queue = ctx.Queue()
        fw = framework_for(framework_name)
        fw_env = fw.env(rank_info)
        env = dict(base_env or {})
        env.update(fw_env)
        self.env = env
        # Spawn-time identity, re-applied on every full-set call for
        # frameworks with per-call identity so a subset call's rebinding
        # never leaks into the next request. None for spawn-fixed identity
        # (JAX/TPU): those workers never touch env per request.
        identity_env = fw_env if fw.per_call_identity else None
        # flipped by ProcessPool._route_responses from the worker's state ops
        self.in_warmup = True
        # zero-copy envelope rings (ISSUE 10): one segment per direction,
        # created by THIS side (which owns their lifecycle — see
        # cleanup_shm) and attached by name in the child. Only built when
        # KT_SHM_THRESHOLD opts the deployment in; creation failure (tiny
        # /dev/shm, exotic platform) downgrades to the queue path.
        self.shm_req = self.shm_resp = None
        shm_spec = None
        from . import shm_ring
        if shm_ring.enabled():
            try:
                size = shm_ring.ring_bytes()
                tag = f"r{rank_info.local_rank}"
                self.shm_req = shm_ring.ShmRing(
                    shm_ring.make_name(f"{tag}-req"), size=size, create=True)
                self.shm_resp = shm_ring.ShmRing(
                    shm_ring.make_name(f"{tag}-resp"), size=size, create=True)
                shm_spec = {"req": self.shm_req.name,
                            "resp": self.shm_resp.name}
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                self.cleanup_shm()
                print(f"[kt] shm ring create failed ({e}); "
                      "using queue path")
        self.process = ctx.Process(
            target=_worker_main,
            args=(self.request_q, self.response_q, env,
                  pointers.to_dict() if pointers else None, init_args,
                  framework_name, identity_env, shm_spec),
            daemon=True,
        )

    def start(self) -> None:
        self.process.start()

    def submit(self, req: Dict) -> None:
        self.request_q.put(req)

    def request_shutdown(self) -> None:
        """Enqueue the graceful-stop op (non-blocking). The worker handles it
        after finishing any in-flight load/warmup."""
        try:
            self.request_q.put({"op": "shutdown"})
        except Exception:
            pass

    def force_kill_if_alive(self) -> None:
        """Last-resort SIGKILL. Callers (ProcessPool.shutdown) must have
        already granted the warmup grace — a process killed mid-jit-compile
        while holding the TPU can wedge the runtime for every successor.
        Always reclaims this worker's shared-memory rings afterwards: a
        rank retired by ANY path (watchdog restart, elastic re-mesh,
        shutdown) must never leak ``/dev/shm`` segments."""
        if self.process.is_alive():
            from ..utils.procs import kill_process_tree
            if self.in_warmup:
                print(f"[kt] rank {self.rank_info.rank} still in warmup at "
                      "kill escalation; TPU runtime may need a reset")
            kill_process_tree(self.process.pid)
        self.cleanup_shm()

    def cleanup_shm(self) -> None:
        """Close + unlink both envelope rings (idempotent). The creating
        side owns segment lifecycle; the watchdog and every restart path
        land here, so a dead rank's segments are reclaimed within one
        watchdog interval."""
        for attr in ("shm_req", "shm_resp"):
            ring = getattr(self, attr, None)
            if ring is not None:
                setattr(self, attr, None)
                ring.unlink()
                ring.close()

    @property
    def alive(self) -> bool:
        return self.process.is_alive()

    @property
    def exitcode(self) -> Optional[int]:
        """``multiprocessing`` exitcode (negative = killed by that signal);
        None while alive or never started — the watchdog's classification
        input."""
        return self.process.exitcode
