"""Ray distribution mode: head-only controller.

Reference (``serving/ray_supervisor.py``): the rank-0 pod starts the Ray head
(GCS), workers join via ``ray start --address``, user code runs only on the
head (1 subprocess) and uses Ray's own scheduling for fan-out. DNS membership
monitoring is off — Ray owns membership.

TPU note: Ray mode is the CPU-side orchestration option; TPU workloads route
through the SPMD/JAX path (SURVEY §2.9). Requires ``ray`` in the image.
"""

from __future__ import annotations

import shutil
import subprocess
import time
from typing import Dict, Optional

from ..utils.procs import wait_for_port
from .discovery import my_pod_ip
from .execution_supervisor import DistributedSupervisor, ExecutionSupervisor

GCS_PORT = 6379


class RaySupervisor(DistributedSupervisor):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._ray_proc: Optional[subprocess.Popen] = None
        self._is_head = False

    def num_procs(self) -> int:
        return 1  # user code runs on the head only

    def setup(self) -> None:
        import os

        if shutil.which("ray") is None:
            raise RuntimeError(
                "distribution_type='ray' requires ray in the image: "
                "Image().pip_install(['ray'])")
        ips = sorted(self.discover() or [my_pod_ip()])
        role = os.environ.get("KT_RAY_ROLE")
        if role:
            # KubeRay provisioning (build_raycluster_manifest) designates
            # head/worker per group — runtime must honor it, not re-elect:
            # the headGroupSpec pod is where KubeRay routes dashboard/GCS.
            # Workers find the head by probing for the live GCS (its IP has
            # no fixed rank in the discovered set).
            self._is_head = role == "head"
            head_ip = (my_pod_ip() if self._is_head
                       else self._find_gcs(self.discover))
        else:
            # homogeneous pods (Deployment/JobSet path): elect by lowest IP
            head_ip = ips[0]
            self._is_head = my_pod_ip() == head_ip or len(ips) == 1
        if self._is_head:
            self._ray_proc = subprocess.Popen(
                ["ray", "start", "--head", "--port", str(GCS_PORT),
                 "--disable-usage-stats", "--block"])
            if not wait_for_port(head_ip, GCS_PORT, timeout=60):
                raise RuntimeError("Ray GCS failed to start")
            # ExecutionSupervisor (grandparent) setup ON PURPOSE: one local
            # ProcessWorker for user code, no quorum wait and no DNS
            # membership monitor — Ray owns membership (reference :126-129),
            # and workers join the GCS on their own schedule
            ExecutionSupervisor.setup(self)
        else:
            self._ray_proc = subprocess.Popen(
                ["ray", "start", "--address", f"{head_ip}:{GCS_PORT}",
                 "--disable-usage-stats", "--block"])
            # workers host Ray worker processes only; no callable pool
            self.pool = None
        # Ray owns membership; no DNS monitor (reference :126-129)

    @staticmethod
    def _find_gcs(discover, timeout: float = 120.0) -> str:
        """The head's GCS is the one answering :6379 — workers poll until it
        comes up. Discovery RE-RUNS every iteration: head and workers start
        concurrently, and a worker that resolved DNS before the head's
        headless-service record was published would otherwise probe a stale
        snapshot for the whole timeout."""
        deadline = time.monotonic() + timeout
        ips = []
        while time.monotonic() < deadline:
            ips = sorted(discover() or [])
            for ip in ips:
                if wait_for_port(ip, GCS_PORT, timeout=0.5):
                    return ip
            time.sleep(1.0)
        raise RuntimeError(f"no Ray GCS found on {ips} within {timeout}s")

    def cleanup(self) -> None:
        # User-code Ray state lives in the rank subprocess; its shutdown op
        # (ProcessWorker) runs framework cleanup before the head dies.
        super().cleanup()
        if self._ray_proc is not None and self._ray_proc.poll() is None:
            subprocess.run(["ray", "stop", "--force"], capture_output=True)
            self._ray_proc.terminate()
            self._ray_proc = None

    @property
    def healthy(self) -> bool:
        if self._is_head:
            return super().healthy
        return self._ray_proc is not None and self._ray_proc.poll() is None

    async def call(self, method, args, kwargs, **kw):
        if not self._is_head:
            raise RuntimeError("Ray calls must target the head pod")
        return await super().call(method, args, kwargs, **kw)
