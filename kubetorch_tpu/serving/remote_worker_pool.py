"""Async pod→pod fan-out client.

Reference (``serving/remote_worker_pool.py``): a singleton subprocess with its
own asyncio loop and a 2000-connection httpx pool, so huge fan-outs never
block the server loop. Here the server *is* async (aiohttp) end to end, so a
separate process buys nothing — we keep the big connection pool and the
health-gated, fast-fail semantics, in-process.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, List, Optional

import aiohttp

from ..exceptions import WorkerCallError, rehydrate_exception
from .. import serialization as ser

MAX_CONNECTIONS = 2000
SUBCALL_PARAM = "distributed_subcall"


class RemoteWorkerPool:
    _instance: Optional["RemoteWorkerPool"] = None

    def __init__(self, server_port: int = 32300):
        self.server_port = server_port
        self._session: Optional[aiohttp.ClientSession] = None

    @classmethod
    def shared(cls, server_port: int = 32300) -> "RemoteWorkerPool":
        if cls._instance is None or cls._instance.server_port != server_port:
            cls._instance = cls(server_port)
        return cls._instance

    async def session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            conn = aiohttp.TCPConnector(limit=MAX_CONNECTIONS)
            self._session = aiohttp.ClientSession(connector=conn)
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def check_health(self, ip: str, timeout: float = 2.0) -> bool:
        try:
            sess = await self.session()
            async with sess.get(f"http://{ip}:{self.server_port}/health",
                                timeout=aiohttp.ClientTimeout(total=timeout)) as r:
                return r.status == 200
        except Exception:
            return False

    async def call_worker(self, ip: str, fn_name: str, method: Optional[str],
                          body: Dict[str, Any], headers: Dict[str, str],
                          timeout: Optional[float] = None,
                          subtree: Optional[List[str]] = None,
                          sel_ips: Optional[List[str]] = None) -> Any:
        """One subcall to a peer pod. ``subtree`` tells the peer which workers
        it coordinates below itself (tree fan-out); ``sel_ips`` carries the
        ordered worker selection so the peer rebinds its rank identity
        relative to the subset (each pod derives its node rank by indexing
        itself in the list)."""
        path = f"/{fn_name}" + (f"/{method}" if method else "")
        params = {SUBCALL_PARAM: "true"}
        payload = dict(body)
        if subtree:
            payload["_kt_subtree"] = subtree
        if sel_ips:
            payload["_kt_sel_ips"] = sel_ips
        sess = await self.session()
        try:
            async with sess.post(
                f"http://{ip}:{self.server_port}{path}",
                data=ser.serialize(payload, ser.JSON),
                params=params,
                headers={**headers, "Content-Type": "application/json"},
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                raw = await resp.read()
                if resp.status != 200:
                    try:
                        err = json.loads(raw.decode())
                        raise rehydrate_exception(err)
                    except (ValueError, KeyError):
                        raise WorkerCallError(
                            f"Worker {ip} returned {resp.status}: {raw[:500]!r}",
                            worker=ip)
                fmt = resp.headers.get("X-Serialization", ser.JSON)
                return ser.deserialize(raw, fmt)
        except aiohttp.ClientError as e:
            raise WorkerCallError(f"Worker {ip} unreachable: {e}", worker=ip) from e
