"""The serving front door: continuous-batching, affinity-aware replica
routing with deadline shedding (ISSUE 9).

``load_balanced`` dispatch used to be a 75-line round-robin: one call → one
pod, an extra health-probe RTT per call, no admission control, no memory of
where a session's state lives. This module is the real inference router
that replaces its selection loop — and the ONLY place in ``serving/`` that
may decide which replica a call lands on (``scripts/check_resilience.py``
lints for strays):

- **Continuous batching across replicas.** The router keeps per-replica
  in-flight/slot accounting (``KT_SERVE_SLOTS`` mirrors the engine's slot
  grid) and packs keyless requests onto the replica with the FULLEST
  partially-full decode batch, so fleets run few hot batches instead of
  many one-deep ones — the cross-replica twin of the engine's slot-grid
  admission. Idle replicas are used round-robin; depth is measured
  (``kt_serve_batch_depth``).
- **Affinity routing.** A session/adapter key (``X-KT-Session`` header or
  well-known kwargs — see :func:`affinity_key`) routes to the replica
  where its prefix K/V or adapter bank is already resident
  (:class:`SessionTable`), falling back to a consistent hash over the
  current replica set when cold — so residency builds deterministically
  instead of smearing across the fleet. Hit/miss counters
  (``kt_serve_affinity_total``) prove the win; the engine-side half is
  ``serve/sessions.py``.
- **Deadline-aware admission + load shedding.** ``X-KT-Deadline`` (on the
  wire since the resilience layer) is checked at the door: already-expired
  → typed 504 without touching a replica; unmeetable against the measured
  queue-wait estimate → typed 429 ``AdmissionShedError``. The admission
  queue is bounded (``KT_SERVE_QUEUE_MAX``); when full, the lowest
  priority tier sheds first (``X-KT-Priority``, the scheduler's bands).
- **Queue-wait telemetry the autoscaler spends.** Time spent in the
  admission queue lands in the ``kt_stage_seconds{stage="queue_wait"}``
  histogram — the series the controller's SLO loop scrapes to size the
  fleet (``KT_SERVE_SLO_MS``).
- **Canary traffic pinning (ISSUE 11).** During a live weight rollout the
  canary replica gets exactly a configured slice of keyless traffic
  (:meth:`Router.set_canary`) while everything else avoids it; per-call
  error/latency lands on the canary ledger and
  :meth:`Router.canary_verdict` judges it against the PRE-SWAP service
  EWMA — the signal ``serve.rollout.CanaryRollout`` turns into an
  automatic promote-or-rollback decision.

Health is cached with a short TTL (:class:`HealthCache`) instead of
probed per dispatch — the per-call RTT the old supervisor paid — and
invalidated the moment a transport error proves a replica dead.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from .. import telemetry
from ..constants import PRIORITY_HEADER, SESSION_HEADER
from ..exceptions import (AdmissionShedError, DeadlineExceededError,
                          WorkerCallError)
from ..resilience import DEADLINE_HEADER, Deadline


_CANARY_REQS = telemetry.counter(
    "kt_serve_canary_requests_total",
    "Requests routed to the live-rollout canary replica, by outcome",
    labels=("result",))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def request_priority(headers: Optional[Dict[str, str]]) -> Tuple[int, str]:
    """(priority, tier) from ``X-KT-Priority`` — the scheduler's bands
    (≥70 high / 40-69 normal / <40 batch), so one priority vocabulary
    covers both placement and request shedding."""
    from ..controller.scheduler import parse_priority, tier_of
    raw = None
    if headers:
        raw = headers.get(PRIORITY_HEADER) or headers.get(
            PRIORITY_HEADER.lower())
    prio = parse_priority(raw)
    return prio, tier_of(prio)


def affinity_key(headers: Optional[Dict[str, str]],
                 kwargs: Optional[Dict[str, Any]]) -> Optional[str]:
    """The routing key one call carries: the explicit session header wins;
    else well-known kwargs (``session_id``, ``session``, ``prefix_id``,
    ``adapter_id``) — a request pinned to a cached prefix or LoRA adapter
    benefits from landing where that state is resident even when the
    caller never named a session. Mirrors ``serve.sessions.session_key``
    (kept import-free of the engine side on purpose)."""
    if headers:
        val = headers.get(SESSION_HEADER) or headers.get(
            SESSION_HEADER.lower())
        if val:
            return str(val)
    if kwargs:
        for field in ("session_id", "session", "prefix_id", "adapter_id"):
            val = kwargs.get(field)
            if val is not None:
                return f"{field}:{val}"
    return None


class HealthCache:
    """TTL-cached replica health (ISSUE 9 satellite: the old supervisor
    awaited ``pool.check_health(target)`` on EVERY dispatch — an extra RTT
    per call). A probe result is trusted for ``ttl_s``; a transport error
    on an actual call is stronger evidence than any probe and marks the
    replica down immediately (:meth:`mark_down`), so failover never waits
    out a stale "healthy". Avoided probes are counted."""

    def __init__(self, ttl_s: Optional[float] = None):
        self.ttl_s = (ttl_s if ttl_s is not None
                      else _env_float("KT_SERVE_HEALTH_TTL_S", 2.0))
        self._cache: Dict[str, Tuple[bool, float]] = {}
        self._lock = threading.Lock()

    async def healthy(self, pool, ip: str) -> bool:
        m = telemetry.serve_metrics()
        now = time.monotonic()
        with self._lock:
            entry = self._cache.get(ip)
        if entry is not None and now - entry[1] < self.ttl_s:
            m["probes_avoided"].inc()
            return entry[0]
        ok = await pool.check_health(ip)
        m["probes"].inc()
        with self._lock:
            self._cache[ip] = (ok, time.monotonic())
        return ok

    def mark_down(self, ip: str) -> None:
        with self._lock:
            self._cache[ip] = (False, time.monotonic())

    def invalidate(self, ip: str) -> None:
        with self._lock:
            self._cache.pop(ip, None)


class SessionTable:
    """Router-side residency map: affinity key → the replica last serving
    it. LRU + TTL bounded — an abandoned session must not pin a replica
    forever, and the table must stay O(active sessions) at million-user
    scale. The engine-side prefix residency this map points at is
    ``serve.sessions.EngineSessionBinder``."""

    def __init__(self, capacity: Optional[int] = None,
                 ttl_s: Optional[float] = None):
        self.capacity = (capacity if capacity is not None
                         else _env_int("KT_SERVE_SESSIONS", 65536))
        self.ttl_s = (ttl_s if ttl_s is not None
                      else _env_float("KT_SERVE_SESSION_TTL_S", 600.0))
        self._entries: "OrderedDict[str, Tuple[str, float]]" = OrderedDict()
        self._lock = threading.Lock()

    def lookup(self, key: str) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            ip, seen = entry
            if now - seen > self.ttl_s:
                del self._entries[key]
                return None
            self._entries.move_to_end(key)   # a lookup IS recency
            return ip

    def touch(self, key: str, replica: str) -> None:
        now = time.monotonic()
        with self._lock:
            self._entries.pop(key, None)
            self._entries[key] = (replica, now)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def evict_replica(self, replica: str) -> int:
        """Forget every session resident on a dead replica — their prefix
        K/V died with it; the next turn should hash to a fresh home, not
        chase a ghost."""
        with self._lock:
            dead = [k for k, (ip, _t) in self._entries.items()
                    if ip == replica]
            for k in dead:
                del self._entries[k]
        return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class _Waiter:
    """One queued admission: woken in priority order, shed when the queue
    overflows or its deadline lapses."""

    __slots__ = ("priority", "tier", "seq", "future", "enqueued_at")

    def __init__(self, priority: int, tier: str, seq: int,
                 future: "asyncio.Future[None]"):
        self.priority = priority
        self.tier = tier
        self.seq = seq
        self.future = future
        self.enqueued_at = time.monotonic()

    def sort_key(self) -> Tuple[int, int]:
        # highest priority first; FIFO within a band
        return (-self.priority, self.seq)

    def __lt__(self, other: "_Waiter") -> bool:
        return self.sort_key() < other.sort_key()


class Router:
    """One per ``LoadBalancedSupervisor`` (i.e. per pod per service). Every
    pod routes with the same policy over the same membership and the same
    consistent hash, so any pod's front door sends a session to the same
    home — no coordination needed, exactly the store ring's trick."""

    def __init__(self, server_port: int = 32300, fn_name: str = "", *,
                 slots_per_replica: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 health_ttl_s: Optional[float] = None,
                 session_capacity: Optional[int] = None,
                 session_ttl_s: Optional[float] = None):
        self.server_port = server_port
        self.fn_name = fn_name
        self.slots = (slots_per_replica if slots_per_replica is not None
                      else _env_int("KT_SERVE_SLOTS", 8))
        self.queue_max = (queue_max if queue_max is not None
                          else _env_int("KT_SERVE_QUEUE_MAX", 256))
        self.health = HealthCache(ttl_s=health_ttl_s)
        self.sessions = SessionTable(capacity=session_capacity,
                                     ttl_s=session_ttl_s)
        self._inflight: Dict[str, int] = {}
        self._active = 0              # total in-flight through this router
        self._capacity = self.slots   # refreshed per dispatch (elastic fleet)
        self._waiters: List[_Waiter] = []
        self._rr = itertools.count()
        self._seq = itertools.count()
        # EWMA of per-request service seconds: the doomed-request estimator.
        # None until the first completion — the router never sheds on a
        # guess it hasn't measured.
        self._ewma_s: Optional[float] = None
        # consistent-hash ring cached per membership: building one is
        # O(nodes × vnodes) blake2b hashes — far too hot to pay per miss
        self._ring: Tuple[Tuple[str, ...], Any] = ((), None)
        # live-rollout canary state (set_canary/clear_canary); None when no
        # canary bake is in flight
        self._canary: Optional[Dict[str, Any]] = None
        # readiness fence (ISSUE 16): replicas the aggressive autoscaler
        # admitted before their cold start finished. A warming replica is
        # ordered LAST and must pass a FRESH health probe before its
        # first dispatch — the fast-scale path may add capacity early,
        # but a request is never the thing that discovers a dead boot. A
        # background prober (started when a pool is known) clears the
        # fence the moment a boot completes, so new capacity takes
        # traffic in one probe interval even while the rest of the fleet
        # stays healthy; the in-dispatch probe is the last resort, not
        # the admission path.
        self._warming: Dict[str, float] = {}
        self.warming_ttl_s = _env_float("KT_SERVE_WARMING_TTL_S", 120.0)
        self.warming_probe_s = _env_float("KT_SERVE_WARMING_PROBE_S", 0.5)
        self._members: Optional[set] = None
        self._prober_task: Optional["asyncio.Task"] = None

    # -- readiness fence ------------------------------------------------------

    def mark_warming(self, ip: str, pool=None) -> None:
        """Admit a still-booting replica behind the fence. Invalidates
        any cached health for it — a stale "healthy" from a previous
        generation at this ip must not leak through the fence. With a
        ``pool`` (the production path — :meth:`observe_membership`), a
        background prober starts immediately so the fence clears on the
        replica's own readiness, not on the next request's failover."""
        self._warming[ip] = time.monotonic()
        self.health.invalidate(ip)
        if pool is not None:
            self._ensure_warming_prober(pool)

    def observe_membership(self, ips: List[str], pool=None) -> None:
        """The membership seam the fence is wired from: every dispatch
        hands the current replica set through here (the supervisor's
        ``pod_ips``), and any ip that was not in the previous set is a
        freshly admitted replica — fenced until a probe passes. The first
        observation is the baseline fleet (this pod is already serving
        through it) and fences nothing; departed ips drop their warming
        mark so a scale-down never leaves ghosts behind the fence."""
        current = set(ips)
        if self._members is None:
            self._members = current
            return
        for ip in current - self._members:
            self.mark_warming(ip, pool=pool)
        for ip in set(self._warming) - current:
            self._warming.pop(ip, None)
            telemetry.cold_start_metrics()["fence"].inc(result="departed")
        self._members = current
        if self._warming and pool is not None:
            self._ensure_warming_prober(pool)

    def _ensure_warming_prober(self, pool) -> None:
        if self._prober_task is not None and not self._prober_task.done():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return      # sync context (tests): the dispatch fence still holds
        self._prober_task = loop.create_task(self._probe_warming(pool))

    async def _probe_warming(self, pool) -> None:
        """Proactively probe every warming replica until the fence set
        drains: a passing probe admits the replica (``fence_ready``) so
        fast-scale capacity starts taking traffic the moment it is ready
        — NOT only when every settled replica has already failed. Probes
        bypass the health cache (a warming replica's state changes faster
        than the TTL) and a failed probe keeps the fence up for the next
        round; the warming TTL still bounds a boot that never comes up."""
        try:
            while self._warming:
                for ip in list(self._warming):
                    if not self._is_warming(ip):      # TTL expiry pops it
                        continue
                    self.health.invalidate(ip)
                    try:
                        ok = await self.health.healthy(pool, ip)
                    except Exception:  # noqa: BLE001 — probe error = not ready
                        ok = False
                    if ok:
                        self.fence_ready(ip)
                if self._warming:
                    await asyncio.sleep(self.warming_probe_s)
        finally:
            self._prober_task = None

    def fence_ready(self, ip: str) -> None:
        """Clear the fence (a fresh probe succeeded): the replica now
        takes normal traffic on the cached-health path."""
        if self._warming.pop(ip, None) is not None:
            telemetry.cold_start_metrics()["fence"].inc(result="admitted")

    def _is_warming(self, ip: str) -> bool:
        t = self._warming.get(ip)
        if t is None:
            return False
        if time.monotonic() - t > self.warming_ttl_s:
            # a boot that never came up: stop deprioritizing the ip (the
            # controller has its own replace-or-retry loop) and count it
            self._warming.pop(ip, None)
            telemetry.cold_start_metrics()["fence"].inc(result="expired")
            return False
        return True

    def _warming_last(self, order: List[str]) -> List[str]:
        if not self._warming:
            return order
        warm = [ip for ip in order if self._is_warming(ip)]
        if not warm:
            return order
        return [ip for ip in order if ip not in warm] + warm

    # -- canary --------------------------------------------------------------

    def set_canary(self, replica: str, fraction: float = 0.1) -> None:
        """Pin a slice of keyless traffic to ``replica`` for a rollout
        bake. The pre-swap service-time EWMA is snapshotted HERE — it is
        the regression baseline; measuring it after the swap would let a
        slow canary poison its own yardstick."""
        self._canary = {
            "replica": replica,
            "fraction": max(0.0, min(1.0, float(fraction))),
            "baseline_ewma_s": self._ewma_s,
            "started_at": time.monotonic(),
            "requests": 0,
            "errors": 0,
            "lat_ewma_s": None,
            "pick": itertools.count(),
        }

    def clear_canary(self) -> None:
        self._canary = None

    def canary_state(self) -> Optional[Dict[str, Any]]:
        c = self._canary
        if c is None:
            return None
        return {k: c[k] for k in ("replica", "fraction", "baseline_ewma_s",
                                  "requests", "errors", "lat_ewma_s")}

    def canary_verdict(self, min_requests: int = 20,
                       ttft_factor: float = 2.0,
                       err_threshold: float = 0.05) -> str:
        """``"none"`` (no canary), ``"warming"`` (not enough traffic yet),
        ``"regressed"`` (error rate past ``err_threshold`` or latency EWMA
        past ``ttft_factor`` × the pre-swap baseline), else ``"ok"``."""
        c = self._canary
        if c is None:
            return "none"
        if c["requests"] < max(1, min_requests):
            return "warming"
        if c["errors"] / c["requests"] >= err_threshold:
            return "regressed"
        base, lat = c["baseline_ewma_s"], c["lat_ewma_s"]
        if base and lat and lat > base * ttft_factor:
            return "regressed"
        return "ok"

    def _canary_order(self, order: List[str]) -> List[str]:
        """Apply the canary pin to a selection order: the configured slice
        of traffic gets the canary FIRST; everything else gets it LAST
        (failover of last resort only) — non-canary traffic must not
        bake on unpromoted weights."""
        c = self._canary
        if c is None or c["replica"] not in order:
            return order
        rest = [ip for ip in order if ip != c["replica"]]
        frac = c["fraction"]
        every = int(round(1.0 / frac)) if frac > 0 else 0
        if every and next(c["pick"]) % every == 0:
            return [c["replica"]] + rest
        return rest + [c["replica"]]

    def _canary_record(self, target: str, started: float,
                       ok: bool) -> None:
        c = self._canary
        if c is None or target != c["replica"]:
            return
        c["requests"] += 1
        if not ok:
            c["errors"] += 1
            _CANARY_REQS.inc(result="error")
            return
        dt = time.monotonic() - started
        c["lat_ewma_s"] = (dt if c["lat_ewma_s"] is None
                           else 0.3 * dt + 0.7 * c["lat_ewma_s"])
        _CANARY_REQS.inc(result="ok")

    # -- admission ----------------------------------------------------------

    def estimated_wait_s(self) -> float:
        """Expected queue wait for a request arriving NOW: queued requests
        drain at (capacity / service-time) per second. 0 until a service
        time has been measured."""
        if self._ewma_s is None or not self._waiters:
            return 0.0
        return len(self._waiters) * self._ewma_s / max(self._capacity, 1)

    def _shed(self, reason: str, tier: str,
              retry_after: Optional[float] = None,
              deadline: Optional[Deadline] = None) -> None:
        m = telemetry.serve_metrics()
        m["shed"].inc(reason=reason, tier=tier)
        telemetry.add_event("router.shed", reason=reason, tier=tier)
        if reason == "deadline_expired":
            raise DeadlineExceededError(
                "request arrived past its deadline; shed at the front door "
                "before prefill", deadline=deadline.at if deadline else None)
        depth = len(self._waiters)
        raise AdmissionShedError(
            f"shed at admission ({reason}): queue depth {depth}, "
            f"estimated wait {self.estimated_wait_s():.3f}s",
            reason=reason, tier=tier, queue_depth=depth,
            retry_after=retry_after)

    def _check_deadline(self, deadline: Optional[Deadline],
                        tier: str) -> None:
        if deadline is None:
            return
        if deadline.expired():
            self._shed("deadline_expired", tier, deadline=deadline)
        est = self.estimated_wait_s()
        if est > 0 and deadline.remaining() < est:
            # doomed: it would expire in the queue — refuse now, while the
            # client's retry budget can still go somewhere useful
            self._shed("doomed", tier, retry_after=est, deadline=deadline)

    async def _admit(self, priority: int, tier: str,
                     deadline: Optional[Deadline]) -> None:
        """Block until a fleet slot frees (priority order), shedding on
        overflow. Runs on the server's event loop — single-threaded, so
        the counters need no lock."""
        m = telemetry.serve_metrics()
        if self._active < self._capacity and not self._waiters:
            self._active += 1
            m["admitted"].inc(tier=tier)
            return
        if len(self._waiters) >= self.queue_max:
            # queue full: the lowest band sheds first. If that's the
            # arrival, shed it; otherwise evict the queue's worst waiter
            # to make room for the better-tiered arrival.
            worst = max(self._waiters)
            if (-priority, next(self._seq)) >= worst.sort_key():
                self._shed("queue_full", tier,
                           retry_after=self.estimated_wait_s())
            self._waiters.remove(worst)
            heapq.heapify(self._waiters)
            m["queue_depth"].set(len(self._waiters))
            if not worst.future.done():
                worst.future.set_exception(AdmissionShedError(
                    "shed from the admission queue by a higher-priority "
                    "arrival", reason="queue_full", tier=worst.tier,
                    queue_depth=len(self._waiters),
                    retry_after=self.estimated_wait_s()))
                m["shed"].inc(reason="queue_full", tier=worst.tier)
        waiter = _Waiter(priority, tier, next(self._seq),
                         asyncio.get_running_loop().create_future())
        heapq.heappush(self._waiters, waiter)
        m["queue_depth"].set(len(self._waiters))
        timeout = deadline.remaining() if deadline is not None else None
        try:
            with telemetry.stage("queue_wait", source="router"):
                await asyncio.wait_for(waiter.future, timeout=timeout)
        except asyncio.TimeoutError:
            self._forget(waiter)
            self._shed("deadline_expired", tier, deadline=deadline)
        except asyncio.CancelledError:
            # the handler task was cancelled (deadline middleware, client
            # gone). If the wake-up raced the cancellation and the slot
            # was already granted, hand it straight to the next waiter —
            # otherwise it leaks and capacity shrinks forever.
            granted = (waiter.future.done()
                       and not waiter.future.cancelled()
                       and waiter.future.exception() is None)
            self._forget(waiter)
            if granted:
                self._active -= 1
                self._wake()
            raise
        # woken by _release: the slot is already accounted to us
        m["admitted"].inc(tier=tier)

    def _forget(self, waiter: _Waiter) -> None:
        if waiter in self._waiters:
            self._waiters.remove(waiter)
            heapq.heapify(self._waiters)
            telemetry.serve_metrics()["queue_depth"].set(len(self._waiters))

    def _release(self, started_at: float) -> None:
        dt = time.monotonic() - started_at
        self._ewma_s = (dt if self._ewma_s is None
                        else 0.2 * dt + 0.8 * self._ewma_s)
        self._active -= 1
        self._wake()

    def _wake(self) -> None:
        while self._waiters and self._active < self._capacity:
            waiter = heapq.heappop(self._waiters)
            if waiter.future.done():
                continue            # already shed/cancelled
            self._active += 1
            waiter.future.set_result(None)
        telemetry.serve_metrics()["queue_depth"].set(len(self._waiters))

    # -- selection ----------------------------------------------------------

    def _free(self, ip: str) -> int:
        return self.slots - self._inflight.get(ip, 0)

    def _pack_order(self, ips: List[str]) -> List[str]:
        """Continuous-batching order for keyless traffic: partially-full
        replicas first (fullest first — join an existing decode batch),
        then idle replicas round-robin, then saturated ones (failover of
        last resort). Sequential traffic on an idle fleet degenerates to
        exactly the old round-robin."""
        start = next(self._rr) % max(len(ips), 1)
        rotated = ips[start:] + ips[:start]
        partial = sorted((ip for ip in rotated
                          if 0 < self._inflight.get(ip, 0) < self.slots),
                         key=lambda ip: -self._inflight.get(ip, 0))
        idle = [ip for ip in rotated if self._inflight.get(ip, 0) == 0]
        full = [ip for ip in rotated
                if self._inflight.get(ip, 0) >= self.slots]
        return partial + idle + full

    def _hash_order(self, key: str, ips: List[str]) -> List[str]:
        """Deterministic cold placement: every pod's router hashes the
        session to the same home replica, so residency accretes in one
        place. Reuses the store ring's membership-order-independent
        consistent hash, rebuilt only when membership changes."""
        tkey = tuple(ips)
        if self._ring[0] != tkey:
            from ..data_store.ring import HashRing
            self._ring = (tkey, HashRing(list(tkey)))
        return self._ring[1].walk(key)

    def select(self, ips: List[str], key: Optional[str]
               ) -> Tuple[List[str], str]:
        """(ordered candidate list, affinity outcome). ``hit`` = resident
        replica first; ``miss`` = consistent-hash placement (cold or the
        resident replica is gone/full); ``none`` = keyless packing."""
        if not key:
            return self._pack_order(ips), "none"
        resident = self.sessions.lookup(key)
        if resident in ips and self._free(resident) > 0:
            rest = [ip for ip in self._pack_order(ips) if ip != resident]
            return [resident] + rest, "hit"
        order = self._hash_order(key, ips)
        # a full home replica falls through to the next ring member rather
        # than queueing behind its own batch
        ready = [ip for ip in order if self._free(ip) > 0]
        starved = [ip for ip in order if self._free(ip) <= 0]
        return ready + starved, "miss"

    # -- dispatch -----------------------------------------------------------

    async def dispatch(self, *, pool, ips: List[str], my_ip: str,
                       method: Optional[str], args: list, kwargs: dict,
                       headers: Optional[Dict[str, str]],
                       timeout: Optional[float],
                       local_call: Callable[..., Awaitable[Any]]) -> Any:
        """The whole front-door path for one call: admission (deadline
        check + bounded priority queue) → affinity/pack selection →
        health-cached forwarding with transport-only failover → slot
        release. Raises typed errors for shed requests; application
        exceptions from the chosen replica propagate un-retried (never
        re-run a possibly non-idempotent call elsewhere)."""
        headers = dict(headers or {})
        deadline = Deadline.from_header(headers.get(DEADLINE_HEADER))
        priority, tier = request_priority(headers)
        key = affinity_key(headers, kwargs)
        m = telemetry.serve_metrics()
        self._capacity = max(len(ips), 1) * self.slots
        attrs = {"tier": tier}
        if key:
            attrs["session"] = key
        with telemetry.span("router.route", **attrs) as sp:
            self._check_deadline(deadline, tier)
            await self._admit(priority, tier, deadline)
            started = time.monotonic()
            try:
                order, affinity = self.select(ips, key)
                order = self._warming_last(self._canary_order(order))
                m["affinity"].inc(result=affinity)
                sp.set_attr("affinity", affinity)
                last_err: Optional[BaseException] = None
                for target in order:
                    if target != my_ip:
                        if self._is_warming(target):
                            # fence: a warming replica takes its FIRST
                            # request only after a fresh (uncached) probe
                            self.health.invalidate(target)
                            if not await self.health.healthy(pool, target):
                                telemetry.cold_start_metrics()["fence"].inc(
                                    result="blocked")
                                continue
                            self.fence_ready(target)
                        elif not await self.health.healthy(pool, target):
                            continue
                    depth = self._inflight.get(target, 0) + 1
                    self._inflight[target] = depth
                    m["batch_depth"].observe(float(depth))
                    sp.set_attr("replica", target)
                    sp.set_attr("batch_depth", depth)
                    attempt_started = time.monotonic()
                    try:
                        if target == my_ip:
                            result = await local_call(method, args, kwargs,
                                                      timeout)
                        else:
                            result = await pool.call_worker(
                                target, self.fn_name, method,
                                {"args": args, "kwargs": kwargs}, headers,
                                timeout, subtree=[])
                    except WorkerCallError as e:
                        # transport failure: this replica is dead to us —
                        # down-cache it, forget its sessions, try the next.
                        # Application exceptions propagate (never re-run a
                        # possibly non-idempotent call on another pod).
                        self.health.mark_down(target)
                        self.sessions.evict_replica(target)
                        telemetry.add_event("router.failover",
                                            replica=target)
                        self._canary_record(target, attempt_started,
                                            ok=False)
                        last_err = e
                        continue
                    except Exception:
                        # application failure: propagates untried-elsewhere,
                        # but it still counts against a baking canary —
                        # injected chaos errors on the canary are exactly
                        # the regression signal auto-rollback fires on
                        self._canary_record(target, attempt_started,
                                            ok=False)
                        raise
                    finally:
                        self._inflight[target] = \
                            max(0, self._inflight.get(target, 1) - 1)
                    self._canary_record(target, attempt_started, ok=True)
                    if key:
                        self.sessions.touch(key, target)
                    return result
                if last_err is not None:
                    raise last_err
                # no healthy peer at all: serve locally rather than fail
                sp.set_attr("replica", "local-fallback")
                return await local_call(method, args, kwargs, timeout)
            finally:
                self._release(started)

    # -- introspection ------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Router state for ``/health`` and ``kt serve status``."""
        m = telemetry.serve_metrics()
        hits = m["affinity"].value(result="hit")
        misses = m["affinity"].value(result="miss")
        return {
            "slots_per_replica": self.slots,
            "capacity": self._capacity,
            "active": self._active,
            "queued": len(self._waiters),
            "queue_max": self.queue_max,
            "sessions": len(self.sessions),
            "ewma_service_s": self._ewma_s,
            "estimated_wait_s": round(self.estimated_wait_s(), 4),
            "inflight": {ip: n for ip, n in self._inflight.items() if n},
            "affinity_hit_rate": (hits / (hits + misses)
                                  if hits + misses else 0.0),
            "warming": sorted(self._warming),
            "canary": self.canary_state(),
        }
