"""Zero-copy array envelopes over per-worker shared-memory rings (ISSUE 10).

The server⇄rank-worker hop moved every large array through an
``mp.Queue``: pickle the array (copy 1), chunk it through the queue's OS
pipe (copies 2–3, 64 KiB at a time behind the feeder thread), unpickle on
the far side (copy 4). For the multi-MB tensors the serving and
checkpoint paths move on every call, that pipe tax dominated dispatch.

This module replaces it with one ``multiprocessing.shared_memory``
**ring per direction per worker**: the sender memcpys the array's bytes
into the ring once, the queue carries only a small header — the
*envelope*: ``{pos, len, dtype, shape, hash}`` — and the receiver decodes
straight out of the mapped buffer into a freshly allocated array (one
copy, then ``device_put`` by user code). A blake2b check makes the path
content-verified: every control-plane-sized envelope (≤1 MiB) is hashed
end to end, bulk tensors on a deterministic sample (:func:`verify_policy`,
``KT_SHM_VERIFY``; the queue path this replaces never checksummed at
all). A failed check raises a typed
:class:`~..exceptions.DataCorruptionError` and the call retries once over
the classic queue path rather than feeding garbage to ``device_put``.

Ring protocol (single-producer / single-consumer by construction — the
server's event loop writes requests, the worker loop reads them in queue
order; symmetric for responses):

- byte 0–8:  ``head_pos`` — monotonic u64, writer-owned
- byte 8–16: ``tail_pos`` — monotonic u64, reader-owned
- byte 64–:  data. Blocks never wrap: an allocation that would straddle
  the end skips to the next lap (the envelope's ``pos`` is monotonic, so
  the reader's ``free`` jumps the gap implicitly).

Fallbacks keep the path *optional end to end*: ``KT_SHM_THRESHOLD``
unset/0 disables it byte-identically (no segments are even created);
a full ring leaves the array inline on the queue (counted in
``kt_shm_ring_fallbacks_total{reason="ring_full"}``); a dead rank's
segments are unlinked by the watchdog/restart path so ``/dev/shm`` never
leaks across worker generations.

This is the ONLY module allowed to touch ``SharedMemory`` directly
(``scripts/check_resilience.py`` lint #9): segment naming, the attach-side
resource-tracker workaround, and cleanup discipline all live here.
"""

from __future__ import annotations

import hashlib
import os
import struct
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from ..exceptions import DataCorruptionError

SHM_THRESHOLD_ENV = "KT_SHM_THRESHOLD"
SHM_RING_BYTES_ENV = "KT_SHM_RING_BYTES"
DEFAULT_RING_BYTES = 64 << 20

# envelope sentinel — mirrors serialization.py's typed-leaf convention
SHM_KEY = "__kt_shm__"

_ENVELOPES = telemetry.counter(
    "kt_shm_ring_envelopes_total",
    "Arrays moved through a shared-memory ring envelope, by direction",
    labels=("dir",))
_SHM_BYTES = telemetry.counter(
    "kt_shm_ring_bytes_total",
    "Array bytes moved through shared-memory rings, by direction",
    labels=("dir",))
_FALLBACKS = telemetry.counter(
    "kt_shm_ring_fallbacks_total",
    "Envelope-path fallbacks to the queue path, by reason",
    labels=("reason",))


def shm_threshold() -> int:
    """Minimum array byte size that rides the ring. Unset or 0 disables
    the envelope path entirely (byte-identical pre-ISSUE-10 behavior) —
    the path is opt-in per deployment because it spends ``/dev/shm``,
    which is a sized resource in pods (docs/operations.md)."""
    raw = os.environ.get(SHM_THRESHOLD_ENV)
    if raw is None:
        try:
            from ..config import config
            return max(0, int(config().get("shm_threshold", 0) or 0))
        except Exception:
            return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


def ring_bytes() -> int:
    raw = os.environ.get(SHM_RING_BYTES_ENV)
    if raw is None:
        try:
            from ..config import config
            return max(1 << 16,
                       int(config().get("shm_ring_bytes",
                                        DEFAULT_RING_BYTES)))
        except Exception:
            return DEFAULT_RING_BYTES
    try:
        return max(1 << 16, int(raw))
    except ValueError:
        return DEFAULT_RING_BYTES


def enabled() -> bool:
    return shm_threshold() > 0


def make_name(tag: str) -> str:
    """Unique, identifiable segment name: ``kt-shm-<pid>-<tag>-<uid>``.
    The pid + the fixed prefix make leak audits greppable in /dev/shm."""
    return f"kt-shm-{os.getpid()}-{tag}-{uuid.uuid4().hex[:8]}"


class ShmRing:
    """One direction of the envelope path: an SPSC byte ring in a shared
    segment. The writer calls :meth:`try_put`, the reader :meth:`view` +
    :meth:`free` in envelope order. Head/tail are *monotonic* u64
    positions (never wrapped), so torn reads of the far side's cursor can
    only under-estimate free space — a late allocation failure, never a
    corrupted one."""

    DATA_OFF = 64          # cursor block, padded to a cache line

    def __init__(self, name: str, size: int = 0, create: bool = False):
        from multiprocessing import shared_memory

        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=size + self.DATA_OFF)
            self.shm.buf[:16] = b"\x00" * 16
            self._owner = True
        else:
            # 3.10 registers every attach with the resource tracker; that
            # is fine here because attachers are always spawned by the
            # ring's creator and SHARE its tracker process, so the
            # attach-side register is an idempotent set-add and the one
            # deliberate unlink (ProcessWorker.cleanup_shm) unregisters it
            # exactly once. (An explicit attach-side unregister would
            # remove the owner's entry from the shared tracker and leak
            # the segment on a parent crash.)
            self.shm = shared_memory.SharedMemory(name=name)
            self._owner = False
        self.name = name
        self.data_size = self.shm.size - self.DATA_OFF
        # a cached uint8 view of the data region: numpy's block copy runs
        # measurably faster than memoryview slice assignment on multi-MB
        # blocks, and this IS the hot path. Released before close() (an
        # exported buffer would make the mmap refuse to unmap).
        import numpy as np
        self._np = np.frombuffer(self.shm.buf, dtype=np.uint8)
        self._env_seq = 0              # writer-side envelope counter
        # pre-fault the whole mapping once at setup so no call ever pays
        # page-fault latency mid-copy: the creator writes (allocates the
        # tmpfs pages), an attacher reads (populates its own page tables
        # without clobbering data the creator may already have written)
        if create:
            self._np[self.DATA_OFF:] = 0
        else:
            int(self._np[:: 4096].sum())

    # -- cursors (8-byte aligned single-writer stores) ----------------------

    @property
    def _head(self) -> int:
        return struct.unpack_from("<Q", self.shm.buf, 0)[0]

    @_head.setter
    def _head(self, v: int) -> None:
        struct.pack_into("<Q", self.shm.buf, 0, v)

    @property
    def _tail(self) -> int:
        return struct.unpack_from("<Q", self.shm.buf, 8)[0]

    @_tail.setter
    def _tail(self, v: int) -> None:
        struct.pack_into("<Q", self.shm.buf, 8, v)

    # -- writer side --------------------------------------------------------

    def try_put(self, buf) -> Optional[int]:
        """Copy ``buf`` into the ring; returns its monotonic position, or
        None when the unconsumed window cannot fit it (caller falls back
        to the inline queue path)."""
        n = len(buf)
        cap = self.data_size
        if n == 0 or n > cap:
            return None
        start = self._head
        rem = cap - (start % cap)
        if rem < n:                      # never wrap a block
            start += rem
        if start + n - self._tail > cap:
            return None
        off = self.DATA_OFF + (start % cap)
        self._np[off:off + n] = buf
        self._head = start + n
        return start

    # -- reader side --------------------------------------------------------

    def view(self, pos: int, n: int):
        """uint8 array view (no copy) of an envelope's bytes."""
        off = self.DATA_OFF + (pos % self.data_size)
        return self._np[off:off + n]

    def free(self, pos: int, n: int) -> None:
        """Release everything up to and including this envelope. Envelopes
        are freed in allocation order (queue order == walk order), so the
        tail only ever moves forward."""
        self._tail = pos + n

    def used(self) -> int:
        return max(0, self._head - self._tail)

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._np = None                # release the exported buffer first
        try:
            self.shm.close()
        except Exception:  # noqa: BLE001 — idempotent teardown
            pass

    def __del__(self):
        # explicit ordering for the GC path: the numpy export must die
        # before SharedMemory.__del__ tries to unmap, or a ring dropped
        # without close() prints a BufferError at interpreter exit
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except Exception:  # noqa: BLE001 — already gone is fine
            pass


# ---------------------------------------------------------------------------
# Envelope encode/decode over call payloads
# ---------------------------------------------------------------------------


def _u8_buffer(arr):
    """Zero-copy uint8 view of an array's bytes (the ``_leaf_buffer``
    idiom from the data plane: extension dtypes refuse direct buffer
    export, a uint8 reinterpret always works)."""
    import numpy as np

    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    try:
        return arr.reshape(-1).view(np.uint8)
    except (ValueError, TypeError):
        return np.frombuffer(arr.tobytes(), dtype=np.uint8)


def _is_np_array(obj: Any) -> bool:
    if not type(obj).__module__.startswith("numpy"):
        return False
    import numpy as np
    return isinstance(obj, np.ndarray)


def verify_policy() -> int:
    """Blake2b coverage: verify every N-th envelope per ring (plus always
    the first, and always any envelope written under an armed chaos
    ``shm-corrupt`` token, so the corruption drill stays deterministic at
    any policy).

    Hashing is the envelope path's only per-byte cost besides the two
    memcpys, and blake2b runs at ~1 GB/s/core — full coverage of every
    multi-MB tensor would hand back most of the win this path exists for.
    The risk the check actually guards is a *systematically* corrupting
    ring (a lifecycle bug reusing a live slot), which deterministic
    sampling catches within a bounded envelope budget; note the mp.Queue
    path this replaces never checksummed at all.

    ``KT_SHM_VERIFY``: ``all``/``1`` = verify every envelope, ``off``/
    ``0`` = never, integer N = verify every N-th (default 8).
    """
    raw = (os.environ.get("KT_SHM_VERIFY") or "").strip().lower()
    if raw in ("all", "1"):
        return 1
    if raw in ("off", "0"):
        return 0
    try:
        return max(1, int(raw)) if raw else 8
    except ValueError:
        return 8


# chaos (ISSUE 10 satellite): the ``shm-corrupt`` verb flips one byte of
# an envelope's ring bytes AFTER the write and BEFORE the header is
# queued — the decode-side hash check must catch it and the call must
# fall back to the queue path. Consumed-once schedule, like the rank
# verbs; lazily parsed so plain deployments never touch the chaos parser.
_corrupt_budget: Optional[int] = None


def _consume_corrupt_token() -> bool:
    global _corrupt_budget
    if _corrupt_budget is None:
        from ..chaos import shm_corrupt_plan
        _corrupt_budget = shm_corrupt_plan()
    if _corrupt_budget > 0:
        _corrupt_budget -= 1
        return True
    return False


def reset_chaos() -> None:
    """Re-arm the shm-corrupt schedule from the current env (tests)."""
    global _corrupt_budget
    _corrupt_budget = None


def encode_item_fields(item: Dict, ring: Optional[ShmRing],
                       fields: Tuple[str, ...], threshold: int,
                       direction: str) -> int:
    """Move qualifying arrays under ``item[field]`` into ``ring``,
    replacing them with envelope headers in place. Returns the envelope
    count (0 = nothing qualified; the item is untouched and byte-identical
    to the pre-envelope wire shape). ``item['no_shm']`` — set by the
    corruption-fallback retry — short-circuits to 0."""
    if ring is None or threshold <= 0 or item.get("no_shm"):
        return 0
    count = 0

    def _has_candidate(o: Any) -> bool:
        if _is_np_array(o):
            return o.nbytes >= threshold
        if isinstance(o, dict):
            return any(_has_candidate(v) for v in o.values())
        if isinstance(o, (list, tuple)):
            return any(_has_candidate(v) for v in o)
        return False

    sample_every = verify_policy()

    def _envelope(arr) -> Any:
        nonlocal count
        u8 = _u8_buffer(arr)
        pos = ring.try_put(u8)
        if pos is None:
            _FALLBACKS.inc(reason="ring_full")
            return arr                   # stays inline on the queue
        corrupting = _consume_corrupt_token()
        seq = ring._env_seq
        ring._env_seq = seq + 1
        verify = corrupting or (sample_every > 0
                                and seq % sample_every == 0)
        spec = {"pos": pos, "len": len(u8), "dtype": str(arr.dtype),
                "shape": list(arr.shape)}
        if verify:
            spec["hash"] = hashlib.blake2b(u8, digest_size=16).hexdigest()
        if corrupting:
            off = ring.DATA_OFF + (pos % ring.data_size)
            ring.shm.buf[off] ^= 0xFF
            print(f"[kt] chaos: shm-corrupt flipped a byte in {ring.name} "
                  f"@pos={pos}")
        count += 1
        _ENVELOPES.inc(dir=direction)
        _SHM_BYTES.inc(len(u8), dir=direction)
        return {SHM_KEY: spec}

    def _rebuild(o: Any) -> Any:
        if _is_np_array(o) and o.nbytes >= threshold:
            return _envelope(o)
        if isinstance(o, dict):
            return {k: _rebuild(v) for k, v in o.items()}
        if isinstance(o, tuple):
            vals = [_rebuild(v) for v in o]
            return type(o)(*vals) if hasattr(o, "_fields") else tuple(vals)
        if isinstance(o, list):
            return [_rebuild(v) for v in o]
        return o

    for f in fields:
        sub = item.get(f)
        if sub is not None and _has_candidate(sub):
            item[f] = _rebuild(sub)
    return count


def decode_item_fields(item: Dict, ring: Optional[ShmRing],
                       fields: Tuple[str, ...], direction: str) -> int:
    """Resolve every envelope under ``item[field]`` back into arrays,
    verifying each blake2b and freeing ring slots as it goes. ALL
    envelopes are freed even when one fails verification (a stuck tail
    would wedge the ring for every later call); the first failure then
    surfaces as a typed :class:`DataCorruptionError` with
    ``source="shm"`` — the signal the pool's retry-without-shm fallback
    keys on. Returns the envelope count."""
    count = 0
    errors: List[DataCorruptionError] = []

    def _open(spec: Dict) -> Any:
        nonlocal count
        count += 1
        pos, n = int(spec["pos"]), int(spec["len"])
        _ENVELOPES.inc(dir=direction)
        _SHM_BYTES.inc(n, dir=direction)
        try:
            src = ring.view(pos, n)
            want = spec.get("hash")
            if want is not None:
                actual = hashlib.blake2b(src, digest_size=16).hexdigest()
                if actual != want:
                    errors.append(DataCorruptionError(
                        f"shm envelope hash mismatch ({n}B "
                        f"{spec['dtype']}{spec['shape']})",
                        key=direction, expected=want, actual=actual,
                        source="shm"))
                    return None
            import numpy as np
            from ..serialization import _np_dtype
            arr = np.empty(spec["shape"], dtype=_np_dtype(spec["dtype"]))
            dst = arr.reshape(-1).view(np.uint8)
            if dst.nbytes != n:
                raise ValueError(
                    f"envelope byte-size mismatch: {n}B for "
                    f"{spec['dtype']}{spec['shape']}")
            dst[:] = src
            return arr
        except (ValueError, TypeError, IndexError) as e:
            # ring unmapped under us (worker torn down mid-drain) or a
            # malformed header — same verdict: the bytes are not usable
            errors.append(DataCorruptionError(
                f"shm envelope unreadable: {e}", key=direction,
                expected=spec.get("hash"), actual=None, source="shm"))
            return None
        finally:
            try:
                ring.free(pos, n)
            except (ValueError, TypeError):
                pass

    def _walk(o: Any) -> Any:
        if isinstance(o, dict):
            if SHM_KEY in o and len(o) == 1:
                return _open(o[SHM_KEY])
            return {k: _walk(v) for k, v in o.items()}
        if isinstance(o, tuple):
            vals = [_walk(v) for v in o]
            return type(o)(*vals) if hasattr(o, "_fields") else tuple(vals)
        if isinstance(o, list):
            return [_walk(v) for v in o]
        return o

    if ring is None:
        errors.append(DataCorruptionError(
            "shm envelope received but no ring is attached",
            key=direction, source="shm"))
        for f in fields:
            if item.get(f) is not None:
                item[f] = None
    else:
        for f in fields:
            sub = item.get(f)
            if sub is not None:
                item[f] = _walk(sub)
    if errors:
        _FALLBACKS.inc(reason="corrupt")
        raise errors[0]
    return count


def has_envelopes(item: Dict) -> bool:
    return bool(item.get("_kt_shm"))


# ---------------------------------------------------------------------------
# Weight segments (ISSUE 16): template-fork weight residency
# ---------------------------------------------------------------------------
#
# The pre-warmed template process stages the model's weights into ONE
# shared segment; every forked replica attaches and materializes its
# params with one memcpy per leaf and zero pickle. Same module as the
# rings on purpose: segment naming (make_name → leak audits), the
# attach-side resource-tracker discipline, and unlink ownership are one
# policy, and lint #9 keeps every SharedMemory touch in this file.

_TUPLE_KEY = "__kt_tuple__"


def _flatten_weights(obj: Any, path: str, leaves: List) -> Any:
    """JSON-able skeleton of the params tree with leaves replaced by
    their index into ``leaves`` (appended in walk order). Tuples are
    tagged so the attach side can rebuild them exactly."""
    if _is_np_array(obj) or type(obj).__module__.startswith("jax"):
        import numpy as np
        arr = np.asarray(obj)
        leaves.append((path, arr))
        return len(leaves) - 1
    if isinstance(obj, dict):
        return {str(k): _flatten_weights(v, f"{path}/{k}", leaves)
                for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {_TUPLE_KEY: [_flatten_weights(v, f"{path}/{i}", leaves)
                             for i, v in enumerate(obj)]}
    if isinstance(obj, list):
        return [_flatten_weights(v, f"{path}/{i}", leaves)
                for i, v in enumerate(obj)]
    raise TypeError(
        f"weight segment: unsupported leaf {type(obj).__name__} at {path!r}")


def _unflatten_weights(skel: Any, arrays: List) -> Any:
    if isinstance(skel, int):
        return arrays[skel]
    if isinstance(skel, dict):
        if _TUPLE_KEY in skel and len(skel) == 1:
            return tuple(_unflatten_weights(v, arrays)
                         for v in skel[_TUPLE_KEY])
        return {k: _unflatten_weights(v, arrays) for k, v in skel.items()}
    if isinstance(skel, list):
        return [_unflatten_weights(v, arrays) for v in skel]
    raise TypeError(f"weight manifest: bad skeleton node {type(skel)}")


class WeightSegment:
    """A created-or-attached weight segment. The CREATOR (the template)
    owns the lifetime: it holds the mapping for its whole life and
    unlinks on close; attachers (forked replicas) close their mapping
    after materializing params and never unlink. ``unlink_by_name``
    covers the crash path — a supervisor that outlives a SIGKILLed
    template removes the segment by its manifest name, so kills leak
    nothing."""

    def __init__(self, shm, manifest: Dict, owner: bool):
        self.shm = shm
        self.manifest = manifest
        self.name = manifest["name"]
        self._owner = owner

    def close(self, unlink: Optional[bool] = None) -> None:
        do_unlink = self._owner if unlink is None else unlink
        try:
            self.shm.close()
        except Exception:  # noqa: BLE001 — idempotent teardown
            pass
        if do_unlink:
            try:
                self.shm.unlink()
            except Exception:  # noqa: BLE001 — already gone is fine
                pass

    def __del__(self):
        try:
            self.shm.close()
        except Exception:  # noqa: BLE001
            pass


def create_weight_segment(params: Any, tag: str = "weights") -> WeightSegment:
    """Stage a params pytree (numpy/jax leaves under dict/list/tuple
    containers) into one shared segment. Returns the owning
    :class:`WeightSegment`; its ``manifest`` (JSON-able: segment name,
    skeleton, per-leaf dtype/shape/offset, full-segment blake2b) is the
    only thing a forked replica needs to attach."""
    from multiprocessing import shared_memory
    import numpy as np

    leaves: List = []
    skel = _flatten_weights(params, "", leaves)
    metas, offset = [], 0
    for path, arr in leaves:
        nbytes = int(arr.nbytes)
        metas.append({"path": path.lstrip("/"), "dtype": str(arr.dtype),
                      "shape": list(arr.shape), "offset": offset,
                      "nbytes": nbytes})
        offset += nbytes
    total = max(offset, 1)
    name = make_name(tag)
    shm = shared_memory.SharedMemory(name=name, create=True, size=total)
    buf = np.frombuffer(shm.buf, dtype=np.uint8)
    h = hashlib.blake2b(digest_size=16)
    for meta, (path, arr) in zip(metas, leaves):
        u8 = _u8_buffer(arr)
        dst = buf[meta["offset"]:meta["offset"] + meta["nbytes"]]
        dst[:] = u8
        h.update(u8)
    del buf                       # release the export before any close()
    manifest = {"name": name, "total_bytes": offset, "tree": skel,
                "leaves": metas, "blake2b": h.hexdigest()}
    return WeightSegment(shm, manifest, owner=True)


def attach_weight_segment(manifest: Dict, *, verify: bool = True) -> Any:
    """Materialize a params pytree from a weight segment: attach by
    name, optionally verify the full-segment blake2b (a corrupt segment
    raises the typed :class:`DataCorruptionError`, never silently wrong
    weights), then one memcpy per leaf into freshly allocated arrays.
    The mapping is closed before returning — the returned tree owns its
    memory, so the template can die without invalidating it."""
    from multiprocessing import shared_memory
    import numpy as np
    from ..serialization import _np_dtype

    # same tracker-sharing situation as ShmRing attach (see __init__):
    # replicas are forked/spawned by the template, so the attach-side
    # register is an idempotent set-add in the shared tracker
    shm = shared_memory.SharedMemory(name=manifest["name"])
    src = None
    try:
        src = np.frombuffer(shm.buf, dtype=np.uint8)
        total = int(manifest["total_bytes"])
        if verify:
            actual = hashlib.blake2b(src[:total],
                                     digest_size=16).hexdigest()
            if actual != manifest["blake2b"]:
                raise DataCorruptionError(
                    f"weight segment {manifest['name']} hash mismatch",
                    key=manifest["name"], expected=manifest["blake2b"],
                    actual=actual, source="shm")
        arrays = []
        for meta in manifest["leaves"]:
            arr = np.empty(meta["shape"], dtype=_np_dtype(meta["dtype"]))
            dst = arr.reshape(-1).view(np.uint8)
            dst[:] = src[meta["offset"]:meta["offset"] + meta["nbytes"]]
            arrays.append(arr)
        return _unflatten_weights(manifest["tree"], arrays)
    finally:
        src = None                # release the export before close()
        try:
            shm.close()
        except Exception:  # noqa: BLE001
            pass


def unlink_weight_segment(name: str) -> bool:
    """Best-effort unlink by name — the supervisor's crash-cleanup path
    for a SIGKILLed template (no destructor ran). Returns whether a
    segment was actually removed."""
    from multiprocessing import shared_memory

    try:
        shm = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        return False
    except Exception:  # noqa: BLE001 — unreadable == nothing to free
        return False
    try:
        shm.close()
    except Exception:  # noqa: BLE001
        pass
    try:
        shm.unlink()
        return True
    except Exception:  # noqa: BLE001
        return False
