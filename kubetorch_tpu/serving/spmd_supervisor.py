"""SPMD supervisor: the distributed execution engine.

Semantics mirror the reference engine (``serving/spmd/spmd_supervisor.py``):

- The pod that receives the client call becomes the **coordinator** of the
  fan-out. Rank identity (MASTER_ADDR / JAX coordinator) is fixed at setup
  from the sorted pod set — stable across calls regardless of which pod the
  client hit, which is what a compiled TPU mesh requires (deviation from the
  reference's per-call coordinator-first reordering, :133-141).
- Fan-out is flat below :data:`TREE_THRESHOLD` workers and a tree with
  :data:`TREE_FANOUT` children above it; a node's children coordinate their
  own subtrees recursively (:68-101).
- Worker selection: ``workers=[ips|indices] | "any" | "ready"`` (:220-261).
- Local ranks and remote subcalls execute in parallel with fast-fail: the
  first error (or a critical membership change) cancels everything (:366-545).
- Results aggregate as a flat per-rank list ordered by global rank (:547-570).

TPU-first deltas: the default framework is JAX (one proc/host), and a
``mesh`` in the distributed config flows to every rank as ``KT_MESH`` so user
code (or our train-step builder) can rebuild the identical device mesh.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from ..exceptions import (WorkerCallError, WorkerDiedError,
                          WorkerMembershipChanged)
from .discovery import my_pod_ip
from .execution_supervisor import DistributedSupervisor
from .remote_worker_pool import RemoteWorkerPool

TREE_THRESHOLD = 100
TREE_FANOUT = 50


def tree_children(index: int, total: int, fanout: int = TREE_FANOUT) -> List[int]:
    """Children of node ``index`` in the implicit fanout tree."""
    lo = index * fanout + 1
    return list(range(lo, min(lo + fanout, total)))


def subtree_indices(index: int, total: int, fanout: int = TREE_FANOUT) -> List[int]:
    """All indices in the subtree rooted at ``index`` (excluding the root)."""
    out: List[int] = []
    stack = tree_children(index, total, fanout)
    while stack:
        node = stack.pop()
        out.append(node)
        stack.extend(tree_children(node, total, fanout))
    return sorted(out)


class SPMDSupervisor(DistributedSupervisor):
    """Coordinator/worker SPMD execution over the pod set."""

    def __init__(self, *args, server_port: int = 32300, fn_name: str = "",
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.server_port = server_port
        self.fn_name = fn_name

    # -- worker selection (reference :220-261) --------------------------------

    async def _select_ips(self, workers: Union[None, str, Sequence]) -> List[str]:
        """Resolve the worker spec to the EXACT set of pods that execute, in
        the caller's order.

        Selection is precise — the coordinator runs user code only when it is
        in the selected set (actor dispatch to a single peer must not also
        run locally) — and order-preserving, so multicast results map back to
        the requested indices.
        """
        all_ips = self.pod_ips() or [my_pod_ip()]
        my_ip = my_pod_ip()
        if workers is None or workers == "all":
            selected = sorted(all_ips)
        elif workers == "any":
            selected = [my_ip]
        elif workers == "ready":
            pool = RemoteWorkerPool.shared(self.server_port)
            checks = await asyncio.gather(
                *[pool.check_health(ip) for ip in all_ips])
            selected = sorted(ip for ip, ok in zip(all_ips, checks)
                              if ok or ip == my_ip)
        elif isinstance(workers, (list, tuple)):
            if all(isinstance(w, int) for w in workers):
                ordered = sorted(all_ips)
                bad = [w for w in workers if not 0 <= w < len(ordered)]
                if bad:
                    raise ValueError(
                        f"Worker indices {bad} out of range for "
                        f"{len(ordered)} workers")
                selected = [ordered[w] for w in workers]
            else:
                selected = [w for w in workers if w in all_ips] or list(workers)
        else:
            raise ValueError(f"Invalid workers spec: {workers!r}")
        return selected

    # -- the call (reference :103, :366-545) ----------------------------------

    async def call(self, method: Optional[str], args: list, kwargs: dict,
                   timeout: Optional[float] = None,
                   workers: Union[None, str, Sequence] = None,
                   subtree: Optional[List[str]] = None,
                   sel_ips: Optional[List[str]] = None,
                   headers: Optional[Dict[str, str]] = None) -> List[Any]:
        async with self.restart_guard():    # each pod restarts its own ranks
            while True:
                try:
                    return await self._call_inner(method, args, kwargs,
                                                  timeout, workers, subtree,
                                                  sel_ips, headers)
                except (WorkerDiedError, WorkerMembershipChanged) as e:
                    # elastic resume (ISSUE 6), coordinator-only: interior
                    # tree nodes surface the typed error to THEIR
                    # coordinator, which owns the one retry — a nested
                    # retry would double-execute surviving subtrees
                    if subtree is not None or \
                            not await self.elastic_recover(e):
                        raise

    async def _call_inner(self, method, args, kwargs, timeout, workers,
                          subtree, sel_ips, headers) -> List[Any]:
        assert self.pool is not None, "supervisor not set up"
        # a pool whose restart budget is exhausted can never answer: fail the
        # whole fan-out here, typed, before any remote subcall is dispatched
        self.pool.raise_if_failed()
        my_ip = my_pod_ip()
        if subtree is not None:
            # we are an interior tree node: coordinate the given subtree;
            # sel_ips (the coordinator's ordered selection) flows down as-is
            ips = [my_ip] + list(subtree)
            sel = list(sel_ips) if sel_ips else None
        else:
            self.check_membership()
            ips = await self._select_ips(workers)
            # Subset (or reordered) selection: rank identity rebinds to the
            # selection for per-call-identity frameworks (reference assembles
            # env per call, :345-364). Full default set → no override.
            sel = None if ips == sorted(self.pod_ips() or [my_ip]) else list(ips)

        pool = RemoteWorkerPool.shared(self.server_port)
        body = {"args": args, "kwargs": kwargs}
        hdrs = headers or {}
        n = len(ips)
        local_subset = (sel, sel.index(my_ip)) if sel and my_ip in sel else None

        tree_order: Optional[List[str]] = None
        if n > TREE_THRESHOLD:
            # fanout tree: we execute iff selected; results come back in
            # tree-traversal order and are re-mapped to selection order
            # below when pod block sizes are uniform
            run_local = my_ip in ips
            others = [ip for ip in ips if ip != my_ip]
            tree = [my_ip, *others] if run_local else others
            targets = [(tree[c], [tree[d] for d in subtree_indices(c, len(tree))])
                       for c in tree_children(0, len(tree))] if run_local else \
                      [(others[0], others[1:])]
            tree_order = []
            if run_local:
                tree_order.append(my_ip)
            for ip, sub in targets:
                tree_order.extend([ip, *sub])
            tasks = []
            if run_local:
                tasks.append(asyncio.ensure_future(
                    self.pool.call_all(method, args, kwargs, timeout,
                                       subset=local_subset)))
            tasks += [asyncio.ensure_future(pool.call_worker(
                ip, self.fn_name, method, body, hdrs, timeout,
                subtree=sub or None, sel_ips=sel)) for ip, sub in targets]
        else:
            # flat fan-out preserves the caller's selection order exactly —
            # mesh.actors([1, 0]) must return [actor1, actor0]
            tasks = [
                asyncio.ensure_future(
                    self.pool.call_all(method, args, kwargs, timeout,
                                       subset=local_subset))
                if ip == my_ip else
                asyncio.ensure_future(pool.call_worker(
                    ip, self.fn_name, method, body, hdrs, timeout,
                    sel_ips=sel))
                for ip in ips
            ]

        try:
            results = await self._gather_fast_fail(tasks, timeout)
        except BaseException:
            for t in tasks:
                t.cancel()
            raise

        flat: List[Any] = []
        for branch in results:
            flat.extend(branch if isinstance(branch, list) else [branch])
        if tree_order is not None and len(tree_order) and \
                len(flat) % len(tree_order) == 0:
            # uniform ranks/pod: reorder per-pod blocks from tree-traversal
            # order back to the caller's selection order
            k = len(flat) // len(tree_order)
            blocks = {ip: flat[i * k:(i + 1) * k]
                      for i, ip in enumerate(tree_order)}
            flat = [r for ip in ips for r in blocks.get(ip, [])]
        return flat

    async def _gather_fast_fail(self, tasks: List[asyncio.Task],
                                timeout: Optional[float]) -> List[Any]:
        """Wait for all tasks; first exception (or critical membership change,
        checked every second) cancels the rest (reference :457-545)."""
        pending = set(tasks)
        while pending:
            done, pending = await asyncio.wait(
                pending, timeout=1.0, return_when=asyncio.FIRST_EXCEPTION)
            for t in done:
                if t.exception() is not None:
                    raise t.exception()
            event = self.pop_membership_event()
            if event is not None and event.is_critical:
                raise event
        return [t.result() for t in tasks]
