"""distribution_type → supervisor class (reference supervisor_factory.py:58)."""

from __future__ import annotations

from typing import Dict, Optional, Type

from ..parallel.mesh import DistributedConfig
from ..resources.pointers import Pointers
from .execution_supervisor import ExecutionSupervisor
from .spmd_supervisor import SPMDSupervisor


def supervisor_for(config: Optional[DistributedConfig], pointers: Optional[Pointers],
                   init_args: Optional[Dict], service_name: str,
                   namespace: str, server_port: int = 32300,
                   fn_name: str = "") -> ExecutionSupervisor:
    dist_type = (config.distribution_type if config else "local").lower()
    if dist_type in ("local", "none") or config is None or config.workers <= 1 and dist_type == "local":
        return ExecutionSupervisor(pointers, init_args, config, service_name, namespace)
    if dist_type in ("jax", "pytorch", "torch", "tensorflow", "tf", "spmd"):
        return SPMDSupervisor(pointers, init_args, config, service_name,
                              namespace, server_port=server_port, fn_name=fn_name)
    if dist_type == "load_balanced":
        from .load_balanced_supervisor import LoadBalancedSupervisor
        return LoadBalancedSupervisor(pointers, init_args, config, service_name,
                                      namespace, server_port=server_port,
                                      fn_name=fn_name)
    if dist_type == "ray":
        from .ray_supervisor import RaySupervisor
        return RaySupervisor(pointers, init_args, config, service_name, namespace)
    raise ValueError(f"Unknown distribution type: {dist_type!r}")
