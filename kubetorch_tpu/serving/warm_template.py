"""Pre-warmed template fork: replica boot without import or pickle (ISSUE 16).

A cold serving replica pays four bills serially: python import (~1–3s),
weight load (pickle/npz decode, multi-GB at scale), XLA compile (tens of
seconds cold), first token. This module collapses the first two to ~0
and hands the third to the persistent AOT cache
(``serve/aot_cache.py``):

- The **template** process imports everything, stages the model's
  weights into ONE shared-memory segment (``shm_ring.create_weight_
  segment`` — the module that owns all SharedMemory lifecycle), binds a
  unix socket, and waits. It deliberately NEVER initializes the JAX
  backend: XLA's thread pools don't survive ``fork()``, so the template
  stays a pure python+numpy process and each forked child initializes
  JAX fresh — the compile win comes from the on-disk AOT cache, not an
  inherited jit cache.
- A **fork request** makes the template ``os.fork()``; the child
  attaches the weight segment (one memcpy per leaf, zero pickle),
  builds the engine against the warm AOT cache, generates a probe
  token, writes its per-phase boot anatomy to the result dir, exits.
- The **supervisor** (driver side) spawns the template, requests forks,
  respawns the template if it dies (the ``kill-template`` chaos verb),
  re-forks children that die mid-boot (``kill-joiner``), and best-effort
  unlinks the weight segment by name on teardown — a SIGKILLed template
  runs no destructor, so crash cleanup is the supervisor's job and
  ``/dev/shm`` never leaks across generations.

Chaos determinism: the TEMPLATE consumes both kill plans (it is the
sole forker). ``kill-template@N`` self-delivers at its N-th fork op;
``kill-joiner@N`` is popped from the plan when fork index N is first
requested and the signal rides the fork call into that child — so a
re-forked survivor with the same index lives, and the drill converges.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Any, Dict, Optional

from . import shm_ring

READY_PREFIX = "KT_TEMPLATE_READY "


# -- weights on disk (numpy-only: the template must not touch jax) ----------

def save_weights(path: os.PathLike, params: Any) -> None:
    """Write a params pytree as a numpy-pickled blob a process can load
    WITHOUT initializing jax (np.asarray any jax leaves first)."""
    import numpy as np

    def _np(o):
        if isinstance(o, dict):
            return {k: _np(v) for k, v in o.items()}
        if isinstance(o, tuple):
            return tuple(_np(v) for v in o)
        if isinstance(o, list):
            return [_np(v) for v in o]
        return np.asarray(o)

    np.save(os.fspath(path), np.array(_np(params), dtype=object),
            allow_pickle=True)


def load_weights(path: os.PathLike) -> Any:
    import numpy as np
    return np.load(os.fspath(path), allow_pickle=True).item()


# -- model spec → config (built in the CHILD, post-fork) --------------------

def _build_cfg(model: Dict[str, Any]):
    """Config object from the spec's model dict. Kinds are the bench/test
    models; real deployments construct the engine directly and only use
    the cache + segment layers."""
    kind = model.get("kind", "llama-tiny")
    if kind == "llama-tiny":
        import jax.numpy as jnp
        from ..models.llama import LlamaConfig
        kwargs = dict(model.get("kwargs") or {})
        kwargs.setdefault("attn_impl", "xla")
        kwargs.setdefault("remat", False)
        return LlamaConfig.tiny(dtype=jnp.float32, **kwargs)
    raise ValueError(f"unknown template model kind {kind!r}")


# -- the forked replica (and the cold-boot A/B arm) -------------------------

def _boot_engine(spec: Dict, params_np, phases: Dict[str, float],
                 aot_root: Optional[str]):
    """Shared engine-boot tail: device_put the host weights (attach
    phase's second half), init the engine through the AOT cache, probe
    one token. Returns (engine, aot_stats)."""
    import jax.numpy as jnp
    import jax

    t = time.monotonic()
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    phases["weight_attach"] = phases.get("weight_attach", 0.0) + (
        time.monotonic() - t)

    cache = None
    if aot_root:
        from ..serve.aot_cache import AOTCompileCache
        cache = AOTCompileCache(aot_root)
    t = time.monotonic()
    from ..serve.engine import GenerationEngine
    eng = GenerationEngine(params, _build_cfg(spec.get("model") or {}),
                           aot_cache=cache,
                           **(spec.get("engine") or {}))
    phases["compile_or_cache"] = time.monotonic() - t

    t = time.monotonic()
    probe = spec.get("probe_prompt") or [1, 2, 3]
    h = eng.submit(list(probe), max_new_tokens=int(
        spec.get("probe_tokens", 2)))
    while eng.step():
        pass
    h.result(timeout=0)
    phases["first_token"] = time.monotonic() - t
    return eng, (eng.aot_stats() if cache else {})


def _write_result(spec: Dict, name: str, payload: Dict) -> None:
    out = Path(spec["result_dir"])
    out.mkdir(parents=True, exist_ok=True)
    tmp = out / f".{name}.tmp"
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, out / f"{name}.json")


def _observe_phases(phases: Dict[str, float], total: float) -> None:
    try:
        from .. import telemetry
        fam = telemetry.cold_start_metrics()
        for phase, dt in phases.items():
            fam["phase_seconds"].observe(dt, phase=phase)
        fam["total"].set(total)
        fam["boot_ts"].set(time.time())
    except Exception:
        pass


def _replica_main(spec: Dict, manifest: Dict, idx: int,
                  kill_sig: Optional[int]) -> None:
    """Runs in the forked child: attach → (chaos) → engine → probe →
    result file. Never returns (``os._exit``) so the child can't fall
    back into the template's accept loop."""
    code = 0
    try:
        t_start = time.monotonic()
        phases: Dict[str, float] = {"import": 0.0}   # template paid it
        t = time.monotonic()
        params_np = shm_ring.attach_weight_segment(manifest)
        phases["weight_attach"] = time.monotonic() - t
        if kill_sig is not None:
            # kill-joiner: die mid-boot, weights attached but not serving
            os.kill(os.getpid(), kill_sig)
        eng, aot = _boot_engine(spec, params_np, phases,
                                spec.get("aot_root"))
        total = time.monotonic() - t_start
        _observe_phases(phases, total)
        _write_result(spec, f"replica_{idx}",
                      {"idx": idx, "pid": os.getpid(), "mode": "fork",
                       "ok": True, "phases": phases, "total_s": total,
                       "aot": aot})
        eng.stop()
    except BaseException as e:  # noqa: BLE001 — child reports, never raises
        code = 1
        try:
            _write_result(spec, f"replica_{idx}",
                          {"idx": idx, "pid": os.getpid(), "mode": "fork",
                           "ok": False, "error": f"{type(e).__name__}: {e}"})
        except Exception:
            pass
    finally:
        os._exit(code)


def cold_boot_main(spec_path: str, idx: int, import_t0: float) -> None:
    """The A/B baseline: a fresh interpreter that pays import + weight
    load + compile with no template and (typically) an empty AOT dir.
    ``import_t0`` is the wall-clock the parent recorded at spawn, so the
    import phase covers the interpreter+jax import bill this process
    already paid before reaching here."""
    spec = json.loads(Path(spec_path).read_text())
    t_start = time.monotonic()
    phases: Dict[str, float] = {"import": max(0.0, time.time() - import_t0)}
    t = time.monotonic()
    params_np = load_weights(spec["weights"])
    phases["weight_fetch"] = time.monotonic() - t
    eng, aot = _boot_engine(spec, params_np, phases, spec.get("aot_root"))
    total = phases["import"] + (time.monotonic() - t_start)
    _observe_phases(phases, total)
    _write_result(spec, f"cold_{idx}",
                  {"idx": idx, "pid": os.getpid(), "mode": "cold",
                   "ok": True, "phases": phases, "total_s": total,
                   "aot": aot})
    eng.stop()


# -- the template process ---------------------------------------------------

def template_main(spec_path: str) -> None:
    """The template's whole life: load weights (numpy), stage the shm
    segment, announce readiness on stdout, serve fork requests over the
    unix socket until ``shutdown``. No jax backend init, ever — see the
    module docstring."""
    spec = json.loads(Path(spec_path).read_text())
    chaos_spec = spec.get("chaos")            # None → read KT_CHAOS env
    from ..chaos import template_kill_plan, joiner_kill_plan
    kill_plan = template_kill_plan(chaos_spec)
    joiner_plan = dict(joiner_kill_plan(chaos_spec))

    # Pre-pay the import bill for every future child: jax and the engine
    # module are IMPORT-safe to fork (no backend, no threads — asserted
    # below) even though backend INIT is not. Children inherit warm
    # sys.modules and only initialize XLA post-fork.
    if spec.get("preimport", True):
        import jax._src.xla_bridge as _xb
        from ..serve import engine as _engine  # noqa: F401
        assert not _xb._backends, \
            "template imported a module that initialized the JAX backend " \
            "— forked children would inherit dead XLA thread pools"

    params_np = load_weights(spec["weights"])
    seg = shm_ring.create_weight_segment(params_np, tag="template")
    sock_path = spec["socket"]
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(16)
    print(f"{READY_PREFIX}{json.dumps({'segment': seg.name})}", flush=True)

    fork_op = 0
    try:
        while True:
            conn, _ = srv.accept()
            with conn:
                try:
                    req = json.loads(conn.makefile("r").readline() or "{}")
                except ValueError:
                    continue
                cmd = req.get("cmd")
                if cmd == "ping":
                    conn.sendall(b'{"ok": true}\n')
                elif cmd == "manifest":
                    conn.sendall((json.dumps(
                        {"ok": True, "manifest": seg.manifest}) + "\n")
                        .encode())
                elif cmd == "shutdown":
                    conn.sendall(b'{"ok": true}\n')
                    return
                elif cmd == "fork":
                    sig_no = kill_plan.get(fork_op)
                    fork_op += 1
                    if sig_no is not None:
                        # kill-template: die on the fork op, BEFORE the
                        # fork — the supervisor sees EOF and respawns
                        os.kill(os.getpid(), sig_no)
                    idx = int(req.get("idx", fork_op - 1))
                    child_sig = joiner_plan.pop(idx, None)
                    pid = os.fork()
                    if pid == 0:
                        try:
                            srv.close()
                        except Exception:  # noqa: BLE001
                            pass
                        _replica_main(spec, seg.manifest, idx, child_sig)
                        # unreachable: _replica_main os._exits
                    conn.sendall((json.dumps(
                        {"ok": True, "pid": pid, "idx": idx}) + "\n")
                        .encode())
                else:
                    conn.sendall(b'{"ok": false, "error": "bad cmd"}\n')
            # reap any exited children so the accept loop never
            # accumulates zombies across a long burst
            try:
                while os.waitpid(-1, os.WNOHANG)[0]:
                    pass
            except ChildProcessError:
                pass
    finally:
        seg.close()                           # owner: close AND unlink
        try:
            srv.close()
            os.unlink(sock_path)
        except Exception:  # noqa: BLE001
            pass


# -- driver-side supervisor -------------------------------------------------

class TemplateSupervisor:
    """Owns one template subprocess: spawn, fork-by-socket, respawn on
    death, crash-safe segment cleanup. The chaos drill's convergence
    logic lives here — a dead template (kill-template) is respawned with
    its chaos schedule consumed, a dead joiner (kill-joiner) is re-forked
    by the caller via :meth:`fork` with the same index."""

    def __init__(self, spec: Dict, *, timeout: float = 120.0):
        self.spec = dict(spec)
        self.spec.setdefault("chaos",
                             os.environ.get("KT_CHAOS") or None)
        self.timeout = timeout
        self.proc: Optional[subprocess.Popen] = None
        self.segment_name: Optional[str] = None
        self.respawns = 0
        self._tmp = Path(tempfile.mkdtemp(prefix="kt-template-"))
        self.spec.setdefault("socket", str(self._tmp / "template.sock"))
        self._spawn()

    # -- lifecycle ----------------------------------------------------------

    def _spawn(self) -> None:
        import select

        spec_file = self._tmp / f"spec_{self.respawns}.json"
        spec_file.write_text(json.dumps(self.spec))
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "kubetorch_tpu.serving.warm_template",
             str(spec_file)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        deadline = time.monotonic() + self.timeout
        # select() on the stdout fd so the deadline holds even while
        # nothing is printed — a template that wedges before READY (alive
        # but silent; its stderr is DEVNULL) must time out and die, not
        # hang the supervisor on a blocking readline
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.proc.kill()
                try:
                    self.proc.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pass
                raise TimeoutError("template not READY in time")
            readable, _, _ = select.select([self.proc.stdout], [], [],
                                           min(remaining, 1.0))
            if not readable:
                if self.proc.poll() is not None:
                    raise RuntimeError("template died before READY")
                continue
            # READY is one short flush()ed print — an atomic pipe write,
            # so a readable fd means the full line arrives without
            # blocking past the deadline
            line = self.proc.stdout.readline()
            if line.startswith(READY_PREFIX):
                self.segment_name = json.loads(
                    line[len(READY_PREFIX):])["segment"]
                break
            if not line:
                # EOF before READY: the template is dead (or severed its
                # stdout, which is the same thing to us) — reap it
                try:
                    self.proc.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    self.proc.kill()
                raise RuntimeError("template died before READY")

    def _respawn(self) -> None:
        old = self.segment_name
        try:
            if self.proc is not None:
                self.proc.kill()
                self.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001
            pass
        # the dead template ran no destructor: reclaim its segment by
        # name so the burst leaks nothing even under SIGKILL
        if old:
            shm_ring.unlink_weight_segment(old)
        self.respawns += 1
        # the schedule is consumed-once per lineage: the respawned
        # template must not re-arm the verb that just killed it
        self.spec["chaos"] = ""
        self._spawn()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    # -- protocol -----------------------------------------------------------

    def _call(self, req: Dict, timeout: float = 30.0) -> Dict:
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.settimeout(timeout)
        try:
            c.connect(self.spec["socket"])
            c.sendall((json.dumps(req) + "\n").encode())
            line = c.makefile("r").readline()
            if not line:
                raise ConnectionError("template hung up")
            return json.loads(line)
        finally:
            c.close()

    def fork(self, idx: int) -> Dict:
        """Request fork ``idx``; if the template is dead (or dies on this
        very request — kill-template), respawn once and retry. Counted in
        ``kt_template_forks_total``."""
        from .. import telemetry
        forks = telemetry.cold_start_metrics()["forks"]
        try:
            out = self._call({"cmd": "fork", "idx": idx})
            forks.inc(outcome="ok" if out.get("ok") else "error")
            return out
        except (OSError, ValueError):
            forks.inc(outcome="template_dead")
            self._respawn()
            out = self._call({"cmd": "fork", "idx": idx})
            forks.inc(outcome="ok" if out.get("ok") else "error")
            return out

    def manifest(self) -> Dict:
        return self._call({"cmd": "manifest"})["manifest"]

    def shutdown(self) -> None:
        try:
            self._call({"cmd": "shutdown"}, timeout=10)
        except Exception:  # noqa: BLE001
            pass
        try:
            if self.proc is not None:
                self.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001
            try:
                self.proc.kill()
            except Exception:  # noqa: BLE001
                pass
        if self.segment_name:
            # idempotent: a clean template already unlinked it
            shm_ring.unlink_weight_segment(self.segment_name)

    def __enter__(self) -> "TemplateSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


def main(argv) -> None:
    if argv and argv[0] == "--cold":
        cold_boot_main(argv[1], int(argv[2]), float(argv[3]))
    else:
        template_main(argv[0])


if __name__ == "__main__":
    main(sys.argv[1:])
