"""Per-pool worker liveness watchdog: fail-fast death detection, typed
crash causes, and bounded auto-restart of the rank pool.

The gap this closes: ``ProcessPool`` checked ``worker.alive`` only at
*submit* time, so a rank that was OOM-killed or segfaulted **mid-call** left
its future pending until the per-call timeout — or forever with
``timeout=None`` — and nothing ever restarted the dead rank even though
``healthy`` flipped false. On GKE TPU slices, where preemption and
maintenance events are routine (Singularity arXiv:2202.07848 argues this
must be a transparent layer, not per-job timeout hygiene), that is the
difference between a 2-second typed failure plus self-heal and a wedged pod.

One watchdog per :class:`~.process_pool.ProcessPool`:

1. **Detect** — a monitor thread polls every rank subprocess each
   ``KT_WATCHDOG_INTERVAL_S`` (default 0.5s). ``Process.is_alive()`` +
   ``exitcode`` are the ground truth; no heartbeat protocol is needed
   because the parent IS the process's parent.
2. **Classify** — the exitcode (negative = signal), the pod's drain state,
   preemption markers, and cgroup OOM evidence map the death to a typed
   cause: ``OOMKilled`` / ``Evicted`` / ``Preempted`` / ``Crashed`` /
   ``Killed`` / ``Exited``.
3. **Fail fast** — every in-flight future registered to the dead rank is
   failed with :class:`~..exceptions.WorkerDiedError` (cause, rank,
   exitcode attached) immediately — bounded by the watchdog interval, never
   the call timeout. ``on_death`` hooks let supervisors fan the cause out
   (``DistributedSupervisor`` translates it into a critical
   ``WorkerMembershipChanged`` that cancels the whole distributed call).
4. **Restart** — a sliding-window budget (``KT_RESTART_BUDGET`` restarts
   per ``KT_RESTART_WINDOW_S``, via :class:`~..resilience.RestartBudget`)
   drives self-healing with :func:`~..resilience.restart_policy` backoff:
   frameworks with spawn-fixed collective identity (JAX/TPU mesh) get a
   **full-pool** restart (a compiled mesh cannot mix old and new ranks);
   per-call-identity frameworks get a **single-rank** respawn. Budget
   exhaustion is a *permanent* typed failure: the pool stays unhealthy,
   ``/ready`` stays down, and every later submit raises immediately.

Deterministic proof: the chaos verb ``kill-rank:<sig>@<op-index>``
(:mod:`kubetorch_tpu.chaos`) kills a rank from inside, mid-call, so the
suite can assert detection latency, restart cadence, and budget semantics
without racing a real preemption.
"""

from __future__ import annotations

import os
import signal as signal_mod
import threading
import time
import traceback
from typing import TYPE_CHECKING, Callable, Dict, List, Optional

from .. import telemetry
from ..exceptions import WorkerDiedError
from ..resilience import RestartBudget, RetryPolicy, restart_policy

# observable self-healing (ISSUE 5): every death classification and every
# restart decision is a counter + a root span in the trace ring, so the
# flight recorder answers "what killed rank 3 and what did we do about it"
# without grepping pod logs
_DEATHS = telemetry.counter(
    "kt_worker_deaths_total",
    "Rank subprocess deaths observed by the watchdog, by typed cause",
    labels=("cause",))
_RESTARTS = telemetry.counter(
    "kt_worker_restarts_total",
    "Rank-pool restarts driven by the watchdog, by mode",
    labels=("mode",))
# ISSUE 6 budget split: hard restarts (crash-loop guard, the watchdog's own
# budget) and elastic resumes (checkpoint-resume/re-mesh, the coordinator's
# budget) are distinct series — one healthy elastic job riding preemptions
# must not look like a crash loop on a dashboard, or in a budget
_RESTARTS_KIND = telemetry.counter(
    "kt_restarts_total",
    "Rank-pool restarts by kind: hard (full respawn, restart budget) vs "
    "elastic (checkpoint resume / N-1 re-mesh, elastic budget)",
    labels=("kind",))
_BUDGET_EXHAUSTED = telemetry.counter(
    "kt_restart_budget_exhausted_total",
    "Permanent pool failures after restart-budget exhaustion")

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .process_pool import ProcessPool
    from .process_worker import ProcessWorker

WATCHDOG_INTERVAL_ENV = "KT_WATCHDOG_INTERVAL_S"
RESTART_BUDGET_ENV = "KT_RESTART_BUDGET"
RESTART_WINDOW_ENV = "KT_RESTART_WINDOW_S"

# cgroup OOM-kill counters, v2 then v1. The kernel increments these when the
# OOM killer fires inside this pod's cgroup — the evidence that turns an
# anonymous SIGKILL into a typed OOMKilled. KT_OOM_EVENTS_PATH overrides for
# tests (and for nonstandard cgroup mounts).
_OOM_EVENT_PATHS = (
    "/sys/fs/cgroup/memory.events",
    "/sys/fs/cgroup/memory/memory.oom_control",
)

# Signals whose default disposition is a core dump: the process crashed on
# its own (segfault, abort, bus error, FPE, illegal instruction) rather than
# being killed from outside.
_CRASH_SIGNALS = frozenset(
    getattr(signal_mod, name).value
    for name in ("SIGSEGV", "SIGABRT", "SIGBUS", "SIGFPE", "SIGILL")
    if hasattr(signal_mod, name))

# The pod-level drain flag: flipped by the server's SIGTERM handler so a
# rank's SIGTERM death during the drain window classifies as an eviction /
# preemption rather than an anonymous kill. Module-level because the pool
# has no path to ServerState (and tests need to flip it without a server).
_draining = threading.Event()


def set_draining(reason: Optional[str] = None) -> None:
    """Mark the pod as draining (called from the server's SIGTERM path)."""
    _draining.set()


def clear_draining() -> None:
    _draining.clear()


def is_draining() -> bool:
    return _draining.is_set()


def _env_or_cfg(env_key: str, cfg_field: str, default: float,
                cast: Callable = float):
    """Env wins over the layered config (the config singleton may predate a
    runtime env mutation — tests and pods set these on the fly)."""
    raw = os.environ.get(env_key)
    if raw is not None:
        try:
            return cast(raw)
        except (TypeError, ValueError):
            pass
    try:
        from ..config import config
        return cast(config().get(cfg_field, default))
    except Exception:
        return default


def watchdog_interval() -> float:
    return max(0.05, _env_or_cfg(WATCHDOG_INTERVAL_ENV,
                                 "watchdog_interval_s", 0.5))


def restart_budget() -> int:
    return max(0, _env_or_cfg(RESTART_BUDGET_ENV, "restart_budget", 3, int))


def restart_window() -> float:
    return max(1.0, _env_or_cfg(RESTART_WINDOW_ENV, "restart_window_s", 300.0))


def read_oom_kill_count() -> Optional[int]:
    """This cgroup's cumulative ``oom_kill`` counter, or None when no
    counter is readable (non-Linux, no cgroup controller)."""
    paths = [os.environ["KT_OOM_EVENTS_PATH"]] \
        if os.environ.get("KT_OOM_EVENTS_PATH") else list(_OOM_EVENT_PATHS)
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 2 and parts[0] == "oom_kill":
                        return int(parts[1])
        except (OSError, ValueError):
            continue
    return None


def _preemption_marker() -> bool:
    """Same markers the server's SIGTERM classifier uses
    (``http_server._termination_reason``): spot/maintenance reclaim."""
    return bool(os.environ.get("KT_PREEMPTIBLE")) or os.path.exists(
        "/var/run/kubetorch/preemption")


def classify_death(exitcode: Optional[int], draining: Optional[bool] = None,
                   oom_evidence: Optional[bool] = None) -> str:
    """Map a dead rank's exitcode to a typed cause.

    ``exitcode`` follows ``multiprocessing.Process.exitcode``: negative is
    the signal number, positive a ``sys.exit`` status. ``draining`` and
    ``oom_evidence`` default to live lookups so the pure mapping stays
    testable with explicit values.
    """
    if exitcode is None:
        return "Unknown"
    if exitcode == 0:
        return "Exited"
    if exitcode > 0:
        return "Crashed"
    sig = -exitcode
    if sig == signal_mod.SIGKILL.value:
        return "OOMKilled" if oom_evidence else "Killed"
    if sig == signal_mod.SIGTERM.value:
        if _preemption_marker():
            return "Preempted"
        if draining if draining is not None else is_draining():
            return "Evicted"
        return "Killed"
    if sig in _CRASH_SIGNALS:
        return "Crashed"
    return "Killed"


# the straggler cause (ISSUE 17): a pipeline stage that is ALIVE but has
# stopped making progress. classify_death can never produce it (there is
# no exitcode), so the elastic layers treat it as a distinct member of the
# cause taxonomy — a Slow stage is re-grouped around, not restarted
CAUSE_SLOW = "Slow"


def classify_straggler(heartbeat_age_s: float,
                       stall_after_s: float) -> Optional[str]:
    """``CAUSE_SLOW`` when a live process's last heartbeat is older than
    the stall threshold, else None. Pure (ages are passed in, not
    sampled) so the pipeline supervisor's stall detection is testable
    without real clocks — the dead/slow distinction matters because a
    GPipe tick is lockstep: one straggling stage paces every tick, so
    waiting it out costs the whole pipe while re-grouping costs one
    stage's layers."""
    if stall_after_s > 0 and heartbeat_age_s > stall_after_s:
        return CAUSE_SLOW
    return None


class Watchdog:
    """Liveness monitor for one :class:`ProcessPool`.

    Owned and started by the pool; all restarts run on the watchdog thread,
    so a restart can never race another restart, and workers the watchdog
    itself replaces are swapped out of ``pool.workers`` before the next
    poll observes them.
    """

    def __init__(self, pool: "ProcessPool",
                 interval_s: Optional[float] = None,
                 budget: Optional[int] = None,
                 window_s: Optional[float] = None,
                 backoff: Optional[RetryPolicy] = None):
        self.pool = pool
        self.interval_s = interval_s if interval_s is not None \
            else watchdog_interval()
        n = budget if budget is not None else restart_budget()
        self.budget = RestartBudget(
            n, window_s if window_s is not None else restart_window())
        self.backoff = backoff or restart_policy(max(n, 1))
        self._delays = self.backoff.preview_delays(max(n, 1))
        # hooks: on_death(local_rank, WorkerDiedError) fires before restart;
        # on_restart() fires after a successful respawn (supervisors clear
        # death-caused membership events so the healed pool serves again)
        self.on_death: List[Callable[[int, WorkerDiedError], None]] = []
        self.on_restart: List[Callable[[], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # keyed by worker object identity; values keep the handle referenced
        # so a recycled id() can never alias a new worker
        self._handled: Dict[int, "ProcessWorker"] = {}
        self.recovering = False
        self.restarts = 0
        self.deaths: List[Dict] = []
        self._failed_fields: Optional[Dict] = None
        self._oom_baseline = read_oom_kill_count()
        # elastic coordinator (serving/elastic.py), attached by supervisors
        # with an elastic policy: deaths then resolve to checkpoint-resume /
        # N-1 re-mesh on the coordinator's OWN budget instead of a same-size
        # hard respawn on this watchdog's budget
        self.elastic = None

    def attach_elastic(self, coordinator) -> None:
        """Route future death verdicts through an elastic coordinator."""
        self.elastic = coordinator

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kt-watchdog")
        self._thread.start()

    def stop(self) -> None:
        """Stop BEFORE the pool tears workers down, so intentional shutdown
        exits are never classified as deaths (and never burn the budget)."""
        self._stop.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=max(5.0, self.interval_s * 2))

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 — the watchdog must outlive bugs
                print("[kt] watchdog check failed:\n" + traceback.format_exc())

    # -- state surfaced to the pool / server --------------------------------

    @property
    def failed(self) -> bool:
        """True after budget exhaustion: the pool is permanently down."""
        return self._failed_fields is not None

    def permanent_error(self) -> Optional[WorkerDiedError]:
        """A FRESH exception per raise site (a shared instance would
        accumulate tracebacks across unrelated calls)."""
        if self._failed_fields is None:
            return None
        return WorkerDiedError(**self._failed_fields)

    def death_error(self, idx: int, worker: "ProcessWorker") -> WorkerDiedError:
        """Typed error for a rank observed dead at submit time."""
        if self._failed_fields is not None:
            return self.permanent_error()
        exitcode = getattr(worker, "exitcode", None)
        cause = classify_death(exitcode, oom_evidence=self._oom_evidence())
        return WorkerDiedError(
            f"Rank subprocess {idx} is dead (cause={cause}, "
            f"exitcode={exitcode})", cause=cause, rank=idx, exitcode=exitcode)

    def state_dict(self) -> Dict:
        """Restart state for ``/health`` (and operators' eyeballs)."""
        out = {"restarts": self.restarts, "recovering": self.recovering,
               "interval_s": self.interval_s, **self.budget.state()}
        if self.elastic is not None:
            out["elastic"] = self.elastic.state_dict()
        if self._failed_fields is not None:
            out["permanent_failure"] = dict(self._failed_fields)
        if self.deaths:
            out["recent_deaths"] = self.deaths[-5:]
        return out

    # -- the check ----------------------------------------------------------

    def _oom_evidence(self) -> bool:
        current = read_oom_kill_count()
        if current is None:
            return False
        baseline = self._oom_baseline or 0
        return current > baseline

    def check_now(self) -> None:
        """One poll pass; called from the monitor thread (and synchronously
        from tests)."""
        pool = self.pool
        if self._stop.is_set() or pool._stopping.is_set():
            return
        newly_dead: List[int] = []
        last_exc: Optional[WorkerDiedError] = None
        for idx, worker in enumerate(list(pool.workers)):
            if worker.alive or id(worker) in self._handled:
                continue
            self._handled[id(worker)] = worker
            exc = self.death_error(idx, worker)
            newly_dead.append(idx)
            last_exc = exc
            self.deaths.append({"rank": idx, "cause": exc.cause,
                                "exitcode": exc.exitcode, "at": time.time()})
            print(f"[kt] watchdog: rank {idx} died "
                  f"(cause={exc.cause}, exitcode={exc.exitcode})")
            _DEATHS.inc(cause=exc.cause)
            # fail-fast: the dead rank's in-flight futures resolve NOW,
            # bounded by the watchdog interval — not the call timeout. The
            # span brackets detection → typed fail-fast → death hooks; it is
            # a root span (no request context on the watchdog thread) the
            # ring keeps for post-incident queries.
            with telemetry.span("watchdog.death", rank=idx, cause=exc.cause,
                                exitcode=exc.exitcode):
                pool.fail_worker_futures(idx, exc)
                # flight-recorder black box (ISSUE 20): commit the death to
                # this supervisor's spool NOW — if the whole pod goes next,
                # the rank's demise is already on disk
                from ..obs import note_death
                note_death(idx, exc.cause, exc.exitcode)
                for hook in list(self.on_death):
                    try:
                        hook(idx, exc)
                    except Exception:  # noqa: BLE001
                        print("[kt] watchdog on_death hook failed:\n"
                              + traceback.format_exc())
        if newly_dead:
            # wake blocked response routers so the dead rank's drain (and
            # router exit) happens NOW; the restart path then reclaims its
            # shared-memory ring segments (ISSUE 10) — a dead rank must
            # never leak /dev/shm across worker generations
            try:
                pool.wake_routers()
            except Exception:  # noqa: BLE001 — test doubles without pipes
                pass
        if newly_dead and not pool._stopping.is_set():
            self._maybe_restart(newly_dead, last_exc)

    # -- restart policy ------------------------------------------------------

    def _fail_permanently(self, exc: WorkerDiedError, why: str) -> None:
        """Flip to the permanent typed failure and strand no waiter."""
        self._failed_fields = {
            "message": (f"rank pool permanently failed: {why}; last death: "
                        f"rank {exc.rank} cause={exc.cause}"),
            "cause": exc.cause, "rank": exc.rank,
            "exitcode": exc.exitcode}
        print(f"[kt] watchdog: {self._failed_fields['message']}")
        _BUDGET_EXHAUSTED.inc()
        with telemetry.span("watchdog.permanent_failure",
                            cause=exc.cause, rank=exc.rank,
                            budget=self.budget.budget):
            # whatever is still in flight on live ranks fails typed too —
            # the pool will never answer
            self.pool.cancel_pending(self.permanent_error())
        # no restart will ever run: reclaim the dead ranks' shm rings here
        # (live ranks keep theirs until shutdown force-kills them)
        for worker in list(self.pool.workers):
            if not worker.alive:
                cleanup = getattr(worker, "cleanup_shm", None)
                if cleanup is not None:
                    cleanup()

    def _maybe_restart(self, dead_idxs: List[int],
                       exc: WorkerDiedError) -> None:
        if self.failed:
            return
        self.recovering = True
        try:
            if self.elastic is not None:
                self._elastic_restart(dead_idxs, exc)
                return
            if not self.budget.try_acquire():
                self._fail_permanently(
                    exc, f"restart budget exhausted ({self.budget.budget} "
                         f"restarts / {self.budget.window_s:g}s window)")
                return
            delay = self._delays[min(self.restarts, len(self._delays) - 1)]
            if delay > 0 and self._stop.wait(delay):
                return          # pool shut down while we backed off
            from .env_contract import framework_for
            fw = framework_for(self.pool.framework_name)
            mode = "single-rank" if fw.per_call_identity else "full-pool"
            with telemetry.span("watchdog.restart", mode=mode,
                                cause=exc.cause, ranks=str(dead_idxs),
                                backoff_s=round(delay, 4)) as sp:
                if fw.per_call_identity:
                    # collective identity binds per call: the dead rank
                    # alone respawns, live ranks keep serving
                    for idx in dead_idxs:
                        self.pool.restart_worker(idx)
                else:
                    # spawn-fixed identity (JAX/TPU mesh): a compiled mesh
                    # cannot mix old and new ranks — the whole pool restarts
                    self.pool.restart_all(exc)
                self.restarts += 1
                _RESTARTS.inc(mode=mode)
                _RESTARTS_KIND.inc(kind="hard")
                sp.set_attr("budget_remaining", self.budget.remaining)
            print(f"[kt] watchdog: pool restarted "
                  f"({'ranks ' + str(dead_idxs) if fw.per_call_identity else 'full pool'}; "
                  f"restart {self.restarts}, "
                  f"{self.budget.remaining} left in window)")
            self._fire_on_restart()
        finally:
            self.recovering = False

    def _elastic_restart(self, dead_idxs: List[int],
                         exc: WorkerDiedError) -> None:
        """Elastic path (ISSUE 6): the coordinator decides — re-mesh to the
        survivors and resume from the last committed checkpoint, restart
        with a scaled-down batch (OOM), or fail hard when the *elastic*
        budget is spent. The watchdog's own hard-restart budget is never
        touched on this path: the budgets are split by design."""
        surviving = max(0, len(self.pool.workers) - len(dead_idxs))
        verdict = self.elastic.decide(exc.cause, surviving,
                                      self.pool.num_procs)
        if verdict["action"] == "fail":
            self._fail_permanently(
                exc, f"elastic policy gave up "
                     f"({verdict.get('reason', 'no resume possible')})")
            return
        delay = self._delays[min(self.restarts, len(self._delays) - 1)]
        if delay > 0 and self._stop.wait(delay):
            return              # pool shut down while we backed off
        with telemetry.span("watchdog.elastic_resume",
                            action=verdict["action"], cause=exc.cause,
                            ranks=str(dead_idxs),
                            num_procs=verdict["num_procs"],
                            backoff_s=round(delay, 4)) as sp:
            # a re-mesh is always a full respawn: surviving ranks hold a
            # world-size-N collective identity that no longer exists
            self.pool.restart_all(exc, num_procs=verdict["num_procs"],
                                  extra_env=verdict["env"])
            self.restarts += 1
            _RESTARTS_KIND.inc(kind="elastic")
            sp.set_attr("budget_remaining", self.elastic.budget.remaining)
        print(f"[kt] watchdog: elastic {verdict['action']} "
              f"(ranks {dead_idxs} died cause={exc.cause}; pool now "
              f"{verdict['num_procs']} rank(s), "
              f"{self.elastic.budget.remaining} elastic resume(s) left)")
        self._fire_on_restart()

    def _fire_on_restart(self) -> None:
        for hook in list(self.on_restart):
            try:
                hook()
            except Exception:  # noqa: BLE001
                print("[kt] watchdog on_restart hook failed:\n"
                      + traceback.format_exc())
