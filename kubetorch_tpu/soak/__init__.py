"""The chaos conductor (ISSUE 15): seeded whole-stack fault-schedule soak.

PRs 2-13 each shipped a hand-written chaos drill — one subsystem, one
scripted fault, one scripted moment. This package is the composition
harness: a **seeded, weighted fault schedule** over the chaos-verb
grammar (:mod:`kubetorch_tpu.chaos` exports the registry it enumerates),
delivered against a REAL subprocess fleet (store ring + elastic trainer +
serving gateway + lease-fenced placements), with every client-visible
operation recorded into an append-only history that Jepsen-style global
invariants are checked over after the dust settles. On a violation, the
schedule is shrunk by delta-debugging replay to a minimal repro and
written to a replay file ``kt soak replay`` refires deterministically.

Modules:

- :mod:`.schedule`  — ``FaultEvent``/``Schedule`` + the seeded generator
  (same seed → byte-identical schedule, the replayability anchor)
- :mod:`.history`   — the op/result history + pure invariant checkers
- :mod:`.conductor` — boots the fleet, interleaves workload ops with due
  fault events, settles, and runs the checkers
- :mod:`.shrink`    — ddmin minimization of a violating schedule

Every random draw in this package comes from an explicitly seeded
``random.Random`` (the 13th ``check_resilience`` lint keeps it that way);
an unseeded draw anywhere would silently break replay.
"""

from .history import (INVARIANTS, History, Violation,  # noqa: F401
                      check_all)
from .schedule import FaultEvent, Schedule, generate  # noqa: F401
from .shrink import ddmin  # noqa: F401
