"""The chaos conductor: one seeded schedule against one real fleet.

``run_soak`` boots the profile's subprocess fleet (store ring, elastic
trainer, serving gateway), then walks the op-indexed schedule: at each
op index it first delivers every due :class:`~.schedule.FaultEvent`,
then performs ONE client workload op (put/get/rm/ls/generate/lease-tick,
drawn from a second seeded RNG so the op stream is as replayable as the
fault stream), recording the client-visible outcome into the
:class:`~.history.History`. After the last op it SETTLES — partition
down, dead processes revived chaos-free, trainer drained with
``--resume``, scrub driven to convergence, every acked write read back
at quorum, leaks scanned — and runs the invariant checkers over the
complete record.

Everything rides the repo's own resilient client surfaces:
``data_store.commands`` for store ops (ring failover + typed errors),
:class:`~kubetorch_tpu.federation.geo.GeoFrontDoor` for serving ops
(exhausted spill is ALWAYS typed), the real ``LeaseTable`` for the
fencing dance. A raw exception reaching the history is therefore a real
contract breach, not a harness artifact — which is what lets the
typed-errors invariant be an invariant.

On violation, :func:`shrink_violation` replays ddmin subsets of the
event list (same seed, same boot chaos, same op stream) until the
schedule is 1-minimal for the SAME invariant, and writes a replay file
``kt soak replay`` refires.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import telemetry
from ..chaos import reset_partition_state
from ..data_store import commands as ds
from ..data_store import netpool, ring
from ..exceptions import StaleLeaseError
from ..federation.lease import LeaseTable
from ..utils.procs import free_port, kill_process_tree, wait_for_port
from .history import History, Violation, check_all, classify_error
from .schedule import FaultEvent, Schedule
from .shrink import ddmin

# env this run mutates and must restore (the conductor runs inside the
# operator's process — a soak must not leave chaos armed in their shell)
_MUTATED_ENV = ("KT_STORE_NODES", "KT_STORE_REPLICATION",
                "KT_STORE_WRITE_QUORUM", "KT_STORE_NODE_TTL_S",
                "KT_DATA_STORE_URL", "KT_CHAOS", "KT_CHAOS_SEED",
                "KT_CHAOS_REGION_HOSTS", "PYTHONPATH",
                "KT_OBS_SPOOL", "KT_OBS_INTERVAL_S")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_TRAINER = os.path.join(_REPO_ROOT, "tests", "assets", "fed_trainer.py")
_PIPELINE_TRAINER = os.path.join(_REPO_ROOT, "tests", "assets",
                                 "pipeline_trainer.py")
_FLYWHEEL_TRAINER = os.path.join(_REPO_ROOT, "tests", "assets",
                                 "flywheel_trainer.py")
_FLYWHEEL_SERVICE = "soak-fly"
_FLYWHEEL_REPLICA = "replica-0"


@dataclass
class SoakResult:
    """One run's verdict: the schedule it played, the history it built,
    and the violations the checkers found (empty == green)."""

    schedule: Schedule
    violations: List[Violation]
    ops: int = 0
    events_fired: int = 0
    duration_s: float = 0.0
    history_path: Optional[str] = None
    records: List[Dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> Dict[str, Any]:
        return {"ok": self.ok, "seed": self.schedule.seed,
                "profile": self.schedule.profile, "ops": self.ops,
                "events_fired": self.events_fired,
                "duration_s": round(self.duration_s, 2),
                "history": self.history_path,
                "violations": [v.to_dict() for v in self.violations]}


def _clean_child_env() -> Dict[str, str]:
    """Base env for fleet children: the operator's env minus any armed
    chaos (each child gets its OWN arming from the schedule) and minus
    the TPU-relay hook that hangs bare python startups."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    for k in ("KT_CHAOS", "KT_CHAOS_SEED", "KT_CHAOS_REGION_HOSTS"):
        env.pop(k, None)
    return env


class _Gateway:
    """One sim-region serving gateway subprocess (the front door the
    generate ops hit through the GeoFrontDoor)."""

    def __init__(self, region: str, seed: int, chaos_token: str = ""):
        self.region = region
        self.seed = seed
        self.chaos_token = chaos_token
        self.port = free_port()
        self.url = f"http://127.0.0.1:{self.port}"
        self.proc: Optional[subprocess.Popen] = None

    def start(self, chaos: bool = True) -> None:
        env = _clean_child_env()
        if chaos and self.chaos_token:
            env["KT_CHAOS"] = self.chaos_token
            env["KT_CHAOS_SEED"] = str(self.seed)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "kubetorch_tpu.federation.sim_region",
             "--port", str(self.port), "--region", self.region,
             "--replicas", "2", "--slots", "4"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        if not wait_for_port("127.0.0.1", self.port, timeout=30):
            raise RuntimeError(f"soak gateway {self.region} did not start")

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            kill_process_tree(self.proc.pid)
        self.proc = None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class _Trainer:
    """The elastic trainer under fire: fed_trainer.py runs against the
    soak's store ring; kills are SIGKILL, resumes re-spawn with
    ``--resume`` appending to the same JSONL ledger."""

    def __init__(self, store: str, base_dir: str, steps: int):
        self.store = store
        self.steps = steps
        self.result = os.path.join(base_dir, "trainer-ledger.jsonl")
        self.base_key = "soak/trainer/ckpt"
        self.proc: Optional[subprocess.Popen] = None

    def start(self, resume: bool) -> None:
        if not os.path.exists(_TRAINER):
            raise RuntimeError(f"trainer asset missing: {_TRAINER}")
        args = [sys.executable, _TRAINER, "--base-key", self.base_key,
                "--store", self.store, "--steps", str(self.steps),
                "--result", self.result, "--step-sleep", "0.05"]
        if resume:
            args.append("--resume")
        self.proc = subprocess.Popen(args, env=_clean_child_env(),
                                     stdout=subprocess.DEVNULL,
                                     stderr=subprocess.DEVNULL)

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ledger(self) -> List[Dict]:
        out: List[Dict] = []
        if os.path.exists(self.result):
            with open(self.result) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            out.append(json.loads(line))
                        except ValueError:
                            out.append({"corrupt_line": line[:120]})
        return out


class _PipelineTrainer:
    """The 4-stage pipelined trainer under fire (ISSUE 17):
    ``pipeline_trainer.py`` drives real stage subprocesses over the soak's
    store ring. The schedule's ``stage:N`` boot-chaos token rides
    ``KT_CHAOS`` + ``KT_CHAOS_STAGE`` into the driver's environment, so
    exactly one stage self-faults mid-step (kill or stall) and the
    driver's embedded supervisor must re-group. Settle waits the driver
    out, then runs the unpartitioned ``--replay`` pass whose fingerprints
    the pipeline-progress invariant bit-compares against the committed
    steps."""

    def __init__(self, store: str, base_dir: str, steps: int, seed: int,
                 boot_chaos: Dict[str, str]):
        self.store = store
        self.steps = steps
        self.seed = seed
        self.result = os.path.join(base_dir, "pipeline-ledger.jsonl")
        self.replay_result = os.path.join(base_dir,
                                          "pipeline-replay.jsonl")
        self.stage_token = ""
        self.stage_index = ""
        for target, tok in sorted(boot_chaos.items()):
            if target.startswith("stage:"):
                self.stage_index = target.split(":")[1]
                self.stage_token = tok
        self.proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        if not os.path.exists(_PIPELINE_TRAINER):
            raise RuntimeError(
                f"pipeline trainer asset missing: {_PIPELINE_TRAINER}")
        env = _clean_child_env()
        if self.stage_token:
            env["KT_CHAOS"] = self.stage_token
            env["KT_CHAOS_STAGE"] = self.stage_index
            env["KT_CHAOS_SEED"] = str(self.seed)
        self.proc = subprocess.Popen(
            [sys.executable, _PIPELINE_TRAINER, "--store", self.store,
             "--steps", str(self.steps), "--stages", "4",
             "--result", self.result],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def replay(self, timeout: float) -> None:
        """The bit-identity oracle: recompute the same steps in ONE
        process with no pipeline partitioning, chaos-free."""
        try:
            subprocess.run(
                [sys.executable, _PIPELINE_TRAINER, "--replay",
                 "--steps", str(self.steps), "--stages", "4",
                 "--result", self.replay_result],
                env=_clean_child_env(), timeout=timeout,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                check=False)
        except subprocess.TimeoutExpired:
            pass

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            kill_process_tree(self.proc.pid)
        self.proc = None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ledger(self) -> List[Dict]:
        out: List[Dict] = []
        for path in (self.result, self.replay_result):
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            out.append(json.loads(line))
                        except ValueError:
                            out.append({"corrupt_line": line[:120]})
        return out


class _FlywheelTrainer:
    """The harvest trainer under fire (ISSUE 19): flywheel_trainer.py
    consumes the soak's feedback ledger through the real cursor +
    Checkpointer. The schedule's ``flywheel-trainer`` boot-chaos token
    (``kill-flywheel:SIG@N``) rides ``KT_CHAOS`` into the FIRST spawn
    only — the ``resume-flywheel`` event and the settle pass run clean,
    the way recovery always runs clean in this conductor."""

    def __init__(self, store: str, base_dir: str, seed: int,
                 chaos_token: str = ""):
        self.store = store
        self.seed = seed
        self.chaos_token = chaos_token
        self.result = os.path.join(base_dir, "flywheel-ledger.jsonl")
        self.base_key = "soak/flywheel/ckpt"
        self.proc: Optional[subprocess.Popen] = None

    def start(self, resume: bool, chaos: bool = False,
              idle_polls: int = 400) -> None:
        if not os.path.exists(_FLYWHEEL_TRAINER):
            raise RuntimeError(
                f"flywheel trainer asset missing: {_FLYWHEEL_TRAINER}")
        env = _clean_child_env()
        if chaos and self.chaos_token:
            env["KT_CHAOS"] = self.chaos_token
            env["KT_CHAOS_SEED"] = str(self.seed)
        args = [sys.executable, _FLYWHEEL_TRAINER,
                "--service", _FLYWHEEL_SERVICE,
                "--replicas", _FLYWHEEL_REPLICA,
                "--store", self.store, "--base-key", self.base_key,
                "--result", self.result, "--poll-sleep", "0.1",
                "--idle-polls", str(idle_polls)]
        if resume:
            args.append("--resume")
        self.proc = subprocess.Popen(args, env=env,
                                     stdout=subprocess.DEVNULL,
                                     stderr=subprocess.DEVNULL)

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ledger(self) -> List[Dict]:
        out: List[Dict] = []
        if os.path.exists(self.result):
            with open(self.result) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            out.append(json.loads(line))
                        except ValueError:
                            out.append({"corrupt_line": line[:120]})
        return out


def _import_flywheel_ledger(history: History,
                            ftrainer: Optional["_FlywheelTrainer"]) -> None:
    """Trainer JSONL → history records: checkpoint lines feed the commits
    invariant (kind=trainer), cursor/consume lines feed the
    flywheel-ledger invariant (kind=flywheel)."""
    for rec in ftrainer.ledger() if ftrainer is not None else []:
        if "committed" in rec:
            history.record("trainer", event="committed",
                           step=rec["committed"],
                           fingerprint=rec.get("fingerprint"))
        elif "restored" in rec:
            history.record("trainer", event="restored",
                           step=rec["restored"],
                           fingerprint=rec.get("fingerprint"))
        elif "consumed" in rec:
            history.record("flywheel", event="consumed",
                           hashes=rec["consumed"], step=rec.get("step"))
        elif "cursor_committed" in rec:
            history.record("flywheel", event="cursor-committed",
                           step=rec["cursor_committed"])
        elif "cursor_restored" in rec:
            history.record("flywheel", event="cursor-restored",
                           step=rec["cursor_restored"])
        elif "dying_at_op" in rec:
            history.record("flywheel", event="dying",
                           op=rec["dying_at_op"])
        elif "done" in rec or "drained" in rec:
            history.record("trainer", event="done",
                           step=rec.get("final_step", rec.get("drained")),
                           fingerprint=rec.get("fingerprint"))


def _promote_drill(history: History, store_url: str) -> None:
    """Settle-phase gated-promotion closure (ISSUE 19 acceptance): promote
    a good delta through the real publish→canary path on the soak's store
    ring, then drive the deliberately-bad delta with the break-glass env
    blinding the eval gate AND a canary that dies mid-bake (a dead canary
    yields no healthy evidence — the verdict is ``regressed``). The bad
    delta must roll back with the fleet fingerprint unchanged; the
    flywheel-ledger invariant's gate clause certifies it from the
    history."""
    import numpy as np

    from ..flywheel.promoter import Promoter
    from ..serve import rollout as ro

    class _Router:
        verdict = "ok"

        def set_canary(self, replica, fraction=0.1):
            pass

        def clear_canary(self):
            pass

        def canary_verdict(self, **kw):
            return self.verdict

    router = _Router()
    promoter = Promoter(
        _FLYWHEEL_SERVICE, router, store_url=store_url,
        eval_fn=lambda t: float(np.abs(t["w"]).mean()),
        bake_s=0.5, min_requests=1, poll_s=0.05)
    good = {"w": np.full(8, 1.0, dtype=np.float32)}
    v1 = promoter.promote(good, step=1)
    history.record("flywheel", event="gate", verdict=v1, bad=False)
    # second good delta so a previous manifest exists and the bad delta
    # takes the canary path, not the first-ever fast path
    v2 = promoter.promote(good, step=2)
    history.record("flywheel", event="gate", verdict=v2, bad=False)
    before = ro.read_manifest(_FLYWHEEL_SERVICE, store_url=store_url)
    router.verdict = "regressed"      # canary SIGKILLed mid-bake: no
    os.environ["KT_FLYWHEEL_BREAK"] = "promote-bad-delta"
    try:
        bad = {"w": np.full(8, 100.0, dtype=np.float32)}
        v3 = promoter.promote(bad, step=3)
    finally:
        os.environ.pop("KT_FLYWHEEL_BREAK", None)
    after = ro.read_manifest(_FLYWHEEL_SERVICE, store_url=store_url)
    unchanged = bool(before and after
                     and after.get("fingerprint") == before.get(
                         "fingerprint"))
    if not unchanged:
        v3 = "promoted" if v3 == "promoted" else f"{v3}-but-fleet-moved"
    history.record("flywheel", event="gate", verdict=v3, bad=True)


def _import_pipeline_ledger(history: History,
                            ptrainer: Optional["_PipelineTrainer"]) -> None:
    for rec in ptrainer.ledger() if ptrainer is not None else []:
        event = rec.get("event")
        if not event:
            continue
        history.record("pipeline", **{k: v for k, v in rec.items()
                                      if k != "kind"})


def _record_op(history: History, op: str, key: str, fn) -> Any:
    """Run one client op, record its client-visible outcome (typed or
    raw), never let the exception escape the soak loop."""
    m = telemetry.soak_metrics()
    try:
        result = fn()
    except BaseException as e:  # noqa: BLE001 — classifying is the point
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        name, typed = classify_error(e)
        history.record("op", op=op, key=key, ok=False, error=name,
                       typed=typed, detail=str(e)[:200])
        m["ops"].inc(op=op, outcome="typed-error" if typed else "raw-error")
        return None
    history.record("op", op=op, key=key, ok=True,
                   acked=(op == "put"))
    m["ops"].inc(op=op, outcome="ok")
    return result


def _import_ledger(history: History, trainer: Optional[_Trainer]) -> None:
    if trainer is None:
        return
    for rec in trainer.ledger():
        if "committed" in rec:
            history.record("trainer", event="committed",
                           step=rec["committed"],
                           fingerprint=rec.get("fingerprint"))
        elif "restored" in rec:
            history.record("trainer", event="restored",
                           step=rec["restored"],
                           fingerprint=rec.get("fingerprint"))
        elif "dying_at_step" in rec:
            history.record("trainer", event="dying",
                           step=rec["dying_at_step"])
        elif "done" in rec:
            history.record("trainer", event="done",
                           step=rec.get("final_step"),
                           fingerprint=rec.get("fingerprint"))


def _scan_leaks(store_roots: List[str]) -> Dict[str, List[str]]:
    shm = sorted(os.path.basename(p)
                 for p in glob.glob("/dev/shm/kt-*")
                 if os.path.exists(p))
    tmp: List[str] = []
    for root in store_roots:
        for p in glob.glob(os.path.join(root, "**", "*.tmp"),
                           recursive=True):
            tmp.append(os.path.relpath(p, root))
    return {"shm": shm, "tmp": sorted(tmp)}


def _scan_spools(spool_root: str, kills: int) -> Dict[str, Any]:
    """Flight-recorder census after teardown (ISSUE 20): hash-verify
    every child's spool. Run AFTER the fleet is dead, so each spool is
    final — a surviving writer would race the read."""
    from ..obs import read_spool, spool_dirs, spool_identity
    from ..obs.blackbox import pid_alive

    spools: List[Dict[str, Any]] = []
    for d in spool_dirs(spool_root):
        name, pid = spool_identity(d)
        loaded = read_spool(d)
        spools.append({
            "dir": str(d), "name": name, "pid": pid,
            "alive": bool(pid is not None and pid_alive(pid)),
            "records": len(loaded["records"]),
            "errors": loaded["errors"],
        })
    return {"armed": True, "kills": kills, "spools": spools}


def run_soak(sched: Schedule, base_dir: str,
             op_interval_s: float = 0.25,
             settle_timeout_s: float = 60.0,
             history_path: Optional[str] = None,
             events_override: Optional[List[FaultEvent]] = None,
             log=lambda msg: None) -> SoakResult:
    """Play one schedule against a real fleet and return the verdict.

    ``events_override`` substitutes the conductor-delivered event list
    (seed, boot chaos, and the op stream stay fixed) — the shrinker's
    replay knob. ``log`` gets human progress lines (the CLI wires it to
    stderr; tests leave it silent)."""
    import random

    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)
    from tests.assets.store_fleet import \
        SubprocessStoreFleet  # test-asset reuse is the point (ISSUE 15)

    events = sorted(events_override if events_override is not None
                    else sched.events,
                    key=lambda e: (e.at_op, e.action, e.target))
    history = History(history_path)
    ops_rng = random.Random(f"{sched.seed}-ops")
    m = telemetry.soak_metrics()
    started = time.monotonic()

    has_store = sched.store_nodes > 0
    has_trainer = sched.profile in ("train", "federation", "all")
    has_gateway = sched.profile in ("serve", "federation", "all")
    has_regions = sched.profile in ("federation", "all")
    has_pipeline = sched.profile == "pipeline"
    has_flywheel = sched.profile == "flywheel"

    saved_env = {k: os.environ.get(k) for k in _MUTATED_ENV}
    # fleet/gateway/trainer children spawn with `python -m kubetorch_tpu...`
    # and inherit os.environ at spawn time: make the package importable
    # regardless of the conductor's cwd
    pp = os.environ.get("PYTHONPATH", "")
    if _REPO_ROOT not in pp.split(os.pathsep):
        os.environ["PYTHONPATH"] = (_REPO_ROOT + os.pathsep + pp if pp
                                    else _REPO_ROOT)
    # arm the flight recorder in every fleet child (ISSUE 20): each
    # subprocess spools delta-encoded telemetry under the run dir at a
    # fast cadence, so a SIGKILLed store node/rank leaves a black box
    # the post-teardown census can hash-verify (check_blackbox)
    spool_root = os.path.join(base_dir, "obs-spool")
    os.environ["KT_OBS_SPOOL"] = spool_root
    os.environ["KT_OBS_INTERVAL_S"] = "0.05"
    from ..config import config
    cfg = config()
    saved_cfg_url = cfg.data_store_url
    fleet = None
    gateway: Optional[_Gateway] = None
    trainer: Optional[_Trainer] = None
    ptrainer: Optional[_PipelineTrainer] = None
    ftrainer: Optional[_FlywheelTrainer] = None
    fly_ledger = None  # conductor-side appender (the "serving replica")
    door = None
    lease: Optional[LeaseTable] = None
    holder: Dict[str, Any] = {}
    expected: Dict[str, Dict] = {}
    key_space = max(8, sched.n_ops // 4)
    fired = 0

    def fire(ev: FaultEvent) -> None:
        nonlocal fired
        fired += 1
        m["events"].inc(action=ev.action)
        history.record("event", action=ev.action, target=ev.target,
                       at_op=ev.at_op, token=ev.token)
        log(f"  op {ev.at_op}: {ev.action} {ev.target}")
        if ev.action == "kill-node" and fleet is not None:
            fleet.kill_node(int(ev.target.split(":")[1]))
        elif ev.action == "restart-node" and fleet is not None:
            i = int(ev.target.split(":")[1])
            # recovery runs clean: a restart never re-arms boot chaos
            fleet.chaos.pop(i, None)
            proc = fleet.procs[i]
            if proc is None or proc.poll() is not None:
                fleet.start_node(i)
            # else: a boot-armed self-kill hasn't consumed its op index
            # yet — leave the node alone; settle revives whatever died
            # after its revival slot passed
        elif ev.action == "kill-trainer" and trainer is not None:
            trainer.kill()
        elif ev.action == "resume-trainer" and trainer is not None:
            if not trainer.alive():
                trainer.start(resume=True)
        elif ev.action == "resume-flywheel" and ftrainer is not None:
            # the boot-chaos kill-flywheel token already fired (or never
            # will); recovery runs clean and must adopt the committed
            # cursor state — the flywheel-ledger invariant checks it
            if not ftrainer.alive():
                ftrainer.start(resume=True, chaos=False)
        elif ev.action == "kill-gateway" and gateway is not None:
            gateway.kill()
        elif ev.action == "restart-gateway" and gateway is not None:
            gateway.start(chaos=False)
        elif ev.action == "scale-to-zero" and gateway is not None:
            # deliberate drain, delivered the way a scale-down lands on a
            # pod: SIGKILL, no goodbye. The workload keeps firing into
            # the zero-replica window — typed errors only, per invariant.
            gateway.kill()
        elif ev.action == "cold-burst" and gateway is not None:
            # burst back under load; recovery runs clean (no chaos
            # re-arm). The end-of-run leak scan owns the "no shm/tmp
            # segments left behind" half of this episode's contract.
            gateway.start(chaos=False)
        elif ev.action == "partition-start":
            os.environ["KT_CHAOS"] = ev.token
            os.environ["KT_CHAOS_SEED"] = str(sched.seed)
            # the store ring is region-local: the partition black-holes
            # the cross-region front door, never the local data plane
            locals_ = list(fleet.urls) if fleet is not None else []
            os.environ["KT_CHAOS_REGION_HOSTS"] = ",".join(locals_)
            reset_partition_state()
        elif ev.action == "partition-stop":
            os.environ.pop("KT_CHAOS", None)
            reset_partition_state()
        elif ev.action == "lease-failover" and lease is not None:
            old = dict(holder)
            epoch = lease.grant(ev.target, "region-b")
            history.record("lease", event="grant", workload=ev.target,
                           region="region-b", epoch=epoch)
            # the fenced region's next heartbeat must die typed — and the
            # conductor records the stop BEFORE region-b starts, which is
            # exactly the ordering the fencing checker certifies
            if old:
                try:
                    lease.validate(ev.target, old["region"], old["epoch"])
                except StaleLeaseError:
                    pass
                history.record("placement", event="stop",
                               workload=ev.target, region=old["region"],
                               epoch=old["epoch"])
            history.record("placement", event="start", workload=ev.target,
                           region="region-b", epoch=epoch)
            holder.update({"workload": ev.target, "region": "region-b",
                           "epoch": epoch})

    def one_op(op_i: int) -> None:
        choices: List[str] = []
        if has_store:
            choices += ["put"] * 4 + ["get"] * 3 + ["ls", "rm"]
        if has_gateway:
            choices += ["generate"] * 2
        if has_regions:
            choices += ["lease-tick"]
        if has_flywheel:
            choices += ["fly-append"] * 3
        op = choices[ops_rng.randrange(len(choices))]
        key = f"soak/k{ops_rng.randrange(key_space)}"
        if op == "put":
            value = {"op": op_i, "nonce": ops_rng.randrange(1 << 30)}
            if _record_op(history, "put", key,
                          lambda: ds.put_json(key, value)) is not None:
                expected[key] = value
        elif op == "get":
            _record_op(history, "get", key,
                       lambda: ds.get_json(key, default=None))
        elif op == "ls":
            _record_op(history, "ls", "soak/", lambda: ds.ls("soak/"))
        elif op == "rm":
            if _record_op(history, "rm", key,
                          lambda: ds.rm(key)) is not None:
                expected.pop(key, None)
        elif op == "generate":
            import asyncio
            payload = {"prompt_len": 8 + ops_rng.randrange(16),
                       "new_tokens": 1 + ops_rng.randrange(4)}
            _record_op(history, "generate", "gateway",
                       lambda: asyncio.run(door.dispatch(payload, {})))
        elif op == "fly-append" and fly_ledger is not None:
            # live-traffic feedback: the ack the client sees is the
            # at-least-once anchor — only records the conductor saw
            # acked are owed back by the settle-read
            payload = {"op": op_i, "prompt": ops_rng.randrange(1 << 30),
                       "reward": round(ops_rng.random(), 6)}
            hashes = _record_op(history, "fly-append", _FLYWHEEL_REPLICA,
                                lambda: fly_ledger.append([payload]))
            if hashes:
                history.record("flywheel", event="acked", hashes=hashes)
        elif op == "lease-tick" and holder:
            def _tick():
                lease.validate(holder["workload"], holder["region"],
                               holder["epoch"])
                history.record("placement", event="confirmed",
                               workload=holder["workload"],
                               region=holder["region"],
                               epoch=holder["epoch"])
            _record_op(history, "lease-tick", holder["workload"], _tick)

    try:
        # --- boot -----------------------------------------------------------
        if has_store:
            chaos_by_node = {
                int(t.split(":")[1]): tok
                for t, tok in sched.boot_chaos.items()
                if t.startswith("store:")}
            fleet = SubprocessStoreFleet(
                os.path.join(base_dir, "store"), n=sched.store_nodes,
                replication=2, write_quorum=2, node_ttl_s=1.0,
                chaos=chaos_by_node,
                extra_env={"KT_CHAOS_SEED": str(sched.seed)})
            fleet.__enter__()
            os.environ.update(fleet.client_env())
            # commands.* resolve their origin from here; ring failover
            # walks the membership list when the seed node is down. The
            # cached config layer outranks the env var, so set both.
            os.environ["KT_DATA_STORE_URL"] = fleet.urls[0]
            cfg.data_store_url = fleet.urls[0]
            ring.reset_rings()
            netpool.reset_breakers()
        os.environ.pop("KT_CHAOS", None)
        reset_partition_state()
        if has_gateway:
            gateway = _Gateway("region-a", sched.seed,
                               sched.boot_chaos.get("gateway:0", ""))
            gateway.start()
            from ..federation.geo import GeoFrontDoor, HttpRegionTarget
            door = GeoFrontDoor(
                [HttpRegionTarget("region-a", gateway.url)],
                local_region="region-a")
        if has_trainer and fleet is not None:
            trainer = _Trainer(",".join(fleet.urls), base_dir,
                               steps=max(6, sched.n_ops // 3))
            trainer.start(resume=False)
        if has_pipeline and fleet is not None:
            # the driver supervises its own stage gang and re-groups
            # in-process; the conductor only arms the stage-scoped chaos
            # and, at settle, runs the unpartitioned replay oracle
            ptrainer = _PipelineTrainer(",".join(fleet.urls), base_dir,
                                        steps=max(6, sched.n_ops // 2),
                                        seed=sched.seed,
                                        boot_chaos=sched.boot_chaos)
            ptrainer.start()
        if has_flywheel and fleet is not None:
            from ..flywheel.ledger import FeedbackLedger
            fly_ledger = FeedbackLedger(_FLYWHEEL_SERVICE,
                                        _FLYWHEEL_REPLICA,
                                        store_url=fleet.urls[0])
            ftrainer = _FlywheelTrainer(
                ",".join(fleet.urls), base_dir, seed=sched.seed,
                chaos_token=sched.boot_chaos.get("flywheel-trainer", ""))
            ftrainer.start(resume=False, chaos=True)
        if has_regions:
            lease = LeaseTable()
            epoch = lease.grant("job-0", "region-a")
            history.record("lease", event="grant", workload="job-0",
                           region="region-a", epoch=epoch)
            history.record("placement", event="start", workload="job-0",
                           region="region-a", epoch=epoch)
            holder.update({"workload": "job-0", "region": "region-a",
                           "epoch": epoch})

        # --- the conducted run ---------------------------------------------
        log(f"soak: {sched.profile} seed={sched.seed} ops={sched.n_ops} "
            f"events={len(events)} boot_chaos={sched.boot_chaos}")
        pending = list(events)
        for op_i in range(sched.n_ops):
            while pending and pending[0].at_op <= op_i:
                fire(pending.pop(0))
            one_op(op_i)
            time.sleep(op_interval_s)
        for ev in pending:  # events past the horizon still fire once
            fire(ev)

        # --- settle ---------------------------------------------------------
        log("soak: settling")
        os.environ.pop("KT_CHAOS", None)
        reset_partition_state()
        if fleet is not None:
            fleet.chaos.clear()
            for i in range(fleet.n):
                proc = fleet.procs[i]
                if proc is None or proc.poll() is not None:
                    fleet.start_node(i)
        if trainer is not None:
            if not trainer.alive():
                trainer.start(resume=True)
            try:
                trainer.proc.wait(timeout=settle_timeout_s)
            except subprocess.TimeoutExpired:
                trainer.kill()
        if gateway is not None and not gateway.alive():
            gateway.start(chaos=False)

        if fleet is not None:
            deadline = time.monotonic() + settle_timeout_s
            status: Dict[str, Any] = {}
            while time.monotonic() < deadline:
                try:
                    for u in fleet.urls:
                        netpool.request("POST", f"{u}/scrub/run",
                                        timeout=60)
                    statuses = [netpool.request(
                        "GET", f"{u}/scrub/status", timeout=10).json()
                        for u in fleet.urls]
                    status = {
                        "under_replicated": sum(
                            s.get("under_replicated", 0)
                            for s in statuses),
                        # a member still in any peer's down-book means the
                        # ring has not re-converged on full membership
                        "nodes_down": max(
                            len((s.get("ring") or {}).get("down", {}))
                            for s in statuses),
                    }
                    if not status["under_replicated"] \
                            and not status["nodes_down"]:
                        break
                except Exception:  # noqa: BLE001 — converging, keep driving
                    status = {"under_replicated": -1, "nodes_down": -1}
                time.sleep(0.25)
            history.record("ring-status", **(status or
                                             {"under_replicated": -1,
                                              "nodes_down": -1}))

            for key in sorted(expected):
                got = None
                err = ""
                for _ in range(3):
                    try:
                        got = ds.get_json(key, quorum=True, default=None)
                        err = ""
                        if got is not None:
                            break
                    except Exception as e:  # noqa: BLE001
                        err = classify_error(e)[0]
                    time.sleep(0.2)
                history.record("verify", key=key, ok=got is not None,
                               match=(got == expected[key]), error=err)
        if ptrainer is not None:
            try:
                ptrainer.proc.wait(timeout=settle_timeout_s)
            except subprocess.TimeoutExpired:
                ptrainer.kill()
            ptrainer.replay(timeout=settle_timeout_s)
        if ftrainer is not None:
            # drain the live run over SIGTERM (the PR 6 contract), then a
            # clean --resume sweep consumes whatever the chaos kill
            # orphaned; only then does the settle-read take its census
            if ftrainer.alive():
                ftrainer.proc.send_signal(signal.SIGTERM)
                try:
                    ftrainer.proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    ftrainer.kill()
            ftrainer.start(resume=True, chaos=False, idle_polls=5)
            try:
                ftrainer.proc.wait(timeout=settle_timeout_s)
            except subprocess.TimeoutExpired:
                ftrainer.kill()
            from ..flywheel.ledger import read_all_hashes
            settle_hashes: List[str] = []
            if fleet is not None:
                try:
                    settle_hashes = read_all_hashes(
                        _FLYWHEEL_SERVICE, [_FLYWHEEL_REPLICA],
                        store_url=fleet.urls[0])
                except Exception as e:  # noqa: BLE001 — census best-effort
                    history.record("flywheel", event="settle-read-error",
                                   error=classify_error(e)[0])
                else:
                    history.record("flywheel", event="settle-read",
                                   hashes=settle_hashes)
            if fleet is not None:
                try:
                    _promote_drill(history, fleet.urls[0])
                except Exception as e:  # noqa: BLE001 — verdict, not crash
                    history.record(
                        "flywheel", event="gate",
                        verdict=f"drill-error:{type(e).__name__}",
                        bad=True)
        if holder:
            history.record("placement", event="stop",
                           workload=holder["workload"],
                           region=holder["region"],
                           epoch=holder["epoch"])
        _import_ledger(history, trainer)
        _import_pipeline_ledger(history, ptrainer)
        _import_flywheel_ledger(history, ftrainer)
    finally:
        if trainer is not None:
            trainer.kill()
        if ptrainer is not None:
            ptrainer.kill()
        if ftrainer is not None:
            ftrainer.kill()
        if gateway is not None:
            gateway.kill()
        roots = list(fleet.roots) if fleet is not None else []
        if fleet is not None:
            fleet.__exit__()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        cfg.data_store_url = saved_cfg_url
        ring.reset_rings()
        reset_partition_state()

    time.sleep(0.2)  # give SIGKILLed children a beat to release segments
    history.record("leak-scan", **_scan_leaks(roots))
    kill_events = sum(
        1 for r in history.records()
        if r.get("kind") == "event"
        and (str(r.get("action", "")).startswith("kill")
             or r.get("action") == "scale-to-zero"
             or str(r.get("verb", "")).startswith("kill")))
    history.record("blackbox", **_scan_spools(spool_root, kill_events))

    violations = check_all(history.records())
    for v in violations:
        m["violations"].inc(invariant=v.invariant)
    m["runs"].inc(outcome="violation" if violations else "ok")
    return SoakResult(schedule=sched, violations=violations,
                      ops=sched.n_ops, events_fired=fired,
                      duration_s=time.monotonic() - started,
                      history_path=history_path,
                      records=history.records())


# ---------------------------------------------------------------------------
# Shrinking a violating run to a minimal repro
# ---------------------------------------------------------------------------


def shrink_violation(sched: Schedule, base_dir: str,
                     invariant: str,
                     op_interval_s: float = 0.25,
                     settle_timeout_s: float = 60.0,
                     max_tests: int = 24,
                     log=lambda msg: None) -> Schedule:
    """ddmin the event list down to a 1-minimal schedule that still
    violates ``invariant``. Each predicate call is a full replay in a
    fresh directory (same seed → same boot chaos and op stream), so
    ``max_tests`` bounds wall-clock, not correctness: on cap the best
    reduction so far is returned, still a valid repro."""
    attempt = [0]

    def violates(subset: List[FaultEvent]) -> bool:
        attempt[0] += 1
        d = os.path.join(base_dir, f"shrink-{attempt[0]:03d}")
        os.makedirs(d, exist_ok=True)
        log(f"shrink: replay {attempt[0]} with {len(subset)} event(s)")
        res = run_soak(sched, d, op_interval_s=op_interval_s,
                       settle_timeout_s=settle_timeout_s,
                       events_override=list(subset))
        return any(v.invariant == invariant for v in res.violations)

    minimal = ddmin(list(sched.events), violates, max_tests=max_tests)
    out = Schedule(seed=sched.seed, profile=sched.profile,
                   n_ops=sched.n_ops, store_nodes=sched.store_nodes,
                   boot_chaos=dict(sched.boot_chaos),
                   events=sorted(minimal,
                                 key=lambda e: (e.at_op, e.action,
                                                e.target)))
    return out


def write_replay(sched: Schedule, path: str,
                 violations: List[Violation]) -> None:
    """Persist a replay file: the (possibly shrunk) schedule plus the
    violations it reproduces — the artifact ``kt soak replay`` refires."""
    body = sched.to_dict()
    body["violations"] = [v.to_dict() for v in violations]
    with open(path, "w") as f:
        json.dump(body, f, indent=2, sort_keys=True)
        f.write("\n")


def load_replay(path: str) -> Schedule:
    with open(path) as f:
        body = json.load(f)
    try:
        return Schedule.from_dict(body)
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(
            f"{path} is not a soak replay file (write one with "
            f"`kt soak run` on a violating seed): {e}") from e
