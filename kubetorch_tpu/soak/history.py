"""Op/result history + the global invariants checked over it.

The conductor records every client-visible operation (store puts/gets,
trainer ledger events, serving calls, lease placements) into an
append-only list of JSON records; after the run settles, the checkers
here are run over the COMPLETE history. Each checker is a pure function
``records -> [Violation]`` — no fleet, no clock, no I/O — so every
invariant is unit-testable with a hand-built *violating* history
(tests/test_soak.py feeds each one a lost write, a stale-lease double
placement, a raw-error leak, a fingerprint mismatch... and asserts the
checker actually fires).

Record shapes (all plain dicts; ``index`` is assigned on append):

- ``{"kind": "op", "op": "put|get|rm|ls|generate|lease-tick", "ok": bool,
  "key": ..., "error": type-name, "typed": bool, ...}`` — one client op.
  ``acked: true`` on a put marks it durability-checked at settle.
- ``{"kind": "trainer", "event": "committed|restored|dying|done",
  "step": int, "fingerprint": str}`` — the trainer's ledger, imported.
- ``{"kind": "lease", "event": "grant", "workload": w, "region": r,
  "epoch": e}`` and ``{"kind": "placement", "event": "start|stop|
  confirmed", "workload": w, "region": r, "epoch": e}`` — the fencing
  dance.
- ``{"kind": "verify", "key": k, "ok": bool, "match": bool}`` — the
  settle-phase read-back of an acked write.
- ``{"kind": "ring-status", "under_replicated": n, "nodes_down": m}`` —
  the final scrub verdict.
- ``{"kind": "leak-scan", "shm": [...], "tmp": [...]}`` — leftover
  /dev/shm segments and orphan .tmp files after teardown.
- ``{"kind": "blackbox", "armed": bool, "kills": n, "spools":
  [{"dir", "name", "pid", "alive", "records", "errors": [...]}]}`` —
  the flight-recorder census (ISSUE 20): after teardown every child's
  spool is hash-chain-verified; ``kills`` counts the process-fatal
  events the conductor fired.
- ``{"kind": "pipeline", "event": "committed|regroup|placed|
  stale-refused|replay", ...}`` — the pipelined trainer's ledger
  (ISSUE 17): ``committed`` carries ``step``/``epoch``/``fingerprint``,
  ``regroup`` carries ``epoch``/``cause``/``lost_stage``, ``placed``
  carries ``stage``/``epoch`` (a stage taking up an assignment),
  ``stale-refused`` a zombie confirm bounced by the epoch fence, and
  ``replay`` the unpartitioned re-run's ``step``/``fingerprint``.
- ``{"kind": "flywheel", "event": "acked|consumed|cursor-committed|
  cursor-restored|settle-read|gate", ...}`` — the feedback-ledger loop
  (ISSUE 19): ``acked`` carries the record ``hashes`` a replica's
  append durably acked, ``consumed`` the ``hashes`` + the ``step`` the
  trainer folded them into, ``cursor-committed``/``cursor-restored``
  the cursor's durable ``step``, ``settle-read`` every hash the settle
  oracle read back from the ledger, and ``gate`` a promotion verdict
  (``verdict`` + ``bad: true`` when the delta was the deliberately-bad
  break-glass one).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..exceptions import EXCEPTION_REGISTRY, KubetorchError


@dataclass
class Violation:
    """One invariant breach, pointing back at the implicated records."""

    invariant: str
    detail: str
    records: List[int] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"invariant": self.invariant, "detail": self.detail,
                "records": list(self.records)}


class History:
    """Append-only op/result history. Thread-safe appends (the conductor's
    main loop and the trainer-ledger importer may interleave); optionally
    mirrored to a JSONL file as it grows, so a soak that wedges still
    leaves its history on disk for the post-mortem."""

    def __init__(self, path: Optional[str] = None):
        self._records: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._path = path

    def record(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec = {"kind": kind, **fields}
        with self._lock:
            rec["index"] = len(self._records)
            self._records.append(rec)
            if self._path:
                with open(self._path, "a") as f:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def classify_error(exc: BaseException) -> Tuple[str, bool]:
    """``(type name, typed?)`` for an exception a client op surfaced.

    "Typed" means the error rode the exception taxonomy clients are
    supposed to see — a :class:`KubetorchError` subclass (equivalently,
    a registered rehydratable type). A raw ``ConnectionError`` /
    ``KeyError`` / ``JSONDecodeError`` reaching the history is exactly
    the leak the typed-errors invariant exists to catch."""
    name = type(exc).__name__
    typed = isinstance(exc, KubetorchError) or name in EXCEPTION_REGISTRY
    return name, typed


# ---------------------------------------------------------------------------
# Invariant checkers — pure functions over the record list
# ---------------------------------------------------------------------------


def check_durability(records: List[Dict]) -> List[Violation]:
    """No acknowledged quorum write may ever become unreadable: every
    ``op=put`` the client saw ``ok`` (and marked ``acked``) must have a
    settle-phase ``verify`` read that succeeded AND matched the written
    content. A missing verify counts too — silently skipping the read-back
    must not pass the gate. An acknowledged ``rm`` releases the obligation
    (the client asked for the data to go away); a later re-put restores
    it."""
    out: List[Violation] = []
    acked: Dict[str, int] = {}
    verified: Dict[str, Dict] = {}
    for r in records:
        if r.get("kind") == "op" and r.get("op") == "put" and r.get("ok") \
                and r.get("acked"):
            acked[r["key"]] = r["index"]
        elif r.get("kind") == "op" and r.get("op") == "rm" and r.get("ok"):
            acked.pop(r.get("key"), None)
        elif r.get("kind") == "verify":
            verified[r["key"]] = r
    for key, idx in sorted(acked.items()):
        v = verified.get(key)
        if v is None:
            out.append(Violation(
                "durability", f"acked write {key!r} was never verified "
                "at settle", [idx]))
        elif not v.get("ok") or not v.get("match", True):
            why = "unreadable" if not v.get("ok") else "content mismatch"
            out.append(Violation(
                "durability", f"acked write {key!r} is {why} after the "
                f"dust settled ({v.get('error', '')})".rstrip(" ()"),
                [idx, v["index"]]))
    return out


def check_commits(records: List[Dict]) -> List[Violation]:
    """No lost committed checkpoint step: the trainer's restored step must
    never fall below the highest step it previously committed (commit-
    marker monotonicity), and any step committed twice (across deaths)
    or restored must reproduce the SAME ``tree_fingerprint`` — the
    deterministic recurrence makes bit-drift a real corruption signal."""
    out: List[Violation] = []
    fingerprints: Dict[int, Tuple[str, int]] = {}
    high = 0
    high_idx: Optional[int] = None
    for r in records:
        if r.get("kind") != "trainer":
            continue
        step = r.get("step")
        fp = r.get("fingerprint")
        if r.get("event") == "committed" and step is not None:
            seen = fingerprints.get(step)
            if seen is not None and fp is not None and seen[0] != fp:
                out.append(Violation(
                    "commit-fingerprint",
                    f"step {step} re-committed with a different "
                    f"fingerprint ({seen[0][:12]}… vs {fp[:12]}…)",
                    [seen[1], r["index"]]))
            if fp is not None:
                fingerprints.setdefault(step, (fp, r["index"]))
            if step > high:
                high, high_idx = step, r["index"]
        elif r.get("event") == "restored":
            if step is None:
                if high:
                    out.append(Violation(
                        "commit-monotonic",
                        f"trainer restored from scratch although step "
                        f"{high} was committed",
                        [i for i in (high_idx, r["index"]) if i is not None]))
                continue
            if step < high:
                out.append(Violation(
                    "commit-monotonic",
                    f"trainer restored step {step} but step {high} was "
                    f"already committed — committed work was lost",
                    [i for i in (high_idx, r["index"]) if i is not None]))
            seen = fingerprints.get(step)
            if seen is not None and fp is not None and seen[0] != fp:
                out.append(Violation(
                    "commit-fingerprint",
                    f"restored step {step} does not reproduce the "
                    f"committed fingerprint ({seen[0][:12]}… vs {fp[:12]}…)",
                    [seen[1], r["index"]]))
    return out


def check_lease_fencing(records: List[Dict]) -> List[Violation]:
    """At most one live placement per workload, and every placement must
    carry the CURRENT lease epoch: a ``placement`` start/confirm stamped
    with an epoch older than the newest grant for that workload means a
    fenced-off region kept running — the split-brain the epoch fence
    exists to prevent."""
    out: List[Violation] = []
    granted: Dict[str, Tuple[int, int]] = {}
    live: Dict[str, Dict] = {}
    for r in records:
        if r.get("kind") == "lease" and r.get("event") == "grant":
            granted[r["workload"]] = (r["epoch"], r["index"])
        elif r.get("kind") == "placement":
            w = r.get("workload")
            if r.get("event") in ("start", "confirmed"):
                cur = granted.get(w)
                if cur is not None and r.get("epoch", 0) < cur[0]:
                    out.append(Violation(
                        "lease-fencing",
                        f"workload {w!r} placement in {r.get('region')!r} "
                        f"ran at stale epoch {r.get('epoch')} (current "
                        f"{cur[0]}) — fenced region kept the placement",
                        [cur[1], r["index"]]))
                prev = live.get(w)
                if prev is not None and prev.get("region") != r.get("region"):
                    out.append(Violation(
                        "lease-fencing",
                        f"workload {w!r} live in BOTH "
                        f"{prev.get('region')!r} (epoch {prev.get('epoch')})"
                        f" and {r.get('region')!r} (epoch {r.get('epoch')})",
                        [prev["index"], r["index"]]))
                if r.get("event") == "start":
                    live[w] = r
            elif r.get("event") == "stop":
                prev = live.get(w)
                if prev is not None and prev.get("region") == r.get("region"):
                    live.pop(w, None)
    return out


def check_typed_errors(records: List[Dict]) -> List[Violation]:
    """Clients see typed errors ONLY: any failed op whose exception was
    not a :class:`KubetorchError` (``typed: false`` in the record) is a
    contract breach — a raw ``ConnectionError``/``KeyError`` escaped the
    resilience layer into user code."""
    out: List[Violation] = []
    for r in records:
        if r.get("kind") == "op" and r.get("ok") is False \
                and not r.get("typed", False):
            out.append(Violation(
                "typed-errors",
                f"raw {r.get('error', '?')} escaped to the client on "
                f"{r.get('op')} {r.get('key', r.get('target', ''))!r}",
                [r["index"]]))
    return out


def check_ring_converged(records: List[Dict]) -> List[Violation]:
    """The ring must re-converge to full replication after the faults: the
    final ``ring-status`` record (post-restart, post-scrub) must report
    zero under-replicated objects and zero dead members. No record at all
    counts as a violation when store ops ran — the settle phase skipped
    its own verdict."""
    out: List[Violation] = []
    last = None
    store_ops = False
    for r in records:
        if r.get("kind") == "ring-status":
            last = r
        elif r.get("kind") == "op" and r.get("op") in ("put", "get", "rm"):
            store_ops = True
    if last is None:
        if store_ops:
            out.append(Violation(
                "ring-convergence",
                "store ops ran but no final ring-status was recorded", []))
        return out
    if last.get("under_replicated", 0) or last.get("nodes_down", 0):
        out.append(Violation(
            "ring-convergence",
            f"ring did not re-converge: under_replicated="
            f"{last.get('under_replicated')} nodes_down="
            f"{last.get('nodes_down')}", [last["index"]]))
    return out


def check_no_leaks(records: List[Dict]) -> List[Violation]:
    """Zero leaked /dev/shm segments and zero orphan ``.tmp`` files after
    teardown: the leak-scan record's lists must be empty. Restart paths
    that forget their cleanup show up here, not in a full disk weeks
    later."""
    out: List[Violation] = []
    for r in records:
        if r.get("kind") != "leak-scan":
            continue
        if r.get("shm"):
            out.append(Violation(
                "no-leaks", f"leaked /dev/shm segments: {r['shm']}",
                [r["index"]]))
        if r.get("tmp"):
            out.append(Violation(
                "no-leaks", f"orphan .tmp files: {r['tmp']}",
                [r["index"]]))
    return out


def check_blackbox(records: List[Dict]) -> List[Violation]:
    """Crash forensics must survive the crash (ISSUE 20): when the run
    armed the flight recorder, every child spool found after teardown
    must verify — hash chain intact per segment, sequence numbers
    contiguous — or the black box lied about the death it recorded. And
    if the conductor SIGKILLed recorder-bearing processes, at least one
    spool must EXIST: kills with no black boxes means the recorder never
    committed a record before dying, i.e. the loss window is unbounded."""
    out: List[Violation] = []
    for r in records:
        if r.get("kind") != "blackbox":
            continue
        if not r.get("armed"):
            continue
        spools = r.get("spools") or []
        for sp in spools:
            if sp.get("errors"):
                out.append(Violation(
                    "blackbox",
                    f"spool {sp.get('dir')} failed verification: "
                    f"{'; '.join(sp['errors'])}", [r["index"]]))
        if r.get("kills", 0) > 0 and not spools:
            out.append(Violation(
                "blackbox",
                f"{r['kills']} process kill(s) fired but no flight-"
                f"recorder spools survived teardown", [r["index"]]))
    return out


def check_pipeline_progress(records: List[Dict]) -> List[Violation]:
    """Re-grouped forward progress, epoch-fenced placement, and replay
    bit-identity for the pipelined trainer (ISSUE 17):

    - every ``regroup`` must be followed by a ``committed`` step strictly
      greater than the highest step committed before it — the pipe
      re-grouped and MOVED, it did not stall;
    - every ``placed`` record must carry the membership epoch current at
      that point in the history (the latest ``regroup``'s epoch, 0 before
      any) — a placement at an older epoch means a zombie stage took up
      an assignment the fence should have refused;
    - when an unpartitioned ``replay`` ran, every committed step it
      covers must bit-match its fingerprint, and the highest committed
      step must be covered — partitioning the layers across a re-group
      changed nothing about the math.
    """
    out: List[Violation] = []
    high = 0
    epoch = 0
    pending: Optional[Dict] = None       # last regroup awaiting progress
    committed: Dict[int, Tuple[str, int]] = {}
    replays: Dict[int, Tuple[str, int]] = {}
    for r in records:
        if r.get("kind") != "pipeline":
            continue
        event = r.get("event")
        if event == "committed" and r.get("step") is not None:
            step = r["step"]
            if r.get("fingerprint"):
                committed.setdefault(step, (r["fingerprint"], r["index"]))
            if pending is not None and step > high:
                pending = None
            high = max(high, step)
        elif event == "regroup":
            if pending is not None:
                out.append(Violation(
                    "pipeline-progress",
                    f"regroup to epoch {pending.get('epoch')} was never "
                    f"followed by a committed step > {high} before the "
                    f"next regroup — the pipe stalled",
                    [pending["index"], r["index"]]))
            pending = r
            epoch = max(epoch, int(r.get("epoch", epoch)))
        elif event == "placed":
            if int(r.get("epoch", 0)) < epoch:
                out.append(Violation(
                    "pipeline-progress",
                    f"stage {r.get('stage')} placed at stale epoch "
                    f"{r.get('epoch')} (current {epoch}) — the membership "
                    f"fence should have refused it", [r["index"]]))
        elif event == "replay" and r.get("step") is not None \
                and r.get("fingerprint"):
            replays.setdefault(r["step"], (r["fingerprint"], r["index"]))
    if pending is not None:
        out.append(Violation(
            "pipeline-progress",
            f"regroup to epoch {pending.get('epoch')} was never followed "
            f"by a committed step > {high} — the pipe stalled",
            [pending["index"]]))
    if replays:
        for step, (fp, idx) in sorted(committed.items()):
            seen = replays.get(step)
            if seen is not None and seen[0] != fp:
                out.append(Violation(
                    "pipeline-progress",
                    f"committed step {step} does not bit-match the "
                    f"unpartitioned replay ({fp[:12]}… vs {seen[0][:12]}…)",
                    [idx, seen[1]]))
        if high and high in committed and high not in replays:
            out.append(Violation(
                "pipeline-progress",
                f"replay ran but never covered the highest committed "
                f"step {high}", [committed[high][1]]))
    return out


def check_flywheel_ledger(records: List[Dict]) -> List[Violation]:
    """Loss-proof feedback flow (ISSUE 19), in four clauses:

    - **zero acked-record loss**: every hash an ``acked`` record carries
      must appear in a ``settle-read`` record — the ledger still serves
      it after the dust settles (checked only when a settle-read ran);
    - **consumed exactly once**: a hash folded into a *committed* step
      (a ``consumed`` record whose ``step`` later shows up in a
      ``cursor-committed`` record) must never be folded into a second
      committed step — the no-double-train half of at-least-once; and
      every acked hash must reach SOME committed step by settle (the
      conductor drains the ledger before checking);
    - **cursor monotonicity**: a ``cursor-restored`` step may never fall
      below the highest ``cursor-committed`` step before it — restoring
      past a committed checkpoint would re-train folded records;
    - **bad deltas never promote**: a ``gate`` record with ``bad: true``
      must carry verdict ``rolled_back`` or ``gate_rejected``.
    """
    out: List[Violation] = []
    acked: Dict[str, int] = {}
    consumed: Dict[int, List[Tuple[str, int]]] = {}   # step → [(hash, idx)]
    committed_steps: Dict[int, int] = {}              # step → record index
    settle_hashes: Optional[set] = None
    settle_idx: Optional[int] = None
    high_committed = 0
    high_idx: Optional[int] = None
    for r in records:
        if r.get("kind") != "flywheel":
            continue
        event = r.get("event")
        if event == "acked":
            for h in r.get("hashes", []):
                acked.setdefault(h, r["index"])
        elif event == "consumed" and r.get("step") is not None:
            consumed.setdefault(int(r["step"]), []).extend(
                (h, r["index"]) for h in r.get("hashes", []))
        elif event == "cursor-committed" and r.get("step") is not None:
            step = int(r["step"])
            committed_steps.setdefault(step, r["index"])
            if step > high_committed:
                high_committed, high_idx = step, r["index"]
        elif event == "cursor-restored":
            step = r.get("step")
            if step is not None and int(step) < high_committed:
                out.append(Violation(
                    "flywheel-ledger",
                    f"cursor restored step {step} but step "
                    f"{high_committed} was already committed — folded "
                    f"records would re-train",
                    [i for i in (high_idx, r["index"]) if i is not None]))
            if step is not None:
                # a restore only succeeds from a COMMITTED checkpoint, so
                # it is commit evidence too — covers a death in the tiny
                # window between the store commit and the ledger line
                committed_steps.setdefault(int(step), r["index"])
                if int(step) > high_committed:
                    high_committed, high_idx = int(step), r["index"]
        elif event == "settle-read":
            if settle_hashes is None:
                settle_hashes = set()
                settle_idx = r["index"]
            settle_hashes.update(r.get("hashes", []))
        elif event == "gate":
            if r.get("bad") and r.get("verdict") not in ("rolled_back",
                                                         "gate_rejected"):
                out.append(Violation(
                    "flywheel-ledger",
                    f"deliberately-bad delta ended "
                    f"{r.get('verdict')!r} — it must be gate_rejected "
                    f"or rolled_back, never promoted", [r["index"]]))
    if settle_hashes is not None:
        for h, idx in sorted(acked.items()):
            if h not in settle_hashes:
                out.append(Violation(
                    "flywheel-ledger",
                    f"acked feedback record {h[:12]}… is gone from the "
                    f"ledger at settle — an acked append was lost",
                    [i for i in (idx, settle_idx) if i is not None]))
    folded: Dict[str, Tuple[int, int]] = {}           # hash → (step, idx)
    for step in sorted(consumed):
        if step not in committed_steps:
            continue                  # died un-committed: re-polls, fine
        for h, idx in consumed[step]:
            prev = folded.get(h)
            if prev is not None and prev[0] != step:
                out.append(Violation(
                    "flywheel-ledger",
                    f"record {h[:12]}… was folded into committed step "
                    f"{prev[0]} AND committed step {step} — "
                    f"double-trained", [prev[1], idx]))
            folded.setdefault(h, (step, idx))
    if committed_steps:
        for h, idx in sorted(acked.items()):
            if h not in folded:
                out.append(Violation(
                    "flywheel-ledger",
                    f"acked record {h[:12]}… never reached a committed "
                    f"training step by settle", [idx]))
    return out


INVARIANTS = {
    "durability": check_durability,
    "commits": check_commits,
    "lease-fencing": check_lease_fencing,
    "typed-errors": check_typed_errors,
    "ring-convergence": check_ring_converged,
    "no-leaks": check_no_leaks,
    "blackbox": check_blackbox,
    "pipeline-progress": check_pipeline_progress,
    "flywheel-ledger": check_flywheel_ledger,
}


def check_all(records: List[Dict]) -> List[Violation]:
    """Run every invariant checker over the history; the soak's verdict."""
    out: List[Violation] = []
    for checker in INVARIANTS.values():
        out.extend(checker(records))
    return out
